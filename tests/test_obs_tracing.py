"""Pass-level tracing + SLO layer (ISSUE 7).

Covers the obs/ substrate end to end: tracer mechanics (nesting, thread
isolation, ring bound, clock injection, disabled no-op), Chrome trace-event
export validity, real-solve instrumentation (>=95% wall-clock coverage,
delta passes visibly skipping the cold-encode spans, trace_id stamped onto
flight-recorder records), span-derived phase histograms, the induced SLO
breach (exactly one metric increment / warning event / flight-recorder
dump), pod time-to-schedule, the clock-injectable Registry.measure, the
metric cardinality cap, and the dump CLI."""

import json
import threading
import time

import pytest

import bench
from karpenter_tpu.metrics.registry import (REGISTRY, Registry,
                                            SERIES_DROPPED, SLO_BREACHES,
                                            SOLVER_PHASE_DURATION,
                                            PODS_TIME_TO_SCHEDULE)
from karpenter_tpu.obs.slo import SLOWatcher, parse_budgets
from karpenter_tpu.obs.tracer import (TRACER, Tracer, chrome_trace,
                                      dumps_chrome, phase_millis)
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods


class _StepClock:
    """Manual monotonic clock for duration injection."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def step(self, s: float) -> None:
        self.t += s


class TestTracer:
    def test_nesting_parent_links_and_attrs(self):
        tr = Tracer(capacity=4)
        with tr.span("root", a=1) as r:
            with tr.span("child") as c1:
                with tr.span("grandchild"):
                    pass
            with tr.span("child") as c2:
                c2.set(late=True)
        t = tr.last()
        assert [s.name for s in t.spans] == ["root", "child", "grandchild",
                                             "child"]
        assert [s.parent for s in t.spans] == [-1, 0, 1, 0]
        assert t.root is r and t.root.attrs == {"a": 1}
        assert t.spans[3].attrs == {"late": True}
        assert t.trace_id.startswith("t")
        assert c1.duration >= 0

    def test_root_completes_trace_and_ring_is_bounded(self):
        tr = Tracer(capacity=2)
        for i in range(5):
            with tr.span("pass", i=i):
                pass
        assert len(tr.traces()) == 2
        assert tr.traces()[-1].root.attrs["i"] == 4
        ids = [t.trace_id for t in tr.traces()]
        assert len(set(ids)) == 2
        assert tr.find(ids[0]) is tr.traces()[0]
        assert tr.find("t999999") is None

    def test_disabled_is_noop(self):
        tr = Tracer(enabled=False)
        sp = tr.span("x")
        with sp as inner:
            assert inner is sp  # the shared no-op object
            inner.set(a=1)
            assert tr.current_trace_id() == ""
        assert tr.traces() == []

    def test_clock_injection_exact_durations(self):
        clk = _StepClock()
        tr = Tracer(now=clk.now)
        with tr.span("outer"):
            clk.step(1.0)
            with tr.span("inner"):
                clk.step(2.5)
            clk.step(0.5)
        t = tr.last()
        assert t.root.duration == pytest.approx(4.0)
        assert t.spans[1].duration == pytest.approx(2.5)
        assert phase_millis(t) == {"inner": 2500.0}
        # set_clock returns the previous clock for restoration
        prev = tr.set_clock(time.perf_counter)
        assert prev == clk.now

    def test_threads_trace_independently(self):
        tr = Tracer(capacity=16)
        done = threading.Barrier(3)

        def work(name):
            with tr.span(name):
                done.wait(timeout=5)  # both threads mid-span concurrently
                with tr.span(name + ".child"):
                    pass

        threads = [threading.Thread(target=work, args=(f"w{i}",))
                   for i in range(2)]
        for t in threads:
            t.start()
        done.wait(timeout=5)
        for t in threads:
            t.join()
        traces = tr.traces()
        assert len(traces) == 2
        roots = sorted(t.name for t in traces)
        assert roots == ["w0", "w1"]
        for t in traces:
            assert [s.name for s in t.spans] == [t.name, t.name + ".child"]

    def test_mispaired_exit_never_rings_an_empty_trace(self):
        tr = Tracer()
        a = tr.span("a")
        b = tr.span("b")
        a.__enter__()
        b.__enter__()
        a.__exit__(None, None, None)  # parent closed before its child
        b.__exit__(None, None, None)  # the late exit must not double-ring
        traces = tr.traces()
        assert len(traces) == 1 and traces[0].name == "a"
        with tr.span("c"):  # the thread's tracing is not wedged
            pass
        assert tr.last().name == "c" and len(tr.traces()) == 2

    def test_drop_current_discards_trace(self):
        """Review fix: idle controller passes (disruption polls with zero
        candidates) must not ring — they would evict the interesting
        traces within minutes."""
        tr = Tracer()
        before = SOLVER_PHASE_DURATION.count(
            {"phase": "idle.pass", "encode_kind": ""})
        with tr.span("idle.pass"):
            tr.drop_current()
        assert tr.traces() == []
        # no derived metrics for a dropped trace either
        assert SOLVER_PHASE_DURATION.count(
            {"phase": "idle.pass", "encode_kind": ""}) == before
        with tr.span("busy.pass"):  # the next trace rings normally
            pass
        assert tr.last().name == "busy.pass"

    def test_idle_disruption_passes_not_ringed(self):
        from karpenter_tpu.operator.operator import Operator
        from test_operator import settle
        op = Operator(clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        TRACER.clear()
        settle(op)  # no pods: every disruption poll has zero candidates
        assert all(t.name != "disruption.pass" for t in TRACER.traces())

    def test_current_trace_id_and_annotate(self):
        tr = Tracer()
        assert tr.current_trace_id() == ""
        with tr.span("root"):
            tid = tr.current_trace_id()
            assert tid
            with tr.span("inner"):
                assert tr.current_trace_id() == tid
                tr.annotate(encode_kind="delta")
        assert tr.current_trace_id() == ""
        assert tr.last().root.attrs["encode_kind"] == "delta"


class TestChromeExport:
    def test_schema_valid(self):
        clk = _StepClock()
        tr = Tracer(now=clk.now)
        with tr.span("solve", pods=3):
            clk.step(0.25)
            with tr.span("pack"):
                clk.step(0.5)
        doc = json.loads(dumps_chrome(tr.traces()))
        events = doc["traceEvents"]
        assert doc["displayTimeUnit"] == "ms"
        assert len(events) == 2
        for e in events:
            assert set(e) == {"name", "cat", "ph", "ts", "dur", "pid",
                              "tid", "args"}
            assert e["ph"] == "X" and e["cat"] == "karpenter"
            assert isinstance(e["ts"], float)
            assert e["args"]["trace_id"] == tr.last().trace_id
        root = next(e for e in events if e["name"] == "solve")
        assert root["dur"] == pytest.approx(0.75e6)
        assert root["args"]["pods"] == 3


@pytest.fixture(scope="module")
def traced_solves():
    """Two instrumented solves of the same small headline mix sharing one
    ProblemState: a cold pass and a delta pass, plus the measured wall
    clock and flight-recorder capture of the cold one."""
    from karpenter_tpu.flightrec import FlightRecorder
    from karpenter_tpu.provisioning.problem_state import ProblemState

    saved = (bench.N_PODS, bench.N_DEPLOYS)
    bench.N_PODS, bench.N_DEPLOYS = 600, 12
    try:
        pods = bench._pods()
    finally:
        bench.N_PODS, bench.N_DEPLOYS = saved
    bench._scheduler(0).solve(pods)  # warm the jit cache
    ps = ProblemState()
    rec = FlightRecorder(capacity=4)

    ts = bench._scheduler(0)
    ts.problem_state = ps
    ts.flight_recorder = rec
    t0 = time.perf_counter()
    ts.solve(pods)
    wall = time.perf_counter() - t0
    assert ts.fallback_reason == "", ts.fallback_reason
    cold = TRACER.last()

    ts2 = bench._scheduler(0)
    ts2.problem_state = ps
    ts2.solve(pods)
    assert ts2.fallback_reason == "", ts2.fallback_reason
    delta = TRACER.last()
    return pods, cold, delta, wall, rec, ts


class TestSolveTracing:
    def test_span_tree_covers_wall_clock(self, traced_solves):
        _, cold, _, wall, _, _ = traced_solves
        assert cold.name == "solve"
        # acceptance: the dumped trace accounts for >=95% of the measured
        # wall clock (10 ms absolute grace: capture/GIL jitter at 600 pods)
        assert cold.duration >= 0.95 * wall or wall - cold.duration < 0.010, \
            f"trace covers {cold.duration:.4f}s of {wall:.4f}s"

    def test_expected_stage_spans_present(self, traced_solves):
        _, cold, _, _, _, _ = traced_solves
        names = {s.name for s in cold.spans}
        for expected in ("build_problem", "encode.groups", "precompute",
                         "device.upload", "device.fetch", "topo.counts",
                         "pack", "materialize"):
            assert expected in names, names
        # span count stays per-STAGE, never per pod/group — the overhead
        # contract the <=5% bench gate relies on
        assert len(cold.spans) < 40

    def test_delta_pass_skips_cold_encode_spans(self, traced_solves):
        _, cold, delta, _, _, _ = traced_solves
        assert cold.root.attrs["encode_kind"] == "cold"
        assert delta.root.attrs["encode_kind"] == "delta"
        # the cold catalog encode is visible on the cold pass and GONE on
        # the delta pass (the whole point of a delta trace); NB the cold
        # solve may still hit the process-wide catalog cache, in which case
        # both skip it — assert the delta side only, plus the kind attr on
        # build_problem
        assert "encode.catalog" not in {s.name for s in delta.spans}
        bp = next(s for s in delta.spans if s.name == "build_problem")
        assert bp.attrs["encode_kind"] == "delta"

    def test_trace_valid_chrome_json(self, traced_solves):
        _, cold, _, _, _, _ = traced_solves
        doc = json.loads(dumps_chrome([cold]))
        assert all(e["ph"] == "X" and e["args"]["trace_id"] == cold.trace_id
                   for e in doc["traceEvents"])
        assert {e["name"] for e in doc["traceEvents"]} == \
            {s.name for s in cold.spans}

    def test_phase_histogram_derived_from_spans(self, traced_solves):
        """Metrics and traces can never disagree: every span of the trace
        observed into the phase histogram under its trace's encode_kind."""
        _, cold, delta, _, _, _ = traced_solves
        for trace, kind in ((cold, "cold"), (delta, "delta")):
            by_name: dict = {}
            for s in trace.spans:
                by_name[s.name] = by_name.get(s.name, 0) + 1
            for name, n in by_name.items():
                labels = {"phase": name, "encode_kind": kind}
                assert SOLVER_PHASE_DURATION.count(labels) >= n, \
                    (name, kind)

    def test_trace_id_stamped_on_flightrec_record(self, traced_solves):
        _, cold, _, _, rec, ts = traced_solves
        r = rec.records()[0]
        assert r.meta["trace_id"] == cold.trace_id == ts.last_trace_id

    def test_phase_millis_is_exclusive(self, traced_solves):
        _, cold, _, _, _, _ = traced_solves
        phases = phase_millis(cold)
        assert "solve" not in phases  # root excluded
        # exclusive times sum to ~the root duration (no double counting)
        assert sum(phases.values()) <= cold.duration * 1e3 * 1.01


class TestSLOWatcher:
    def test_parse_budgets(self):
        assert parse_budgets("a=1.5, b=2") == {"a": 1.5, "b": 2.0}
        assert parse_budgets("") == {}
        with pytest.raises(ValueError):
            parse_budgets("nobudget")
        with pytest.raises(ValueError):
            parse_budgets("a=notanumber")
        # review fix: zero/negative = every pass breaches, nan = a budget
        # that can never fire — both are boot failures, not silent states
        for bad in ("a=0", "a=-1", "a=nan", "a=inf"):
            with pytest.raises(ValueError):
                parse_budgets(bad)

    def test_dump_files_bounded_and_restart_unique(self, tmp_path):
        """Review fix: a budget below the steady-state pass time must not
        exhaust the disk — dump files are FIFO-capped — and names carry a
        per-process tag so a restart can't overwrite a prior incident."""
        class FakeRec:
            def dump_matching(self, path, trace_id):
                with open(path, "w") as f:
                    f.write(trace_id + "\n")
                return 1

        clk = _StepClock()
        tr = Tracer(now=clk.now)
        watcher = SLOWatcher({"pass": 0.5}, flightrec=FakeRec(),
                             dump_dir=str(tmp_path))
        watcher.MAX_DUMP_FILES = 2
        tr.watcher = watcher
        for _ in range(5):
            with tr.span("pass"):
                clk.step(1.0)  # every pass breaches
        files = sorted(tmp_path.iterdir())
        assert len(files) == 2  # oldest three deleted
        assert all(f.name.startswith(f"slo-breach-{watcher._file_tag}-")
                   for f in files)
        # the kept files are the two NEWEST breaches
        kept_ids = {f.read_text().strip() for f in files}
        assert kept_ids == {b.trace_id for b in list(watcher.breaches)[-2:]}

    def test_induced_breach_exactly_once(self, traced_solves, tmp_path):
        """Acceptance: a fake-clock inflated pass produces exactly one
        breach metric increment, one warning event, and one flight-recorder
        dump whose trace_id matches the breaching pass."""
        from karpenter_tpu.events.recorder import Recorder
        from karpenter_tpu.flightrec import FlightRecorder

        pods, *_ = traced_solves
        clk = _StepClock()
        events_clock = FakeClock()
        recorder = Recorder(events_clock)
        rec = FlightRecorder(capacity=8)
        watcher = SLOWatcher({"provisioner.pass": 2.0}, recorder=recorder,
                             flightrec=rec, clock=events_clock,
                             dump_dir=str(tmp_path))
        before = SLO_BREACHES.value({"slo": "provisioner.pass"})
        prev_clock = TRACER.set_clock(clk.now)
        prev_watcher, TRACER.watcher = TRACER.watcher, watcher
        try:
            with TRACER.span("provisioner.pass"):
                ts = bench._scheduler(0)
                ts.flight_recorder = rec
                ts.solve(pods)
                clk.step(10.0)  # inflate the pass past its 2s budget
            trace = TRACER.last()
        finally:
            TRACER.set_clock(prev_clock)
            TRACER.watcher = prev_watcher
        assert trace.name == "provisioner.pass"
        assert SLO_BREACHES.value({"slo": "provisioner.pass"}) == before + 1
        breaches = [e for e in recorder.events if e.reason == "SLOBreached"]
        assert len(breaches) == 1
        assert trace.trace_id in breaches[0].message
        import pathlib
        dump = pathlib.Path(watcher.breaches[0].dump_path)
        assert dump.parent == tmp_path and dump.exists()
        assert trace.trace_id in dump.name
        dumped = [json.loads(l) for l in dump.read_text().splitlines()]
        assert len(dumped) == 1
        assert dumped[0]["meta"]["trace_id"] == trace.trace_id
        # re-observation (e.g. a replayed completion) is a no-op
        watcher.observe(trace)
        assert SLO_BREACHES.value({"slo": "provisioner.pass"}) == before + 1
        assert len([e for e in recorder.events
                    if e.reason == "SLOBreached"]) == 1
        assert len(watcher.breaches) == 1
        snap = watcher.snapshot()
        assert snap["breaches"][0]["trace_id"] == trace.trace_id
        assert snap["budgets"]["provisioner.pass"]["observed"] == 1

    def test_multiple_budgets_each_counted_one_dump(self, tmp_path):
        """Review fix: a pass breaching TWO independent budgets increments
        both series (alerting on either never misses), with ONE dump."""
        clk = _StepClock()
        tr = Tracer(now=clk.now)
        watcher = SLOWatcher({"pass": 2.0, "solve": 1.0},
                             dump_dir=str(tmp_path))
        tr.watcher = watcher
        before_pass = SLO_BREACHES.value({"slo": "pass"})
        before_solve = SLO_BREACHES.value({"slo": "solve"})
        with tr.span("pass"):
            clk.step(3.0)
            with tr.span("solve"):
                clk.step(1.5)  # solve 1.5s > 1.0s; pass 4.5s > 2.0s
        assert SLO_BREACHES.value({"slo": "pass"}) == before_pass + 1
        assert SLO_BREACHES.value({"slo": "solve"}) == before_solve + 1
        assert len(watcher.breaches) == 2
        assert {b.slo for b in watcher.breaches} == {"pass", "solve"}

    def test_slo_budgets_require_tracing_enabled(self):
        """Review fix: budgets that can never fire (tracer off) are a boot
        failure, not a silent no-op."""
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        with pytest.raises(ValueError, match="trace-ring"):
            Operator(options=Options(metrics_port=0, health_probe_port=0,
                                     trace_ring=0,
                                     slo_budgets="provisioner.pass=2.0"),
                     clock=FakeClock())
        # the failed boot left the process-wide tracer untouched
        assert TRACER.enabled

    def test_dump_matching_failure_leaves_no_partial_file(self, tmp_path,
                                                          monkeypatch):
        """Review fix: a mid-encode failure must not leave a truncated
        breach dump on disk (all lines encode before the file opens)."""
        import karpenter_tpu.flightrec.record as rec_codec
        from karpenter_tpu.flightrec import FlightRecorder
        from karpenter_tpu.flightrec.recorder import FlightRecord
        rec = FlightRecorder(capacity=4)
        for i in range(2):
            rec._append(FlightRecord("provisioning", 0.0, 0.1,
                                     {"trace_id": "tX"}, {"d": i}))
        real = rec_codec.dumps_record
        calls = {"n": 0}

        def flaky(d):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("boom")
            return real(d)

        monkeypatch.setattr(rec_codec, "dumps_record", flaky)
        path = tmp_path / "dump.jsonl"
        with pytest.raises(RuntimeError):
            rec.dump_matching(str(path), "tX")
        assert not path.exists()

    def test_within_budget_no_breach(self):
        clk = _StepClock()
        tr = Tracer(now=clk.now)
        watcher = SLOWatcher({"pass": 5.0})
        tr.watcher = watcher
        with tr.span("pass"):
            clk.step(1.0)
        assert not watcher.breaches
        assert watcher.snapshot()["budgets"]["pass"]["observed"] == 1
        assert watcher.snapshot()["budgets"]["pass"]["p99"] == \
            pytest.approx(1.0)

    def test_unwatched_spans_ignored(self):
        clk = _StepClock()
        tr = Tracer(now=clk.now)
        watcher = SLOWatcher({"other": 0.1})
        tr.watcher = watcher
        with tr.span("pass"):
            clk.step(10.0)
        assert not watcher.breaches


class TestTimeToSchedule:
    def test_claim_creation_closes_the_window(self):
        """first-seen-pending -> claim-created rides the fake clock into
        karpenter_pods_time_to_schedule_seconds."""
        from karpenter_tpu.operator.operator import Operator
        from test_operator import settle
        op = Operator(clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        before_count = PODS_TIME_TO_SCHEDULE.count()
        before_sum = PODS_TIME_TO_SCHEDULE.sum()
        for p in make_pods(3, cpu="500m"):
            op.store.create(p)
        settle(op)
        assert PODS_TIME_TO_SCHEDULE.count() == before_count + 3
        # the batcher needs >= 1s of idle before solving, so each pod waited
        # at least that long on the fake clock; settle steps 1.1s/round
        per_pod = (PODS_TIME_TO_SCHEDULE.sum() - before_sum) / 3
        assert 1.0 <= per_pod <= 10.0
        # the window closed: the tracking dict does not grow without bound
        assert not op.provisioner._pending_first_seen

    def test_failed_claim_recycle_resumes_original_window(self):
        """Review fix: an ICE-killed claim recycles its pod back to
        pending; the retry must observe the CUMULATIVE wait from the
        original first-seen — a capacity drought must show up in p99, not
        be averaged away as a stream of short healthy samples."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.cloudprovider.types import \
            InsufficientCapacityError
        from karpenter_tpu.operator.operator import Operator
        from test_operator import settle
        provider = FakeCloudProvider()
        op = Operator(cloud_provider=provider, clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        provider.next_create_err = InsufficientCapacityError("dry")
        before = PODS_TIME_TO_SCHEDULE.count()
        sum_before = PODS_TIME_TO_SCHEDULE.sum()
        settle(op, rounds=10)  # claim 1 ICEs + is deleted; claim 2 lands
        assert PODS_TIME_TO_SCHEDULE.count() == before + 2
        # the second sample spans BOTH attempts (resumed window): 1.1s
        # first window + 3.3s cumulative = 4.4 on the fake clock; fresh
        # per-retry windows top out at ~3.3 (1.1 + 2.2)
        total = PODS_TIME_TO_SCHEDULE.sum() - sum_before
        assert total >= 4.0

    def test_deleting_node_ride_alongs_not_reobserved(self):
        """Review fix: pods still bound to a draining node re-enter the
        solve batch every pass; they must not mint a ~0s histogram sample
        per pass — their window opens when the drain unbinds them."""
        from karpenter_tpu.api.objects import Node, Pod
        from karpenter_tpu.operator.operator import Operator
        from test_operator import settle
        op = Operator(clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        bound = PODS_TIME_TO_SCHEDULE.count()
        pod = op.store.list(Pod)[0]
        node = op.store.get(Node, pod.spec.node_name)
        op.store.delete(node)  # drain: the pod rides along while bound
        for _ in range(4):
            op.provisioner.trigger()
            op.step()
            op.clock.step(1.1)
        # at most ONE more observation (the legitimate re-schedule once
        # the drain unbinds the pod) — never one per drain pass
        assert PODS_TIME_TO_SCHEDULE.count() <= bound + 1

    def test_unschedulable_pod_window_stays_open(self):
        from karpenter_tpu.operator.operator import Operator
        from test_operator import settle
        op = Operator(clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m",
                                 node_selector={"no-such-label": "x"}))
        before = PODS_TIME_TO_SCHEDULE.count()
        settle(op)
        assert PODS_TIME_TO_SCHEDULE.count() == before
        assert len(op.provisioner._pending_first_seen) == 1


class TestMeasureClockInjection:
    def test_exact_bucket_placement(self):
        reg = Registry()
        clk = _StepClock()
        prev = reg.set_measure_clock(clk.now)
        try:
            h = reg.histogram("test_measure_seconds", "t",
                              buckets=(1.0, 2.0, 5.0))
            done = reg.measure("test_measure_seconds")
            clk.step(1.5)
            done()
        finally:
            reg.set_measure_clock(prev)
        assert h.count() == 1
        assert h.sum() == pytest.approx(1.5)
        # exactly the 2.0 and 5.0 buckets (and +Inf), NOT the 1.0 bucket
        counts = h._counts[()]
        assert counts == [0, 1, 1, 1]

    def test_restores_previous_clock(self):
        reg = Registry()
        prev = reg.set_measure_clock(lambda: 0.0)
        assert prev is time.perf_counter
        restored = reg.set_measure_clock(prev)
        assert restored() == pytest.approx(restored())


class TestCardinalityCap:
    def test_counter_cap_and_overflow_counter(self):
        reg = Registry()
        c = reg.counter("test_capped_total", "t", ("k",), max_series=2)
        before = SERIES_DROPPED.value({"metric": "test_capped_total"})
        c.inc({"k": "a"})
        c.inc({"k": "b"})
        c.inc({"k": "c"})  # past the cap: dropped
        c.inc({"k": "a"})  # existing series still accepted
        assert c.value({"k": "a"}) == 2
        assert c.value({"k": "b"}) == 1
        assert c.value({"k": "c"}) == 0
        assert len(c._values) == 2
        assert SERIES_DROPPED.value(
            {"metric": "test_capped_total"}) == before + 1

    def test_histogram_and_gauge_caps(self):
        reg = Registry()
        h = reg.histogram("test_capped_seconds", "t", ("k",), max_series=1)
        h.observe(1.0, {"k": "a"})
        h.observe(1.0, {"k": "b"})
        assert h.count({"k": "a"}) == 1 and h.count({"k": "b"}) == 0
        g = reg.gauge("test_capped_gauge", "t", ("k",), max_series=1)
        g.set(1.0, {"k": "a"})
        g.set(2.0, {"k": "b"})
        assert g.value({"k": "a"}) == 1.0 and g.value({"k": "b"}) == 0.0
        # prune frees capacity for new series again
        g.prune([])
        g.set(3.0, {"k": "b"})
        assert g.value({"k": "b"}) == 3.0

    def test_phase_histogram_is_capped(self):
        # phases x {cold, delta, ""} x bounded tenants (the sidecar's
        # per-tenant label rides this family): the cap must clear the
        # legitimate worst case (~40 x 3 x 34) with headroom
        assert SOLVER_PHASE_DURATION.max_series == 8192

    def test_uncapped_by_default(self):
        reg = Registry()
        c = reg.counter("test_uncapped_total", "t", ("k",))
        for i in range(100):
            c.inc({"k": str(i)})
        assert len(c._values) == 100


class TestDumpCLI:
    def test_dump_and_show_roundtrip(self, traced_solves, tmp_path, capsys):
        from karpenter_tpu.obs.__main__ import main
        out = tmp_path / "trace.json"
        assert main(["dump", "--out", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert doc["traceEvents"]
        assert all(e["ph"] == "X" for e in doc["traceEvents"])
        assert main(["show", str(out)]) == 0
        text = capsys.readouterr().out
        assert "root=" in text and "traces" in text

    def test_dump_out_dash_means_stdout(self, traced_solves, tmp_path,
                                        capsys, monkeypatch):
        from karpenter_tpu.obs.__main__ import main
        monkeypatch.chdir(tmp_path)
        assert main(["dump", "--out", "-"]) == 0
        out = capsys.readouterr().out
        assert json.loads(out)["traceEvents"]
        assert not (tmp_path / "-").exists()  # no literal "-" file

    def test_show_prints_exclusive_times(self, tmp_path, capsys):
        """Review fix: `obs show` subtracts child time like phase_millis,
        so its table and the bench's phases line agree on the same data."""
        from karpenter_tpu.obs.__main__ import main
        clk = _StepClock()
        tr = Tracer(now=clk.now)
        with tr.span("root"):
            with tr.span("parent"):
                clk.step(1.0)
                with tr.span("child"):
                    clk.step(3.0)
            clk.step(0.5)
        out = tmp_path / "t.json"
        out.write_text(dumps_chrome(tr.traces()))
        assert main(["show", str(out)]) == 0
        lines = capsys.readouterr().out.splitlines()
        parent = next(l for l in lines if l.strip().startswith("parent"))
        child = next(l for l in lines if l.strip().startswith("child"))
        assert "1000.000 ms" in parent  # exclusive, not the 4000ms span
        assert "3000.000 ms" in child

    def test_dump_against_live_operator(self, tmp_path):
        import urllib.request

        from karpenter_tpu.obs.__main__ import main
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from test_operator import settle
        op = Operator(options=Options(metrics_port=0, health_probe_port=0),
                      clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        sg = op.start_serving()
        out = tmp_path / "live.json"
        try:
            assert main(["dump",
                         "--url", f"http://127.0.0.1:{sg.metrics_port}",
                         "--out", str(out)]) == 0
        finally:
            op.stop_serving()
        doc = json.loads(out.read_text())
        names = {e["name"] for e in doc["traceEvents"]}
        assert "provisioner.pass" in names
        assert "solve" in names


class TestMispairedSpanRendering:
    """ISSUE 12 satellite: spans that do NOT nest cleanly (possible after
    a mid-span exception recovery closes out of order) must render
    deterministically with no negative exclusive times — in both the live
    phase_millis breakdown and `obs show`'s ts/dur reconstruction."""

    def test_exclusive_micros_clips_overlap_to_parent_interval(self):
        from karpenter_tpu.obs.__main__ import _exclusive_micros
        # a=[0,10ms]; b=[5,15ms] OVERLAPS a (not nested); c=[12,14ms] in b
        evs = [
            {"name": "a", "ts": 0.0, "dur": 10_000.0, "tid": 1},
            {"name": "b", "ts": 5_000.0, "dur": 10_000.0, "tid": 1},
            {"name": "c", "ts": 12_000.0, "dur": 2_000.0, "tid": 1},
        ]
        totals = _exclusive_micros(evs)
        assert all(v >= 0 for v in totals.values()), totals
        # a is discounted ONLY b's overlap (5 ms), never b's full 10 ms
        assert totals["a"] == pytest.approx(5_000.0)
        assert totals["b"] == pytest.approx(8_000.0)  # minus c's 2 ms
        assert totals["c"] == pytest.approx(2_000.0)
        # deterministic: same input, same table, regardless of input order
        assert _exclusive_micros(list(reversed(evs))) == totals

    def test_exclusive_micros_child_outliving_parent(self):
        from karpenter_tpu.obs.__main__ import _exclusive_micros
        # child starts inside the parent and ends AFTER it, with a child
        # duration LONGER than the parent's: the old full-duration
        # discount drove the parent negative (silently clamped to 0)
        evs = [
            {"name": "p", "ts": 0.0, "dur": 9_000.0, "tid": 1},
            {"name": "q", "ts": 8_000.0, "dur": 12_000.0, "tid": 1},
        ]
        totals = _exclusive_micros(evs)
        assert totals["p"] == pytest.approx(8_000.0)  # 9 ms - 1 ms overlap
        assert totals["q"] == pytest.approx(12_000.0)

    def test_phase_millis_overlapping_child_never_negative(self):
        from karpenter_tpu.obs.tracer import PassTrace, Span
        root = Span("solve", 0.0, -1, 0, 1, {})
        root.end = 0.020
        x = Span("x", 0.001, 0, 1, 1, {})
        x.end = 0.010
        # y records x as its parent but OVERLAPS it (mispaired exit):
        # y's duration (12 ms) exceeds x's (9 ms)
        y = Span("y", 0.008, 1, 2, 1, {})
        y.end = 0.020
        trace = PassTrace("t1", 0.0, [root, x, y])
        phases = phase_millis(trace)
        assert phases["x"] == pytest.approx(7.0)   # 9 ms - 2 ms overlap
        assert phases["y"] == pytest.approx(12.0)
        assert all(v >= 0 for v in phases.values())
        # rendering is deterministic
        assert phase_millis(trace) == phases

    def test_clean_nesting_unchanged(self):
        from karpenter_tpu.obs.tracer import PassTrace, Span
        root = Span("solve", 0.0, -1, 0, 1, {})
        root.end = 0.010
        a = Span("a", 0.001, 0, 1, 1, {})
        a.end = 0.008
        b = Span("b", 0.002, 1, 2, 1, {})
        b.end = 0.004
        phases = phase_millis(PassTrace("t2", 0.0, [root, a, b]))
        assert phases["a"] == pytest.approx(5.0)
        assert phases["b"] == pytest.approx(2.0)
