"""Durable state: store snapshot/replay + kill-and-restart resync
(VERDICT r2 #7; reference invariant: restart = resync from the apiserver,
state/cluster.go:96-150)."""

import pytest

from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.nodepool import NodePool
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.kube.store import Store
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods
from test_operator import settle


class TestSnapshotRoundtrip:
    def test_save_load_preserves_objects_and_uids(self, tmp_path):
        path = str(tmp_path / "state.bin")
        s1 = Store(FakeClock())
        pod = make_pod(cpu="100m")
        s1.create(pod)
        s1.create(make_nodepool(name="np"))
        assert s1.save(path) == 2

        s2 = Store(FakeClock())
        events = []
        s2.watch(lambda ev: events.append((ev.type, type(ev.obj).__name__)))
        assert s2.load(path) == 2
        restored = s2.get(Pod, pod.name, pod.namespace)
        assert restored is not None and restored.uid == pod.uid
        assert s2.get_by_uid(Pod, pod.uid) is restored
        # replay announced as ADDED, dependency order (pool before pod)
        assert ("ADDED", "NodePool") in events and ("ADDED", "Pod") in events
        assert events.index(("ADDED", "NodePool")) < \
            events.index(("ADDED", "Pod"))

    def test_load_keeps_live_state_on_conflict(self, tmp_path):
        path = str(tmp_path / "state.bin")
        s1 = Store(FakeClock())
        pod = make_pod(cpu="100m", name="same")
        s1.create(pod)
        s1.save(path)
        s2 = Store(FakeClock())
        newer = make_pod(cpu="200m", name="same")
        s2.create(newer)
        s2.load(path)
        assert s2.get(Pod, "same", "default") is newer


class _BrokenObj:
    """Simulates a snapshot object from an incompatible code version: any
    metadata access explodes during load()'s staging pass."""
    @property
    def metadata(self):
        raise AttributeError("incompatible snapshot object")


class TestSnapshotResilience:
    def test_corrupt_snapshot_boots_fresh(self, tmp_path):
        path = str(tmp_path / "state.bin")
        with open(path, "wb") as f:
            f.write(b"\x00garbage")
        op = Operator(options=Options(state_file=path), clock=FakeClock())
        # restart = resync: booting fresh is always legal
        assert op.store.list(Pod) == []
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        assert op.store.list(Node)

    def test_checkpoint_skips_when_unchanged(self, tmp_path):
        import os
        path = str(tmp_path / "state.bin")
        op = Operator(options=Options(state_file=path), clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        op.checkpoint()
        mtime = os.path.getmtime(path)
        os.utime(path, (mtime - 100, mtime - 100))
        op.checkpoint()  # rv unchanged -> no rewrite
        assert os.path.getmtime(path) == mtime - 100

    def test_deletion_advances_checkpoint_watermark(self, tmp_path):
        """A pure-delete tick must still checkpoint: otherwise a restart
        resurrects the deleted object from the stale snapshot."""
        path = str(tmp_path / "state.bin")
        op = Operator(options=Options(state_file=path), clock=FakeClock())
        pod = make_pod(cpu="100m")
        op.store.create(pod)
        op.checkpoint()
        op.store.delete(pod)  # pods carry no finalizers: immediate removal
        op.checkpoint()
        s2 = Store(FakeClock())
        s2.load(path)
        assert s2.get(Pod, pod.name, pod.namespace) is None

    def test_finalizer_removal_advances_watermark(self, tmp_path):
        path = str(tmp_path / "state.bin")
        op = Operator(options=Options(state_file=path), clock=FakeClock())
        pod = make_pod(cpu="100m")
        pod.metadata.finalizers.append("test/f")
        op.store.create(pod)
        op.store.delete(pod)  # only stamps deletionTimestamp
        op.checkpoint()
        op.store.remove_finalizer(pod, "test/f")  # actual removal
        op.checkpoint()
        s2 = Store(FakeClock())
        s2.load(path)
        assert s2.get(Pod, pod.name, pod.namespace) is None

    def test_partial_snapshot_stages_before_announcing(self, tmp_path):
        """load() must mutate nothing when the snapshot can't be fully
        staged (e.g. pickled objects from an incompatible code version)."""
        import pickle
        path = str(tmp_path / "state.bin")
        s1 = Store(FakeClock())
        s1.create(make_pod(cpu="100m"))
        data = {"objs": {**s1._objs, _BrokenObj: {("", "x"): _BrokenObj()}},
                "rv": s1._rv}
        with open(path, "wb") as f:
            pickle.dump(data, f)
        s2 = Store(FakeClock())
        events = []
        s2.watch(lambda ev: events.append(ev))
        with pytest.raises(AttributeError):
            s2.load(path)
        assert not events and s2.list(Pod) == []

    def test_resync_never_reissues_live_claim_provider_id(self, tmp_path):
        """A NodeClaim whose Node is already reaped (restart mid-
        termination) must still pin its provider_id sequence number."""
        path = str(tmp_path / "state.bin")
        op1 = Operator(options=Options(state_file=path), clock=FakeClock())
        op1.store.create(make_nodepool(name="default"))
        op1.store.create(make_pod(cpu="500m"))
        settle(op1)
        nc = op1.store.list(NodeClaim)[0]
        node = op1.store.list(Node)[0]
        node.metadata.finalizers.clear()
        op1.store.delete(node)  # node reaped, claim (with provider_id) lives
        op1.checkpoint()
        clock2 = FakeClock()
        clock2.step(op1.clock.now())
        op2 = Operator(options=Options(state_file=path), clock=clock2)
        op2.store.create(make_pod(cpu="500m", name="after-restart"))
        settle(op2)
        # the orphaned claim is legitimately GC'd (instance vanished), but
        # its provider_id must never be REISSUED to the replacement claim
        pids = [c.status.provider_id for c in op2.store.list(NodeClaim)
                if c.status.provider_id]
        assert pids and nc.status.provider_id not in pids
        assert len(pids) == len(set(pids)), f"duplicate provider_id: {pids}"

    def test_resync_reaps_orphan_kwok_nodes(self, tmp_path):
        path = str(tmp_path / "state.bin")
        op1 = Operator(options=Options(state_file=path), clock=FakeClock())
        op1.store.create(make_nodepool(name="default"))
        op1.store.create(make_pod(cpu="500m"))
        settle(op1)
        node = op1.store.list(Node)[0]
        # claim vanishes behind the snapshot's back (divergent snapshot)
        nc = op1.store.list(NodeClaim)[0]
        nc.metadata.finalizers.clear()
        op1.store.delete(nc)
        op1.checkpoint()
        clock2 = FakeClock()
        clock2.step(op1.clock.now())
        op2 = Operator(options=Options(state_file=path), clock=clock2)
        # resync starts the reap: the node is terminating (finalizer-gated)
        assert op2.store.get(Node, node.name).metadata.deletion_timestamp \
            is not None
        settle(op2)
        # phantom instance drained away, not left as packable capacity
        assert op2.store.get(Node, node.name) is None


class TestKillAndRestart:
    def test_restart_preserves_cluster_and_resumes(self, tmp_path):
        path = str(tmp_path / "state.bin")
        op1 = Operator(options=Options(state_file=path), clock=FakeClock())
        op1.store.create(make_nodepool(name="default"))
        for p in make_pods(3, cpu="500m"):
            op1.store.create(p)
        settle(op1)
        claims1 = {nc.name for nc in op1.store.list(NodeClaim)}
        nodes1 = {n.name for n in op1.store.list(Node)}
        bound1 = {p.name: p.spec.node_name for p in op1.store.list(Pod)}
        assert claims1 and nodes1 and all(bound1.values())
        op1.checkpoint()

        # kill: op1 is gone; a fresh process restores from the snapshot
        clock2 = FakeClock()
        clock2.step(op1.clock.now())
        op2 = Operator(options=Options(state_file=path), clock=clock2)
        assert {nc.name for nc in op2.store.list(NodeClaim)} == claims1
        assert {n.name for n in op2.store.list(Node)} == nodes1
        assert {p.name: p.spec.node_name
                for p in op2.store.list(Pod)} == bound1
        # Synced()-style invariant holds immediately after restore
        assert op2.cluster.synced()

        # controllers resume without wrecking state: GC must NOT reap the
        # restored claims (the kwok fleet resynced from the store)
        settle(op2)
        assert {nc.name for nc in op2.store.list(NodeClaim)} == claims1
        assert {n.name for n in op2.store.list(Node)} == nodes1

        # and the runtime keeps working: a new pod packs onto the restored
        # node's remaining capacity (existing-node state survived)
        newpod = make_pod(cpu="100m")
        op2.store.create(newpod)
        settle(op2)
        assert op2.store.get(Pod, newpod.name, newpod.namespace).spec.node_name

    def test_restart_resumes_inflight_termination(self, tmp_path):
        """A node mid-drain at crash time finishes terminating after
        restart — deletionTimestamp/finalizers are part of the snapshot."""
        path = str(tmp_path / "state.bin")
        op1 = Operator(options=Options(state_file=path), clock=FakeClock())
        op1.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        op1.store.create(pod)
        settle(op1)
        node = op1.store.list(Node)[0]
        op1.store.delete(node)  # sets deletionTimestamp (finalizer held)
        op1.checkpoint()

        clock2 = FakeClock()
        clock2.step(op1.clock.now())
        op2 = Operator(options=Options(state_file=path), clock=clock2)
        restored = op2.store.get(Node, node.name)
        assert restored is not None
        assert restored.metadata.deletion_timestamp is not None
        settle(op2)
        # drain completed: old node gone, pod re-provisioned onto a new one
        assert op2.store.get(Node, node.name) is None
        live = op2.store.get(Pod, pod.name, pod.namespace)
        assert live.spec.node_name and live.spec.node_name != node.name


class TestVersionedSnapshotFormat:
    """VERDICT r4 #9: the snapshot is a versioned wire format, not pickle —
    durable state survives code upgrades, legacy snapshots restore, and a
    future-version snapshot boots fresh with a logged warning."""

    def test_format_is_versioned_json(self, tmp_path):
        from karpenter_tpu.kube import snapshot
        clock = FakeClock()
        store = Store(clock)
        store.create(make_nodepool(name="default"))
        store.create(make_pod(cpu="100m"))
        path = str(tmp_path / "snap.json")
        store.save(path)
        import json
        with open(path, "rb") as f:
            d = json.loads(f.read().decode())
        assert d["format"] == snapshot.FORMAT
        assert d["version"] == snapshot.VERSION
        assert len(d["objects"]) == 2

    def test_round_trip_preserves_objects(self, tmp_path):
        from karpenter_tpu.api.nodeclaim import COND_LAUNCHED, NodeClaim, NodeClaimSpec
        from karpenter_tpu.api.objects import ObjectMeta, Taint
        clock = FakeClock()
        store = Store(clock)
        pool = make_nodepool(name="default",
                             taints=[Taint(key="example.com/t",
                                           effect="NoSchedule")],
                             limits={"cpu": "100"})
        store.create(pool)
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""),
                       spec=NodeClaimSpec())
        nc.status.provider_id = "t://x"
        nc.conditions.set_true(COND_LAUNCHED, now=clock.now())
        store.create(nc)
        path = str(tmp_path / "snap.json")
        store.save(path)
        store2 = Store(FakeClock())
        n = store2.load(path)
        assert n == 2
        pool2 = store2.get(NodePool, "default")
        assert pool2.spec.limits == pool.spec.limits
        assert pool2.spec.template.spec.taints[0].key == "example.com/t"
        nc2 = store2.get(NodeClaim, "nc1")
        assert nc2.status.provider_id == "t://x"
        assert nc2.conditions.is_true(COND_LAUNCHED)

    def test_legacy_pickle_snapshot_restores(self, tmp_path):
        import pickle
        from karpenter_tpu.kube.store import _key
        clock = FakeClock()
        store = Store(clock)
        pool = make_nodepool(name="default")
        store.create(pool)
        path = str(tmp_path / "legacy.pkl")
        with open(path, "wb") as f:
            pickle.dump({"objs": {NodePool: {_key(pool): pool}},
                         "rv": store._rv}, f)
        store2 = Store(FakeClock())
        assert store2.load(path) == 1
        assert store2.get(NodePool, "default") is not None

    def test_future_version_boots_fresh_with_warning(self, tmp_path):
        import json
        from karpenter_tpu.kube import snapshot
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        path = str(tmp_path / "future.json")
        with open(path, "w") as f:
            json.dump({"format": snapshot.FORMAT,
                       "version": snapshot.VERSION + 1,
                       "rv": 7, "objects": [{"__t": "Quantum", "f": {}}]}, f)
        # direct load raises the typed error
        store = Store(FakeClock())
        with pytest.raises(snapshot.IncompatibleSnapshot):
            store.load(path)
        # the operator treats it as unreadable and boots fresh
        op = Operator(options=Options(state_file=path))
        assert not op.store.list(NodePool)
        assert op.cluster.synced()

    def test_field_evolution_tolerated(self, tmp_path):
        """A snapshot written by older code (missing now-existing fields)
        or newer code (extra unknown fields) restores by name: unknown
        fields drop, missing fields take their defaults."""
        import json
        clock = FakeClock()
        store = Store(clock)
        store.create(make_nodepool(name="default"))
        path = str(tmp_path / "snap.json")
        store.save(path)
        with open(path) as f:
            d = json.load(f)

        def walk(node):
            if isinstance(node, dict):
                if node.get("__t") == "NodePoolSpec":
                    node["f"]["future_field"] = {"__u": [1, 2]}  # unknown
                    node["f"].pop("weight", None)                # removed
                for v in node.values():
                    walk(v)
            elif isinstance(node, list):
                for v in node:
                    walk(v)
        walk(d)
        with open(path, "w") as f:
            json.dump(d, f)
        store2 = Store(FakeClock())
        assert store2.load(path) == 1
        pool = store2.get(NodePool, "default")
        assert pool.spec.weight is None        # default filled in
        assert not hasattr(pool.spec, "future_field") or True
        assert pool.spec.template is not None
