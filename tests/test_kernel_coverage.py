"""Widened tensor-kernel constraint coverage (VERDICT r2 #3): minDomains,
multi-constraint groups (zone layer x hostname layer), non-self-selecting
topology selectors, and self-selecting constraints coupled to scheduled
cluster pods — all solved ON the tensor path (no fallback) and pinned
against the host oracle (topologygroup.go:181-342 semantics)."""

import numpy as np
import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (LabelSelector, PodAffinityTerm,
                                       TopologySpreadConstraint)
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.grouping import partition_pods
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import (StaticClusterView, affinity_term, make_nodepool,
                       make_pod, make_pods, make_scheduler, make_state_node,
                       running_on, spread_hostname, spread_zone)

ZONE = api_labels.LABEL_TOPOLOGY_ZONE
HOST = api_labels.LABEL_HOSTNAME


def _its(n=48):
    return kwok.construct_instance_types()[:n]


def spread_zone_md(min_domains, max_skew=1, key="app", value="demo"):
    return TopologySpreadConstraint(
        topology_key=ZONE, max_skew=max_skew, min_domains=min_domains,
        label_selector=LabelSelector(match_labels={key: value}))


def other_sel(value="other"):
    return LabelSelector(match_labels={"app": value})


def tensor_solve(nodepools, its, pods, **kw):
    if not isinstance(its, dict):
        its = {np_.name: list(its) for np_ in nodepools}
    ts = TensorScheduler(nodepools, its, force_tensor=True, **kw)
    results = ts.solve(pods)
    assert ts.fallback_reason == "", f"unexpected fallback: {ts.fallback_reason}"
    assert ts.partition[1] == 0, "expected a fully tensor-eligible batch"
    return results


def host_solve(nodepools, its, pods, **kw):
    return make_scheduler(nodepools, its, pods, **kw).solve(pods)


def zones_of(results):
    out = []
    for nc in results.new_nodeclaims:
        zs = nc.requirements.get(ZONE).values_list()
        if len(zs) == 1:
            out.extend(zs * len(nc.pods))
    return sorted(out)


class TestMinDomains:
    def test_within_domain_count_behaves_like_plain_spread(self):
        def pods():
            return make_pods(8, labels={"app": "demo"},
                             spread=[spread_zone_md(min_domains=2)])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert not t.pod_errors and not h.pod_errors
        assert zones_of(t) == zones_of(h)

    def test_floor_zero_blocks_overflow(self):
        """minDomains > available domains floors the global min to zero
        (topologygroup.go:240-247): with maxSkew=1 every zone takes at most
        one pod, the rest are unschedulable."""
        def pods():
            return make_pods(8, labels={"app": "demo"},
                             spread=[spread_zone_md(min_domains=6)])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert len(t.pod_errors) == len(h.pod_errors) == 4
        assert zones_of(t) == zones_of(h)
        assert len(set(zones_of(t))) == 4  # one pod in each of the 4 zones

    def test_floor_zero_respects_higher_skew(self):
        def pods():
            return make_pods(11, labels={"app": "demo"},
                             spread=[spread_zone_md(min_domains=9, max_skew=2)])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert len(t.pod_errors) == len(h.pod_errors) == 3  # 4 zones x 2
        assert zones_of(t) == zones_of(h)


class TestMultiConstraint:
    def test_zone_spread_plus_host_anti_affinity(self):
        """The most common real combo: spread across zones AND one per node."""
        def pods():
            return make_pods(
                8, labels={"app": "demo"}, spread=[spread_zone()],
                pod_anti_affinity=[affinity_term(HOST)])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert not t.pod_errors and not h.pod_errors
        # one pod per claim, zones balanced 2-2-2-2
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 8
        assert all(len(nc.pods) == 1 for nc in t.new_nodeclaims)
        zt = zones_of(t)
        assert [zt.count(z) for z in sorted(set(zt))] == [2, 2, 2, 2]
        assert zt == zones_of(h)

    def test_zone_spread_plus_hostname_spread(self):
        def pods():
            return make_pods(
                12, labels={"app": "demo"},
                spread=[spread_zone(), spread_hostname(max_skew=2)])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert not t.pod_errors and not h.pod_errors
        assert all(len(nc.pods) <= 2 for nc in t.new_nodeclaims)
        zt = zones_of(t)
        assert [zt.count(z) for z in sorted(set(zt))] == [3, 3, 3, 3]
        assert zt == zones_of(h)

    def test_zone_affinity_plus_host_anti_affinity(self):
        def pods():
            return make_pods(
                5, labels={"app": "demo"},
                pod_affinity=[affinity_term(ZONE)],
                pod_anti_affinity=[affinity_term(HOST)])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 5
        assert len(set(zones_of(t))) == 1  # all in one zone, separate nodes

    def test_unsupported_combos_demote_to_host(self):
        # zonal anti-affinity + hostname spread: host path
        a = make_pods(2, labels={"app": "a"},
                      pod_anti_affinity=[affinity_term(ZONE, value="a")],
                      spread=[spread_hostname(value="a")])
        # hostname affinity + zonal spread: host path
        b = make_pods(2, labels={"app": "b"},
                      pod_affinity=[affinity_term(HOST, value="b")],
                      spread=[spread_zone(value="b")])
        groups, leftover, reason = partition_pods(a + b)
        assert not groups and len(leftover) == 4
        assert "unsupported" in reason

    def test_cross_namespace_affinity_demotes(self):
        term = PodAffinityTerm(topology_key=ZONE,
                               label_selector=other_sel("demo"),
                               namespaces=("elsewhere",))
        pods = make_pods(2, labels={"app": "demo"}, pod_affinity=[term])
        groups, leftover, reason = partition_pods(pods)
        assert not groups and len(leftover) == 2


class TestNonSelfSelectors:
    """Selectors that don't match the group's own labels: the domain counts
    are static (batch placements never change them)."""

    def _view(self, zone_for_other="test-zone-a", node="other-node"):
        others = running_on(make_pods(2, labels={"app": "other"}), node)
        return StaticClusterView(others, {
            node: {ZONE: zone_for_other, HOST: node}})

    def test_non_self_zone_spread_avoids_loaded_zone(self):
        """Counts (2,0,0,0), maxSkew=1: zone a is skew-ineligible; the whole
        batch lands in ONE other zone (the min-count domain never moves)."""
        view = self._view()
        def pods():
            return make_pods(6, labels={"app": "demo"},
                             spread=[spread_zone(value="other")])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view)
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view)
        assert not t.pod_errors and not h.pod_errors
        zt, zh = zones_of(t), zones_of(h)
        assert len(set(zt)) == 1 and "test-zone-a" not in zt
        assert zt == zh

    def test_non_self_zone_spread_no_matches_single_zone(self):
        """Nothing matches the selector anywhere: all-zero counts, min-count
        domain is fixed, every pod goes there."""
        def pods():
            return make_pods(6, labels={"app": "demo"},
                             spread=[spread_zone(value="other")])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert not t.pod_errors and not h.pod_errors
        assert len(set(zones_of(t))) == 1
        assert zones_of(t) == zones_of(h)

    def test_non_self_anti_zone_schedules_all(self):
        """Unlike self-selecting zonal anti-affinity (late committal, one pod
        per batch), non-self pods never exclude each other: all schedule in
        statically-empty zones."""
        view = self._view()
        def pods():
            return make_pods(
                6, labels={"app": "demo"},
                pod_anti_affinity=[PodAffinityTerm(
                    topology_key=ZONE, label_selector=other_sel())])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view)
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view)
        assert not t.pod_errors and not h.pod_errors
        assert "test-zone-a" not in zones_of(t)
        assert "test-zone-a" not in zones_of(h)

    def test_non_self_anti_host_excludes_node_packs_freely(self):
        """The occupied node is excluded, but fresh nodes take many pods
        (no one-per-node cap: batch pods don't match the selector)."""
        sn = make_state_node("other-node", zone="test-zone-a")
        others = running_on(make_pods(1, labels={"app": "other"}),
                            "other-node")
        view = StaticClusterView(others, {
            "other-node": {ZONE: "test-zone-a", HOST: "other-node"}})
        def pods():
            return make_pods(
                8, cpu="100m", labels={"app": "demo"},
                pod_anti_affinity=[PodAffinityTerm(
                    topology_key=HOST, label_selector=other_sel())])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view,
                         state_nodes=[sn])
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view,
                       state_nodes=[sn])
        assert not t.pod_errors and not h.pod_errors
        assert all(not en.pods for en in t.existing_nodes)
        assert all(not en.pods for en in h.existing_nodes)
        # dense packing: far fewer nodes than pods
        assert len(t.new_nodeclaims) < 8
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)

    def test_non_self_zone_affinity_follows_matches(self):
        view = self._view(zone_for_other="test-zone-c")
        def pods():
            return make_pods(
                6, labels={"app": "demo"},
                pod_affinity=[PodAffinityTerm(
                    topology_key=ZONE, label_selector=other_sel())])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view)
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view)
        assert not t.pod_errors and not h.pod_errors
        assert set(zones_of(t)) == {"test-zone-c"} == set(zones_of(h))

    def test_non_self_zone_affinity_no_matches_unschedulable(self):
        """Non-self affinity has no bootstrap (topologygroup.go:283-287
        requires the pod to match its own selector)."""
        def pods():
            return make_pods(
                3, labels={"app": "demo"},
                pod_affinity=[PodAffinityTerm(
                    topology_key=ZONE, label_selector=other_sel())])
        t = tensor_solve([make_nodepool()], _its(), pods())
        h = host_solve([make_nodepool()], _its(), pods())
        assert len(t.pod_errors) == len(h.pod_errors) == 3


class TestSelfWithClusterMatches:
    """Self-selecting constraints coupled to already-scheduled replicas of
    the same deployment — previously host-path territory."""

    def _fixture(self, n_existing=1, zone="test-zone-a"):
        sn = make_state_node("occupied", zone=zone, cpu="16", memory="32Gi")
        existing = running_on(
            make_pods(n_existing, labels={"app": "demo"}), "occupied")
        view = StaticClusterView(existing, {
            "occupied": {ZONE: zone, HOST: "occupied"}})
        return sn, view

    def test_self_anti_host_avoids_occupied_node(self):
        sn, view = self._fixture()
        def pods():
            return make_pods(4, labels={"app": "demo"},
                             pod_anti_affinity=[affinity_term(HOST)])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view,
                         state_nodes=[sn])
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view,
                       state_nodes=[sn])
        assert not t.pod_errors and not h.pod_errors
        assert all(not en.pods for en in t.existing_nodes)
        assert all(not en.pods for en in h.existing_nodes)
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 4

    def test_self_host_spread_budgets_occupied_node(self):
        """maxSkew=2 with one replica already on the node: only ONE more fits
        there (hostname min floors at 0, topologygroup.go:232-234)."""
        sn, view = self._fixture()
        def pods():
            return make_pods(5, cpu="100m", labels={"app": "demo"},
                             spread=[spread_hostname(max_skew=2)])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view,
                         state_nodes=[sn])
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view,
                       state_nodes=[sn])
        assert not t.pod_errors and not h.pod_errors
        t_on = sum(len(en.pods) for en in t.existing_nodes)
        h_on = sum(len(en.pods) for en in h.existing_nodes)
        assert t_on == h_on == 1

    def test_self_affinity_host_joins_occupied_node(self):
        sn, view = self._fixture()
        def pods():
            return make_pods(3, cpu="100m", labels={"app": "demo"},
                             pod_affinity=[affinity_term(HOST)])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view,
                         state_nodes=[sn])
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view,
                       state_nodes=[sn])
        assert not t.pod_errors and not h.pod_errors
        assert sum(len(en.pods) for en in t.existing_nodes) == 3
        assert sum(len(en.pods) for en in h.existing_nodes) == 3
        assert not t.new_nodeclaims and not h.new_nodeclaims

    def test_self_zone_affinity_joins_occupied_zone(self):
        sn, view = self._fixture(zone="test-zone-b")
        def pods():
            return make_pods(4, labels={"app": "demo"},
                             pod_affinity=[affinity_term(ZONE)])
        t = tensor_solve([make_nodepool()], _its(), pods(), cluster=view,
                         state_nodes=[sn])
        h = host_solve([make_nodepool()], _its(), pods(), cluster=view,
                       state_nodes=[sn])
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            claimed = {z for nc in r.new_nodeclaims
                       for z in nc.requirements.get(ZONE).values_list()}
            assert claimed <= {"test-zone-b"}


class TestMixedWideBatch:
    """All widened shapes in one batch, at modest scale, both paths."""

    def _mix(self, per):
        pods = []
        pods += make_pods(per, cpu="1", memory="2Gi")
        pods += make_pods(per, labels={"app": "md"},
                          spread=[spread_zone_md(min_domains=2, key="app",
                                                 value="md")])
        pods += make_pods(per, labels={"app": "combo"},
                          spread=[spread_zone(value="combo")],
                          pod_anti_affinity=[affinity_term(HOST,
                                                           value="combo")])
        pods += make_pods(per, labels={"app": "nonself"},
                          spread=[spread_zone(value="elsewhere")])
        return pods

    @pytest.mark.parametrize("per", [4, 12])
    def test_mix_parity(self, per):
        its = kwok.construct_instance_types()
        np_ = [make_nodepool()]
        t = tensor_solve(np_, its, self._mix(per))
        h = host_solve(np_, its, self._mix(per))
        assert len(t.pod_errors) == len(h.pod_errors), (t.pod_errors,
                                                        h.pod_errors)
        th, hh = len(t.new_nodeclaims), len(h.new_nodeclaims)
        assert abs(th - hh) <= max(1, round(0.05 * hh)), (th, hh)
