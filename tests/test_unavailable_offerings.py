"""Capacity-failure feedback: the TTL'd unavailable-offerings registry.

Five layers of evidence that a capacity drought changes future decisions
instead of hot-looping on the dry offering:

- registry unit behavior: TTL expiry, escalating (capped) TTL on repeated
  exhaustion, wildcard keys, metrics, object-level catalog masking;
- the empty-offerings regression (ISSUE 5 satellite): cheapest() /
  most_expensive() on an empty list, worst_launch_price, and the it_price
  encode all treat "every offering masked" as price +inf, never a bare
  ValueError;
- directed vectors for wildcard-key masking in BOTH solver encodes: the
  provisioning TensorScheduler.build_problem off_available tensor and the
  disruption DisruptionSnapshot encode (consolidation replacements never
  target a masked offering);
- the lifecycle feedback path: an offering-keyed InsufficientCapacityError
  marks the registry, deletes the claim, and re-triggers the provisioner
  (pre-registration claims have no Node, so NodeDeletionTrigger can never
  fire for them); liveness-TTL deletion publishes a warning event and a
  counter instead of vanishing silently;
- the seeded drought soak: zone-wide exhaustion -> one ICE -> the very
  next pass routes every pod to surviving zones with ZERO further create
  calls against the cached-dry zone -> TTL + drought expiry -> recovery
  reaches quiescence with the zone usable again (no flapping).

Deterministic throughout: FakeClock, fixed drought schedules, no sleeps.
"""

import math

import numpy as np
import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec
from karpenter_tpu.api.objects import (LabelSelector, Node, ObjectMeta, Pod,
                                       TopologySpreadConstraint)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.cloudprovider.types import (InsufficientCapacityError,
                                               Offering, Offerings,
                                               order_by_price)
from karpenter_tpu.controllers.nodeclaim_lifecycle import \
    REGISTRATION_TTL_SECONDS
from karpenter_tpu.disruption.helpers import get_candidates
from karpenter_tpu.disruption.methods import SingleNodeConsolidation
from karpenter_tpu.disruption.prefix import DisruptionSnapshot
from karpenter_tpu.metrics.registry import (NODECLAIMS_LIVENESS_TERMINATED,
                                            OFFERINGS_MARKED,
                                            OFFERINGS_UNAVAILABLE)
from karpenter_tpu.provisioning.grouping import group_pods
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.state.unavailable import (UNAVAILABLE_TTL_SECONDS,
                                             UnavailableOfferings, WILDCARD,
                                             mask_instance_types_for)
from karpenter_tpu.utils.chaos import CapacityDrought
from karpenter_tpu.utils.clock import FakeClock

from expectations import (Env, bind_pod, make_env, make_nodeclaim_and_node,
                          most_expensive_instance)
from factories import make_nodepool, make_pod, make_pods

ZONE = api_labels.LABEL_TOPOLOGY_ZONE
SPOT = api_labels.CAPACITY_TYPE_SPOT
OD = api_labels.CAPACITY_TYPE_ON_DEMAND


# --------------------------------------------------------------------------
# registry unit behavior
# --------------------------------------------------------------------------

class TestRegistryUnit:
    def test_mark_expire_ttl(self):
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock, ttl=60.0)
        assert len(reg) == 0 and not reg.is_unavailable("it", "z", "spot")
        ttl = reg.mark("it-a", "zone-1", SPOT)
        assert ttl == 60.0
        assert reg.is_unavailable("it-a", "zone-1", SPOT)
        assert not reg.is_unavailable("it-a", "zone-2", SPOT)
        clock.step(59.0)
        assert reg.is_unavailable("it-a", "zone-1", SPOT)
        clock.step(2.0)
        assert not reg.is_unavailable("it-a", "zone-1", SPOT)
        assert reg.expire() == [("it-a", "zone-1", SPOT)]
        assert len(reg) == 0 and reg.live() == ()

    def test_escalating_ttl_is_capped(self):
        """Escalation fires on failed re-probes AFTER expiry (the marks
        are spaced past each TTL) and is capped."""
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock, ttl=10.0, escalation=2.0,
                                   max_ttl=40.0)
        ttls = []
        for _ in range(5):
            ttl = reg.mark(zone="zone-1")
            ttls.append(ttl)
            clock.step(ttl + 1.0)
        assert ttls == [10.0, 20.0, 40.0, 40.0, 40.0]

    def test_remark_while_live_refreshes_without_escalating(self):
        """Several in-flight claims failing on the same drought in one
        episode (review finding): a re-mark while the entry is LIVE is not
        re-probe evidence — it refreshes the window at the current TTL
        instead of multiplying it 2^N within seconds."""
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock, ttl=10.0, escalation=2.0,
                                   max_ttl=40.0)
        assert reg.mark(zone="zone-1") == 10.0   # expires t=10
        clock.step(5.0)
        assert reg.mark(zone="zone-1") == 10.0   # refresh, no escalation
        assert reg.next_expiry() == clock.now() + 10.0
        clock.step(11.0)                         # t=16: expired re-probe
        assert reg.mark(zone="zone-1") == 20.0   # NOW it escalates

    def test_strikes_reset_after_clear_window(self):
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock, ttl=10.0, escalation=2.0,
                                   max_ttl=40.0)
        assert reg.mark(zone="zone-1") == 10.0   # expires t=10
        clock.step(11.0)
        assert reg.mark(zone="zone-1") == 20.0   # t=11, expires t=31
        # clearance is measured from EXPIRY: the key must stay clear past
        # the cap after the entry lapsed before strikes reset
        clock.step(50.0)  # t=61: clear for 30s < 40s cap -> still strikes
        assert reg.mark(zone="zone-1") == 40.0   # expires t=101
        clock.step(40.0 + 42.0)  # t=143: clear for 42s > the 40s cap
        assert reg.mark(zone="zone-1") == 10.0

    def test_escalation_holds_at_cap_under_persistent_drought(self):
        """Regression (review finding): re-probes arrive one pass AFTER
        each entry expires, so the inter-mark gap ~= the previous TTL — a
        since-last-mark clearance window would reset the escalation the
        moment it reached the cap, cycling 10->...->40->10 forever. The
        expiry-anchored window holds the cap."""
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock, ttl=10.0, escalation=2.0,
                                   max_ttl=40.0)
        ttls = []
        for _ in range(6):
            ttl = reg.mark(zone="zone-1")
            ttls.append(ttl)
            clock.step(ttl + 1.0)  # next doomed probe just after expiry
        assert ttls == [10.0, 20.0, 40.0, 40.0, 40.0, 40.0]

    def test_wildcard_key_coverage(self):
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock)
        reg.mark(zone="zone-1")                      # zone-wide
        reg.mark(instance_type="it-big")             # type-wide
        reg.mark("it-x", "zone-2", SPOT)             # exact
        assert reg.is_unavailable("anything", "zone-1", OD)
        assert reg.is_unavailable("it-big", "zone-3", SPOT)
        assert reg.is_unavailable("it-x", "zone-2", SPOT)
        assert not reg.is_unavailable("it-x", "zone-2", OD)
        assert not reg.is_unavailable("it-y", "zone-3", OD)

    def test_metrics_and_snapshot(self):
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock, ttl=30.0)
        marked0 = OFFERINGS_MARKED.value({"reason": "insufficient_capacity"})
        reg.mark(zone="zone-1")
        reg.mark(zone="zone-2")
        assert OFFERINGS_MARKED.value(
            {"reason": "insufficient_capacity"}) == marked0 + 2
        assert OFFERINGS_UNAVAILABLE.value() == 2.0
        snap = reg.snapshot()
        assert [e["zone"] for e in snap] == ["zone-1", "zone-2"]
        assert all(e["instance_type"] == WILDCARD for e in snap)
        clock.step(31.0)
        reg.expire()
        assert OFFERINGS_UNAVAILABLE.value() == 0.0

    def test_mask_instance_types_copies_not_mutates(self):
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock)
        its = construct_instance_types()
        # empty pattern set: no-op returning the same list
        assert mask_instance_types_for(its, reg.live()) is its
        reg.mark(zone="test-zone-a")
        masked = mask_instance_types_for(its, reg.live())
        assert masked is not its
        for orig, cp in zip(its, masked):
            assert cp is not orig
            assert all(o.available for o in orig.offerings)  # untouched
            for o in cp.offerings:
                assert o.available == (o.zone != "test-zone-a")


# --------------------------------------------------------------------------
# empty-offerings regression (satellite: bare ValueError -> price inf)
# --------------------------------------------------------------------------

class TestEmptyOfferingsRegression:
    def test_cheapest_and_most_expensive_on_empty_return_none(self):
        assert Offerings().cheapest() is None
        assert Offerings().most_expensive() is None

    def test_worst_launch_price_on_empty_is_inf(self):
        reqs = Requirements([Requirement(
            api_labels.CAPACITY_TYPE_LABEL_KEY, IN, [SPOT, OD])])
        assert Offerings().worst_launch_price(reqs) == math.inf

    def test_order_by_price_with_fully_masked_type(self):
        clock = FakeClock()
        reg = UnavailableOfferings(clock=clock)
        its = construct_instance_types()[:4]
        reg.mark(instance_type=its[0].name)  # type-wide: empties it
        masked = mask_instance_types_for(its, reg.live())
        ordered = order_by_price(masked, Requirements())
        # the fully masked type prices at +inf: sorted last, no ValueError
        assert ordered[-1].name == its[0].name
        assert not ordered[-1].offerings.available()

    def test_it_price_encodes_inf_for_fully_masked_type(self):
        reg = UnavailableOfferings(clock=FakeClock())
        its = construct_instance_types()
        dead = its[0].name
        reg.mark(instance_type=dead)
        ts = TensorScheduler([make_nodepool(name="default")],
                             {"default": its}, unavailable=reg)
        groups, reason = group_pods([make_pod()])
        assert groups is not None, reason
        problem, _, catalog = ts.build_problem(groups)
        t = next(i for i, it in enumerate(catalog) if it.name == dead)
        assert problem.it_price[t] == np.inf
        assert not problem.off_available[t].any()
        # unmasked rows are untouched
        alive = next(i for i, it in enumerate(catalog) if it.name != dead)
        assert problem.off_available[alive].any()
        assert np.isfinite(problem.it_price[alive])


# --------------------------------------------------------------------------
# wildcard-key masking in the PROVISIONING encode
# --------------------------------------------------------------------------

class TestProvisioningEncodeMask:
    def _ts(self, reg):
        return TensorScheduler([make_nodepool(name="default")],
                               {"default": construct_instance_types()},
                               unavailable=reg)

    def _spread_pods(self, n=8):
        sel = LabelSelector(match_labels={"app": "spread"})
        return make_pods(n, labels={"app": "spread"},
                         spread=[TopologySpreadConstraint(
                             topology_key=ZONE, max_skew=1,
                             label_selector=sel)])

    def test_zone_wide_mask_flips_off_available(self):
        reg = UnavailableOfferings(clock=FakeClock())
        reg.mark(zone="test-zone-a")
        ts = self._ts(reg)
        groups, _ = group_pods(self._spread_pods())
        problem, _, _ = ts.build_problem(groups)
        zi = problem.vocab.value_idx[problem.zone_key]["test-zone-a"]
        assert not np.any(problem.off_available & (problem.off_zone == zi))
        # the other zones stay live
        zb = problem.vocab.value_idx[problem.zone_key]["test-zone-b"]
        assert np.any(problem.off_available & (problem.off_zone == zb))

    def test_zone_wide_mask_routes_affinity_pods(self):
        """Reroutable pods (zone affinity admitting the dry zone AND a
        survivor) all schedule the very next pass — and when every
        admitted zone is masked, they all error, proving the mask actually
        gates the offering tensor rather than riding along inertly."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        pods = make_pods(6, required_affinity=[[NodeSelectorRequirement(
            ZONE, "In", ("test-zone-a", "test-zone-b"))]])
        reg = UnavailableOfferings(clock=FakeClock())
        reg.mark(zone="test-zone-a")
        ts = self._ts(reg)
        r = ts.solve(pods)
        assert ts.fallback_reason == "", ts.fallback_reason
        assert not r.pod_errors
        assert r.new_nodeclaims
        reg.mark(zone="test-zone-b")  # now every admitted zone is dry
        ts2 = self._ts(reg)
        r2 = ts2.solve(pods)
        assert ts2.fallback_reason == ""
        assert len(r2.pod_errors) == len(pods)

    def test_zone_wide_mask_waterlines_hard_spread(self):
        """DoNotSchedule zonal spread keeps REFERENCE semantics: the dry
        zone stays in the domain universe (domains derive from
        requirements, not offerings — provisioner.go:236-283), so only the
        skew waterline schedules into survivors and the rest error. The
        mask must route what is routable and never commit the dry zone."""
        pods = self._spread_pods(8)
        reg = UnavailableOfferings(clock=FakeClock())
        reg.mark(zone="test-zone-a")
        ts = self._ts(reg)
        r = ts.solve(pods)
        assert ts.fallback_reason == "", ts.fallback_reason
        # waterline: one pod per surviving zone (skew vs the empty dry
        # zone caps at 1), five stuck
        assert len(r.pod_errors) == 5, r.pod_errors
        committed = set()
        for nc in r.new_nodeclaims:
            zr = nc.requirements.raw(ZONE)
            assert zr is not None and not zr.complement
            committed |= set(zr.values)
        assert committed == {"test-zone-b", "test-zone-c", "test-zone-d"}
        # documented deviation (DEVIATIONS.md): the host oracle mirrors the
        # reference greedy, whose next-domain pick for a spread is the
        # single min-count domain regardless of offerings — a dry min
        # domain strands the whole group there, while the tensor path's
        # offering-gated zone water-fill still ships the waterline. The
        # tensor path never does WORSE than the oracle.
        host = self._ts(reg)
        rh = host._host_solve(pods, "forced oracle comparison")
        assert len(rh.pod_errors) >= len(r.pod_errors)

    def test_capacity_type_wide_mask(self):
        reg = UnavailableOfferings(clock=FakeClock())
        reg.mark(capacity_type=SPOT)  # spot dry everywhere
        ts = self._ts(reg)
        groups, _ = group_pods([make_pod()])
        problem, _, _ = ts.build_problem(groups)
        ct_names = np.array(
            [[problem.vocab.values[problem.captype_key][c] if c >= 0 else ""
              for c in row] for row in problem.off_captype], dtype=object)
        assert not np.any(problem.off_available & (ct_names == SPOT))
        assert np.any(problem.off_available & (ct_names == OD))

    def test_type_wide_mask_excludes_type_from_claims(self):
        pods = make_pods(4)
        plain = self._ts(None)
        r0 = plain.solve(pods)
        assert r0.new_nodeclaims
        # without the mask, the launch decision's cheapest option is first
        cheapest = r0.new_nodeclaims[0].instance_type_options[0].name
        reg = UnavailableOfferings(clock=FakeClock())
        reg.mark(instance_type=cheapest)
        ts = self._ts(reg)
        r = ts.solve(pods)
        assert ts.fallback_reason == "" and not r.pod_errors
        for nc in r.new_nodeclaims:
            assert cheapest not in {it.name
                                    for it in nc.instance_type_options}

    def test_host_fallback_sees_the_mask(self):
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        reg = UnavailableOfferings(clock=FakeClock())
        reg.mark(zone="test-zone-a")
        ts = self._ts(reg)
        pods = make_pods(6, required_affinity=[[NodeSelectorRequirement(
            ZONE, "In", ("test-zone-a", "test-zone-b"))]])
        r = ts._host_solve(pods, "forced for the test")
        assert not r.pod_errors
        assert r.new_nodeclaims
        for nc in r.new_nodeclaims:
            for it in nc.instance_type_options:
                for o in it.offerings.available():
                    assert o.zone != "test-zone-a"


# --------------------------------------------------------------------------
# wildcard-key masking in the DISRUPTION encode
# --------------------------------------------------------------------------

class TestDisruptionEncodeMask:
    def test_snapshot_encode_masks_zone(self):
        env = make_env()
        nc, node = make_nodeclaim_and_node(env, zone="test-zone-b")
        bind_pod(env, node, cpu="200m")
        env.unavailable.mark(zone="test-zone-a")
        snap = DisruptionSnapshot(env.cluster, env.provisioner)
        method = SingleNodeConsolidation(env.cluster, env.provisioner)
        candidates = get_candidates(env.cluster, env.provisioner,
                                    method.should_disrupt)
        assert candidates
        enc = snap.encoding_for(candidates)
        problem = enc.problem
        zi = problem.vocab.value_idx[problem.zone_key]["test-zone-a"]
        assert not np.any(problem.off_available & (problem.off_zone == zi))
        zb = problem.vocab.value_idx[problem.zone_key]["test-zone-b"]
        assert np.any(problem.off_available & (problem.off_zone == zb))

    def test_replacement_never_targets_masked_type(self):
        env = make_env()
        big = most_expensive_instance(OD)
        nc, node = make_nodeclaim_and_node(env, instance_type=big,
                                           capacity_type=OD,
                                           zone="test-zone-b")
        bind_pod(env, node, cpu="200m", memory="128Mi")
        method = SingleNodeConsolidation(env.cluster, env.provisioner)
        candidates = get_candidates(env.cluster, env.provisioner,
                                    method.should_disrupt)
        cmd, _ = method.compute_command({"default": 10}, candidates)
        assert cmd.decision == "replace", cmd.decision
        cheapest_opt = cmd.replacements[0].instance_type_options[0].name

        # mask the winning replacement type type-wide and re-plan: the new
        # replacement must avoid it entirely
        env.unavailable.mark(instance_type=cheapest_opt)
        method2 = SingleNodeConsolidation(env.cluster, env.provisioner)
        candidates2 = get_candidates(env.cluster, env.provisioner,
                                     method2.should_disrupt)
        cmd2, _ = method2.compute_command({"default": 10}, candidates2)
        assert cmd2.decision == "replace", cmd2.decision
        for repl in cmd2.replacements:
            assert cheapest_opt not in {it.name
                                        for it in repl.instance_type_options}


# --------------------------------------------------------------------------
# the lifecycle feedback path (ICE -> registry -> trigger; liveness)
# --------------------------------------------------------------------------

class TestLifecycleFeedback:
    def test_ice_marks_registry_triggers_and_reroutes(self):
        env = make_env()
        drought = CapacityDrought(clock=env.clock)
        env.provider.drought = drought
        drought.exhaust(zone="test-zone-a")  # zone-wide, until cleared
        pod = make_pod()
        env.store.create(pod)
        env.settle(rounds=6)
        # exactly ONE create probed the dry zone; the registry now covers
        # it zone-wide and the re-triggered pass landed in a survivor
        assert sum(drought.hits.values()) == 1, dict(drought.hits)
        assert env.unavailable.is_unavailable("m-4x-amd64-linux",
                                              "test-zone-a", SPOT)
        live = env.store.get(Pod, pod.name, "default")
        assert live.spec.node_name, "pod never rescheduled after ICE"
        node = env.store.get(Node, live.spec.node_name)
        assert node.metadata.labels[ZONE] != "test-zone-a"
        assert env.events("InsufficientCapacityError")
        # no node ever materialized in the dry zone
        assert all(n.metadata.labels.get(ZONE) != "test-zone-a"
                   for n in env.nodes())

    def test_ice_path_calls_the_provisioner_trigger(self):
        """The satellite fix pinned directly: an ICE-deleted claim is
        pre-registration (no Node), so NodeDeletionTrigger can never fire
        — the lifecycle controller itself must re-trigger provisioning."""
        from karpenter_tpu.controllers.nodeclaim_lifecycle import \
            NodeClaimLifecycle
        env = Env(provider=lambda s: FakeCloudProvider())
        fired = []
        lc = NodeClaimLifecycle(env.store, env.cluster, env.provider,
                                env.clock, recorder=env.recorder,
                                unavailable=env.unavailable,
                                trigger=lambda: fired.append(1))
        env.provider.next_create_err = InsufficientCapacityError(
            "zone dry", offerings=(("*", "test-zone-1", "*"),))
        nc = NodeClaim(
            metadata=ObjectMeta(
                name="doomed",
                labels={api_labels.NODEPOOL_LABEL_KEY: "default"}),
            spec=NodeClaimSpec())
        env.store.create(nc)
        lc.reconcile(env.store.get(NodeClaim, "doomed"))
        assert fired == [1]
        live = env.store.get(NodeClaim, "doomed")
        # deleted (the termination finalizer may still be draining)
        assert live is None or live.metadata.deletion_timestamp is not None
        assert env.unavailable.live() == (("*", "test-zone-1", "*"),)

    def test_ice_without_offering_keys_marks_nothing(self):
        env = Env(provider=lambda s: FakeCloudProvider())
        env.store.create(make_nodepool(name="default"))
        env.provider.next_create_err = InsufficientCapacityError("legacy")
        env.store.create(make_pod())
        env.allow_reconcile_errors = True  # fake creates no Nodes: claims
        for _ in range(3):                 # churn without quiescing
            env.mgr.run_until_quiet()
            env.clock.step(1.1)
        assert len(env.unavailable) == 0

    def test_liveness_deletion_publishes_event_and_metric(self):
        env = Env(provider=lambda s: FakeCloudProvider())
        base = NODECLAIMS_LIVENESS_TERMINATED.value({"nodepool": "default"})
        nc = NodeClaim(
            metadata=ObjectMeta(
                name="stuck",
                labels={api_labels.NODEPOOL_LABEL_KEY: "default"}),
            spec=NodeClaimSpec())
        env.store.create(nc)
        env.mgr.run_until_quiet()
        # launched (fake sets a provider id) but no Node ever appears
        assert env.store.get(NodeClaim, "stuck") is not None
        env.clock.step(REGISTRATION_TTL_SECONDS + 1.0)
        env.settle()
        assert env.store.get(NodeClaim, "stuck") is None
        assert env.events("FailedRegistration"), \
            [e.reason for e in env.recorder.events]
        assert NODECLAIMS_LIVENESS_TERMINATED.value(
            {"nodepool": "default"}) == base + 1


# --------------------------------------------------------------------------
# graceful exhaustion: every compatible offering masked
# --------------------------------------------------------------------------

class TestGracefulExhaustion:
    def test_total_drought_warns_once_backs_off_and_recovers(self):
        env = make_env()
        drought = CapacityDrought(clock=env.clock)
        env.provider.drought = drought
        drought.exhaust()  # EVERYTHING dry
        pod = make_pod()
        env.store.create(pod)
        env.settle(rounds=6)
        # one probe, one wildcard registry entry, zero instances created
        assert sum(drought.hits.values()) == 1, dict(drought.hits)
        assert len(env.provider.created) == 0
        live = env.store.get(Pod, pod.name, "default")
        assert not live.spec.node_name
        # ONE distinct warning, deduped across the backoff requeues
        assert len(env.events("AllOfferingsUnavailable")) == 1
        # more churn inside the TTL: no hot loop — no new create probes,
        # no duplicate warning
        env.settle(rounds=6)
        assert sum(drought.hits.values()) == 1
        assert len(env.provider.created) == 0
        assert len(env.events("AllOfferingsUnavailable")) == 1

        # capacity returns; the registry TTL lapses; the held provisioner
        # re-solves and the pod lands — quiescence, no flapping
        drought.clear()
        env.clock.step(UNAVAILABLE_TTL_SECONDS + 1.0)
        env.settle(rounds=6)
        live = env.store.get(Pod, pod.name, "default")
        assert live.spec.node_name, "pod never recovered after the drought"
        assert len(env.unavailable) == 0
        assert env.mgr.run_until_quiet()

    def test_mixed_batch_only_drought_pods_warn(self):
        """A pod failing for non-capacity reasons keeps the plain
        FailedScheduling path; only the pod whose every compatible
        offering is masked gets the distinct warning."""
        env = make_env()
        drought = CapacityDrought(clock=env.clock)
        env.provider.drought = drought
        drought.exhaust(zone="test-zone-a")
        # pinned to the (about-to-be-)dry zone: after the ICE marks it,
        # every offering this pod can use is masked
        blocked = make_pod(name="drought-blocked",
                           node_selector={ZONE: "test-zone-a"})
        impossible = make_pod(name="impossible", cpu="100000")  # fits nothing
        env.store.create(blocked)
        env.store.create(impossible)
        env.settle(rounds=6)
        warned = {e.object_name
                  for e in env.events("AllOfferingsUnavailable")}
        assert warned == {"drought-blocked"}
        failed = {e.object_name for e in env.events("FailedScheduling")}
        assert "impossible" in failed

    def test_untolerated_pool_pod_never_warns(self):
        """Pool-level admission counts too (review finding): a pod no
        nodepool admits (untolerated taint) is misconfigured, not
        capacity-blocked, even when a wildcard drought masks everything."""
        from karpenter_tpu.api.objects import Taint, Toleration
        env = Env()
        env.store.create(make_nodepool(
            name="default",
            taints=[Taint(key="team", value="x", effect="NoSchedule")]))
        drought = CapacityDrought(clock=env.clock)
        env.provider.drought = drought
        drought.exhaust()  # everything dry
        tolerant = make_pod(name="capacity-blocked", tolerations=[
            Toleration(key="team", operator="Equal", value="x",
                       effect="NoSchedule")])
        excluded = make_pod(name="never-admitted")
        env.store.create(tolerant)
        env.store.create(excluded)
        env.settle(rounds=6)
        warned = {e.object_name
                  for e in env.events("AllOfferingsUnavailable")}
        assert warned == {"capacity-blocked"}
        failed = {e.object_name for e in env.events("FailedScheduling")}
        assert "never-admitted" in failed

    def test_unfittable_pod_never_warns_even_under_total_drought(self):
        """A wildcard drought masks every offering — but a pod that fits
        NO instance type is unschedulable, not capacity-blocked, and must
        not be misreported to operators chasing capacity."""
        env = make_env()
        drought = CapacityDrought(clock=env.clock)
        env.provider.drought = drought
        drought.exhaust()  # everything dry
        blocked = make_pod(name="capacity-blocked")
        impossible = make_pod(name="never-fits", cpu="100000")
        env.store.create(blocked)
        env.store.create(impossible)
        env.settle(rounds=6)
        warned = {e.object_name
                  for e in env.events("AllOfferingsUnavailable")}
        assert warned == {"capacity-blocked"}
        failed = {e.object_name for e in env.events("FailedScheduling")}
        assert "never-fits" in failed


# --------------------------------------------------------------------------
# the seeded drought soak (acceptance)
# --------------------------------------------------------------------------

@pytest.mark.chaos
class TestDroughtSoak:
    """Zone-wide exhaustion -> reroute within one pass -> TTL expiry ->
    recovery -> quiescence, with zero creates against the cached-dry zone
    while its TTL lives."""

    DROUGHT_SECONDS = 240.0

    def _env(self):
        env = make_env()
        drought = CapacityDrought(clock=env.clock)
        env.provider.drought = drought
        drought.exhaust(zone="test-zone-a", duration=self.DROUGHT_SECONDS)
        return env, drought

    def _workload(self, n_generic=6, n_zonal=8, tag="w1"):
        """Generic pods (provider routes them) + zone-affinity pods
        admitting the dry zone and one survivor (the SOLVER must route
        them) — both reroutable shapes of the acceptance criterion. Hard
        DoNotSchedule spread over all zones is deliberately absent: the
        dry zone stays in its domain universe (reference semantics), so
        those pods waterline rather than reroute."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        return (make_pods(n_generic, cpu="500m", memory="256Mi",
                          labels={"role": tag})
                + make_pods(n_zonal, cpu="250m", memory="128Mi",
                            labels={"app": tag},
                            required_affinity=[[NodeSelectorRequirement(
                                ZONE, "In",
                                ("test-zone-a", "test-zone-b"))]]))

    def test_drought_soak_converges_and_recovers(self):
        env, drought = self._env()
        for p in self._workload():
            env.store.create(p)
        env.settle(rounds=8)

        # phase 1: exactly one create probed zone-a; everything else was
        # routed by the registry — every pod bound, every node in a
        # surviving zone, no repeat probe against the cached-dry zone
        assert sum(drought.hits.values()) == 1, dict(drought.hits)
        zones = {n.metadata.labels.get(ZONE) for n in env.nodes()
                 if n.metadata.deletion_timestamp is None}
        assert zones and "test-zone-a" not in zones, zones
        for p in env.store.list(Pod):
            assert p.spec.node_name, f"pod {p.name} unbound mid-drought"
        assert ("*", "test-zone-a", "*") in env.unavailable.live()

        # phase 2: a second wave INSIDE the TTL window rides the cache —
        # still zero new probes against zone-a
        for p in self._workload(n_generic=4, n_zonal=4, tag="w2"):
            env.store.create(p)
        env.settle(rounds=8)
        assert sum(drought.hits.values()) == 1, dict(drought.hits)
        zones = {n.metadata.labels.get(ZONE) for n in env.nodes()
                 if n.metadata.deletion_timestamp is None}
        assert "test-zone-a" not in zones

        # phase 3: the drought lapses and the TTL expires; fresh demand
        # that existing free capacity cannot absorb (7-cpu pods vs the
        # small phase-1/2 nodes) forces new launches, which land in the
        # recovered zone (kwok's cheapest offering is zone-a spot) and the
        # system quiesces — no flapping, no stale registry entries
        env.clock.step(max(self.DROUGHT_SECONDS,
                           UNAVAILABLE_TTL_SECONDS) + 30.0)
        env.settle(rounds=4)
        assert len(env.unavailable) == 0
        for p in make_pods(3, cpu="7", memory="8Gi", labels={"role": "w3"}):
            env.store.create(p)
        env.settle(rounds=8)
        live_nodes = {n.name for n in env.nodes()
                      if n.metadata.deletion_timestamp is None}
        for p in env.store.list(Pod):
            assert p.spec.node_name in live_nodes, f"pod {p.name} lost"
        zones = {n.metadata.labels.get(ZONE) for n in env.nodes()
                 if n.metadata.deletion_timestamp is None}
        assert "test-zone-a" in zones, \
            f"recovered zone never reused: {zones}"
        assert sum(drought.hits.values()) == 1  # the window is over
        assert env.mgr.run_until_quiet()

    def test_soak_is_deterministic(self):
        def run():
            env, drought = self._env()
            for p in self._workload():
                env.store.create(p)
            env.settle(rounds=8)
            env.clock.step(self.DROUGHT_SECONDS + 200.0)
            env.settle(rounds=6)
            return (dict(drought.hits), tuple(env.unavailable.live()),
                    sorted((n.metadata.labels.get(ZONE) or "")
                           for n in env.nodes()
                           if n.metadata.deletion_timestamp is None),
                    sorted(bool(p.spec.node_name)
                           for p in env.store.list(Pod)))

        assert run() == run()
