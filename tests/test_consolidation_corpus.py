"""Consolidation regression corpus, ported scenario-by-scenario from
/root/reference/pkg/controllers/disruption/consolidation_test.go (4,382 LoC)
on the expectations harness (tests/expectations.py — the
pkg/test/expectations analog). Each test cites its Go source range.

Families covered here: Replace (:870-2233), Delete (:2234-3071), TTL
validation races (:3072-3498), Multi-NodeClaim (:3499-3984), Node Lifetime
(:3985-4065), Topology (:4066-4254), Events (:102-179), plus the
do-not-disrupt / PDB candidate-gating tables. Budget interplay lives in
test_consolidation_suite.py (ported earlier rounds).

Not ported: PDB unhealthyPodEvictionPolicy entries (:1703-1794) — the PDB
model carries minAvailable/maxUnavailable only (DEVIATIONS: no unhealthy
pod tracking in the standalone runtime).
"""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import COND_CONSOLIDATABLE, NodeClaim
from karpenter_tpu.api.objects import Node, NodeSelectorRequirement
from karpenter_tpu.scheduling.requirement import EXISTS, IN

from expectations import (OD, SPOT, Env, MinValuesReq, bind_pod, catalog,
                          cheapest_instance, consolidation_nodepool,
                          instance_named, make_env, make_nodeclaim_and_node,
                          make_pdb, make_replacements_ready,
                          most_expensive_instance, sorted_by_price)
from factories import make_nodepool, make_pod


def _it_label(obj):
    return obj.metadata.labels.get(api_labels.LABEL_INSTANCE_TYPE, "")


class TestReplace:
    """consolidation_test.go:870-2233."""

    @pytest.mark.parametrize("capacity_type", [OD, SPOT])
    def test_can_replace_node(self, capacity_type):
        """:871-931 'can replace node' (on-demand and spot entries): a pod
        on the most expensive instance moves to a cheaper replacement; the
        old claim and node are deleted."""
        env = make_env(spot_to_spot=True)
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=capacity_type,
            instance_type=most_expensive_instance(capacity_type))
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption()
        claims, nodes = env.nodeclaims(), env.nodes()
        assert len(claims) == 1 and len(nodes) == 1
        assert claims[0].name != nc.name, "old claim survived"
        assert not env.nodeclaim_exists(nc.name)
        assert not env.node_exists(node.name)
        # the replacement must not be the most expensive type (:922-924)
        assert _it_label(nodes[0]) != most_expensive_instance(capacity_type).name
        # the pod rode over
        live_pods = [p for p in env.store.list(type(make_pod()))
                     if p.spec.node_name]
        assert all(p.spec.node_name == nodes[0].name for p in live_pods)

    def test_spot_to_spot_fewer_than_15_cheaper_blocks(self):
        """:932-1005 'cannot replace spot with spot if less than minimum
        InstanceTypes flexibility': restrict the pool so fewer than 15
        cheaper spot types exist; the node stays and the Unconsolidatable
        event names the floor."""
        spot_sorted = sorted_by_price(SPOT)
        allowed = [it.name for it in spot_sorted[:5]] + [spot_sorted[-1].name]
        pool = consolidation_nodepool()
        pool.spec.template.spec.requirements = [NodeSelectorRequirement(
            key=api_labels.LABEL_INSTANCE_TYPE, operator=IN,
            values=tuple(allowed))]
        env = make_env(pool, spot_to_spot=True)
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=spot_sorted[-1])
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name), "node must not consolidate"
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert any("SpotToSpotConsolidation requires 15 cheaper instance "
                   "type options" in m for m in msgs), msgs

    def test_spot_to_spot_disabled_blocks_with_event(self):
        """:1009-1080 'cannot replace spot with spot if the
        spotToSpotConsolidation is disabled'."""
        env = make_env(spot_to_spot=False)
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT,
            instance_type=most_expensive_instance(SPOT))
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert any("SpotToSpotConsolidation is disabled" in m for m in msgs)

    def test_spot_to_spot_launch_list_capped_at_15_cheapest(self):
        """:1082-1185: the single-node spot replacement launches with AT
        MOST the 15 cheapest cheaper types (no continual-consolidation
        ping-pong), every option strictly cheaper than the candidate."""
        env = make_env(spot_to_spot=True)
        cand_it = most_expensive_instance(SPOT)
        cand_price = max(o.price for o in cand_it.offerings
                         if o.capacity_type == SPOT)
        nc, node = make_nodeclaim_and_node(env, capacity_type=SPOT,
                                           instance_type=cand_it)
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.disruption.reconcile()
        assert env.disruption.pending is not None, "no command computed"
        cmd, _ = env.disruption.pending
        [replacement] = cmd.replacements
        opts = replacement.instance_type_options
        assert 0 < len(opts) <= 15
        for it in opts:
            cheapest_spot = min(o.price for o in it.offerings
                                if o.capacity_type == SPOT)
            assert cheapest_spot < cand_price

    def test_min_values_broken_by_price_filter_blocks(self):
        """:1487-1581 'Consolidation should fail if filterByPrice breaks
        the minimum requirement from the NodePools': minValues demands more
        instance-type flexibility than the cheaper-than-candidate set can
        offer, so no command forms."""
        by_price = sorted_by_price(OD)
        # candidate near the cheap end: far fewer than 40 strictly-cheaper
        # types exist, but minValues demands 40 (satisfiable against the
        # full 144-type catalog, so the simulation itself succeeds)
        cand = by_price[3]
        pool = consolidation_nodepool()
        pool.spec.template.spec.requirements = [MinValuesReq(
            key=api_labels.LABEL_INSTANCE_TYPE, operator=EXISTS,
            min_values=40)]
        env = make_env(pool)
        nc, node = make_nodeclaim_and_node(env, instance_type=cand)
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)
        assert env.nodeclaim_exists(nc.name)

    def test_replace_when_another_nodepool_unusable(self):
        """:1582-1645 'can replace nodes if another nodePool returns no
        instance types': a broken second pool must not veto the good
        pool's consolidation."""
        broken = consolidation_nodepool(name="broken")
        broken.spec.template.spec.requirements = [NodeSelectorRequirement(
            key=api_labels.LABEL_INSTANCE_TYPE, operator=IN,
            values=("does-not-exist",))]
        env = make_env(consolidation_nodepool(), broken)
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption()
        assert not env.node_exists(node.name)
        [replacement] = env.nodes()
        assert _it_label(replacement) != most_expensive_instance(OD).name

    def test_pdb_blocking_eviction_blocks_candidate(self):
        """:1646-1702 'can replace nodes, considers PDB': maxUnavailable=0
        over the node's pod blocks the candidate outright."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node, cpu="500m", labels={"app": "guarded"})
        make_pdb(env, {"app": "guarded"}, max_unavailable="0")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)
        msgs = [e.message for e in env.events("DisruptionBlocked")]
        assert any("pdb" in m for m in msgs), msgs

    def test_pdb_with_headroom_allows_replacement(self):
        """:1646-1702 (the allowing entries): a PDB with eviction headroom
        does not block consolidation."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node, cpu="500m", labels={"app": "guarded"})
        make_pdb(env, {"app": "guarded"}, max_unavailable="1")
        env.clock.step(600)
        env.run_disruption()
        assert not env.node_exists(node.name)

    def test_pdb_namespace_must_match(self):
        """:1795-1862 'can replace nodes, PDB namespace must match': a
        blocking PDB in a DIFFERENT namespace is irrelevant."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node, cpu="500m", labels={"app": "guarded"},
                 namespace="default")
        make_pdb(env, {"app": "guarded"}, max_unavailable="0",
                 namespace="other-ns")
        env.clock.step(600)
        env.run_disruption()
        assert not env.node_exists(node.name)

    def test_do_not_disrupt_node_annotation_blocks(self):
        """:1863-1955 'considers karpenter.sh/do-not-disrupt on nodes'."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD),
            annotations={api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"})
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)
        msgs = [e.message for e in env.events("DisruptionBlocked")]
        assert any(api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY in m
                   for m in msgs), msgs

    def test_do_not_disrupt_pod_annotation_blocks(self):
        """:1956-2020 'considers karpenter.sh/do-not-disrupt on pods'."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        pod = make_pod(cpu="500m")
        pod.metadata.annotations[api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = \
            "true"
        bind_pod(env, node, pod)
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)

    def test_terminal_do_not_disrupt_pod_does_not_block(self):
        """:2021-2233 (terminal/terminating entries): a Succeeded or Failed
        do-not-disrupt pod no longer blocks consolidation."""
        for phase in ("Succeeded", "Failed"):
            env = make_env()
            nc, node = make_nodeclaim_and_node(
                env, instance_type=most_expensive_instance(OD))
            done = make_pod(cpu="500m", name=f"done-{phase.lower()}")
            done.metadata.annotations[
                api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
            bind_pod(env, node, done)
            done.status.phase = phase
            env.store.update(done)
            live = bind_pod(env, node, cpu="100m",
                            name=f"live-{phase.lower()}")
            env.clock.step(600)
            env.run_disruption()
            assert not env.node_exists(node.name), phase


class TestDelete:
    """consolidation_test.go:2234-3071."""

    def _two_cheap_nodes(self, env, cpu="32"):
        # cheapest SPOT type: the kwok catalog prices every type's spot
        # offering below its on-demand one, so an on-demand "cheapest" node
        # always has a cheaper spot REPLACEMENT — true delete semantics
        # need candidates nothing undercuts (the reference builds its test
        # catalog with the same property: leastExpensiveInstance has the
        # floor price)
        it = cheapest_instance(SPOT)
        pair = [make_nodeclaim_and_node(
            env, instance_type=it, capacity_type=SPOT,
            allocatable={"cpu": cpu, "memory": "128Gi", "pods": "100"})
            for _ in range(2)]
        return pair

    def test_can_delete_node(self):
        """:2259-2304 'can delete nodes': two cheapest-type nodes, three
        pods that fit on one — the emptier node deletes with NO
        replacement."""
        env = make_env()
        (nc0, node0), (nc1, node1) = self._two_cheap_nodes(env)
        bind_pod(env, node0, cpu="500m")
        bind_pod(env, node0, cpu="500m")
        bind_pod(env, node1, cpu="500m")
        env.clock.step(600)
        env.run_disruption()
        assert len(env.nodes()) == 1
        assert len(env.nodeclaims()) == 1
        # no replacement was launched: the survivor is one of the originals
        assert env.nodes()[0].name in (node0.name, node1.name)

    def test_wont_delete_when_pods_dont_fit_elsewhere(self):
        """:2680-2740 (delete guards): both nodes nearly full — removing
        either strands pods, so nothing is disrupted."""
        env = make_env()
        (nc0, node0), (nc1, node1) = self._two_cheap_nodes(env, cpu="3")
        for node in (node0, node1):
            for _ in range(3):
                bind_pod(env, node, cpu="900m")  # 2.7 of 3 allocatable
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert len(env.nodes()) == 2

    def test_delete_prefers_lower_disruption_cost(self):
        """:2234-2304 + types.go disruption-cost ordering: with unequal pod
        counts the lighter node goes."""
        env = make_env()
        (nc0, node0), (nc1, node1) = self._two_cheap_nodes(env)
        for _ in range(4):
            bind_pod(env, node0, cpu="400m")
        bind_pod(env, node1, cpu="400m")
        env.clock.step(600)
        env.run_disruption()
        assert env.node_exists(node0.name)
        assert not env.node_exists(node1.name)

    def test_delete_respects_do_not_disrupt_pod(self):
        """:2775-2860: the delete path honors do-not-disrupt too."""
        env = make_env()
        (nc0, node0), (nc1, node1) = self._two_cheap_nodes(env)
        bind_pod(env, node0, cpu="500m")
        guarded = make_pod(cpu="500m")
        guarded.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        bind_pod(env, node1, guarded)
        env.clock.step(600)
        env.run_disruption(rounds=2)
        # node1 is protected; node0's pod fits on node1? No - node1 is
        # blocked as a candidate but node0 can still consolidate INTO it
        assert env.node_exists(node1.name)


class TestCandidateLabelGates:
    """consolidation_test.go:140-216 (Events + Metrics contexts): the
    price-comparison prerequisites and the eligible-nodes gauge."""

    def test_unresolvable_instance_type_fires_event(self):
        """:140-152: a candidate whose instance-type label names nothing in
        the catalog can't be price-compared."""
        from karpenter_tpu.api.nodeclaim import COND_DRIFTED
        env = make_env()
        nc, node = make_nodeclaim_and_node(env,
                                           instance_type="tpu-ghost-type")
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        # the repo's drift marker ALSO flags unknown instance types
        # (InstanceTypeNotFound) and Drift ranks above consolidation; the
        # reference scenario runs without the marker controller, so clear
        # the condition to reach the consolidation guard under test
        live = env.store.get(type(nc), nc.name)
        live.conditions.set_false(COND_DRIFTED, reason="Test",
                                  now=env.clock.now())
        env.store.update(live)
        env.disruption.reconcile()
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert any('Instance Type "tpu-ghost-type" not found' == m
                   for m in msgs), msgs
        assert env.node_exists(node.name)

    def test_missing_capacity_type_label_fires_event(self):
        """:153-165."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(env)
        for obj in (node, nc):
            del obj.metadata.labels[api_labels.CAPACITY_TYPE_LABEL_KEY]
            env.store.update(obj)
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.disruption.reconcile()
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert any(api_labels.CAPACITY_TYPE_LABEL_KEY in m for m in msgs), msgs

    def test_missing_zone_label_fires_event(self):
        """:166-179."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(env)
        for obj in (node, nc):
            del obj.metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE]
            env.store.update(obj)
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.disruption.reconcile()
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert any(api_labels.LABEL_TOPOLOGY_ZONE in m for m in msgs), msgs

    def test_eligible_nodes_metric_reported(self):
        """:181-216 'should correctly report eligible nodes': the gauge
        follows the candidate count for the underutilized reason."""
        from karpenter_tpu.api.nodepool import REASON_UNDERUTILIZED
        from karpenter_tpu.metrics.registry import DISRUPTION_ELIGIBLE_NODES
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.disruption.reconcile()
        assert DISRUPTION_ELIGIBLE_NODES.value(
            {"reason": REASON_UNDERUTILIZED}) >= 1


class TestReplacePriceGuards:
    """consolidation_test.go:2048-2233."""

    def test_wont_replace_when_replacement_more_expensive(self):
        """:2048-2131 'won't replace node if any spot replacement is more
        expensive': a pod filling the cheapest spot node leaves no cheaper
        home — nothing is disrupted."""
        env = make_env(spot_to_spot=True)
        it = cheapest_instance(SPOT)
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            allocatable={"cpu": "3", "memory": "12Gi", "pods": "100"})
        bind_pod(env, node, cpu="2500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)
        assert env.nodeclaim_exists(nc.name)

    def test_spot_candidate_already_among_cheapest_not_replaced(self):
        """:1050-1120 'cannot replace spot with spot if it is part of the
        15 cheapest instance types': churn protection — a cheapest-tier
        spot node stays put."""
        env = make_env(spot_to_spot=True)
        it = sorted_by_price(SPOT)[2]  # comfortably inside the 15 cheapest
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            allocatable={"cpu": "3", "memory": "12Gi", "pods": "100"})
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)


class TestDeleteEdgeCases:
    """consolidation_test.go:2351-3005."""

    def test_non_karpenter_capacity_can_fit_pods(self):
        """:2351-2404 'can delete nodes, when non-Karpenter capacity can
        fit pods': an unmanaged node's headroom counts, so the managed
        node deletes without any replacement."""
        from karpenter_tpu.api.objects import NodeSpec, NodeStatus, ObjectMeta
        from karpenter_tpu.utils import resources as res
        env = make_env()
        unmanaged = Node(
            metadata=ObjectMeta(
                name="byo-node",
                labels={api_labels.LABEL_HOSTNAME: "byo-node",
                        api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-a"}),
            spec=NodeSpec(provider_id="byo://node"),
            status=NodeStatus(
                capacity=res.parse_list({"cpu": "32", "memory": "128Gi",
                                         "pods": "100"}),
                allocatable=res.parse_list({"cpu": "32", "memory": "128Gi",
                                            "pods": "100"})))
        env.store.create(unmanaged)
        it = cheapest_instance(SPOT)
        nc, node = make_nodeclaim_and_node(env, capacity_type=SPOT,
                                           instance_type=it)
        for _ in range(3):
            bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption()
        assert not env.node_exists(node.name)
        assert env.node_exists("byo-node")
        # no replacement claim was launched
        assert len(env.nodeclaims()) == 0

    def test_evicts_pods_without_owner_ref(self):
        """:2662-2713 'can delete nodes, evicts pods without an ownerRef':
        ownerless pods don't pin the node."""
        env = make_env()
        (nc0, node0), (nc1, node1) = [
            make_nodeclaim_and_node(env, capacity_type=SPOT,
                                    instance_type=cheapest_instance(SPOT))
            for _ in range(2)]
        bind_pod(env, node0, cpu="500m")   # factories make ownerless pods
        env.clock.step(600)
        env.run_disruption()
        # the empty node AND eventually the loaded one consolidate down to
        # one; the ownerless pod was evicted (unbound), then re-placed
        assert len(env.nodes()) == 1

    def test_wont_delete_when_pods_need_uninitialized_node(self):
        """:2714-2758 'won't delete node if it would require pods to
        schedule on an uninitialized node'."""
        env = make_env()
        it = cheapest_instance(SPOT)
        nc0, node0 = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            allocatable={"cpu": "3", "memory": "12Gi", "pods": "100"})
        nc1, node1 = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it, initialized=False,
            allocatable={"cpu": "3", "memory": "12Gi", "pods": "100"})
        bind_pod(env, node0, cpu="2500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node0.name), (
            "pods were parked on an uninitialized node")

    def test_permanently_pending_pod_does_not_block(self):
        """:2907-2962 'can delete nodes with a permanently pending pod':
        a pod that was already unschedulable BEFORE consolidation must not
        veto it (AllNonPendingPodsScheduled ignores it)."""
        env = make_env()
        (nc0, node0), (nc1, node1) = [
            make_nodeclaim_and_node(env, capacity_type=SPOT,
                                    instance_type=cheapest_instance(SPOT))
            for _ in range(2)]
        bind_pod(env, node1, cpu="500m")
        forever_pending = make_pod(
            cpu="500m",
            node_selector={api_labels.LABEL_INSTANCE_TYPE: "no-such-type"})
        env.store.create(forever_pending)
        env.clock.step(600)
        env.settle()
        env.run_disruption()
        assert len(env.nodes()) == 1, "pending pod blocked consolidation"

    def test_anti_affinity_blocks_merge(self):
        """:4193-4254 'won't delete node if it would violate pod
        anti-affinity': one anti-affinity pod per node over the hostname
        domain — neither node can absorb the other's pod."""
        from factories import affinity_term
        env = make_env()
        it = cheapest_instance(SPOT)
        duo = [make_nodeclaim_and_node(env, capacity_type=SPOT,
                                       instance_type=it) for _ in range(2)]
        for _, node in duo:
            p = make_pod(cpu="500m", labels={"app": "exclusive"},
                         pod_anti_affinity=[affinity_term(
                             api_labels.LABEL_HOSTNAME,
                             key="app", value="exclusive")])
            bind_pod(env, node, p)
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert len(env.nodes()) == 2, "anti-affinity was violated"


class TestBudgetMarkerInterplay:
    """consolidation_test.go:608-860: a budget-blocked pass must NOT mark
    the cluster consolidated — when budget opens, consolidation proceeds
    even though nothing else changed."""

    def test_budget_block_does_not_mark_consolidated(self):
        pool = consolidation_nodepool(budgets=("0",))
        env = make_env(pool)
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert env.node_exists(node.name)
        for m in env.disruption.methods[2:]:
            assert not m.is_consolidated(), (
                "budget-blocked pass marked the cluster consolidated")
        # budget opens; NOTHING else changes — consolidation must fire
        live_pool = env.store.get(type(pool), "default")
        live_pool.spec.disruption.budgets = []
        env.store.update(live_pool)
        env.run_disruption()
        assert not env.node_exists(node.name)


class TestParallelization:
    """consolidation_test.go:4255-4381."""

    def test_pending_pods_provision_while_consolidating(self):
        """:4256-4308 'should schedule an additional node when receiving
        pending pods while consolidating': the TTL wait must not starve
        the provisioner."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD),
            allocatable={"cpu": "3", "memory": "12Gi", "pods": "10"})
        bind_pod(env, node, cpu="2500m")
        env.clock.step(600)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        # a burst of pending pods arrives mid-TTL
        for i in range(3):
            env.store.create(make_pod(cpu="2000m", name=f"burst-{i}"))
        env.settle()
        bound = [p for p in env.store.list(type(make_pod()))
                 if p.metadata.name.startswith("burst-") and p.spec.node_name]
        assert len(bound) == 3, "provisioner starved during consolidation TTL"


class TestTTLValidation:
    """consolidation_test.go:3072-3498: the 15 s consolidation TTL and the
    re-validation races inside it (validation.go:83-215)."""

    def _expensive_node_with_pod(self, env):
        nc, node = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        pod = bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        return nc, node, pod

    def test_command_waits_for_ttl(self):
        """:3072-3130 'should wait for the node TTL for non-empty nodes
        before consolidating': after the compute pass the node still
        exists; it goes only once the TTL elapsed and validation passed."""
        env = make_env()
        nc, node, _ = self._expensive_node_with_pod(env)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        assert env.node_exists(node.name), "deleted before the TTL"
        env.clock.step(7)
        env.disruption.reconcile()  # mid-TTL: still pending
        assert env.node_exists(node.name)
        env.run_disruption()
        assert not env.node_exists(node.name)

    def test_new_do_not_disrupt_pod_during_ttl_aborts(self):
        """:3131-3220 'should not consolidate if a do-not-disrupt pod
        schedules during the TTL wait'."""
        env = make_env()
        nc, node, _ = self._expensive_node_with_pod(env)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        guarded = make_pod(cpu="100m")
        guarded.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        bind_pod(env, node, guarded)
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        env.settle()
        assert env.node_exists(node.name), "validation missed the new pod"

    def test_new_pdb_during_ttl_aborts(self):
        """:3221-3300 'should not consolidate if a PDB is added during the
        TTL wait'."""
        env = make_env()
        nc, node, pod = self._expensive_node_with_pod(env)
        pod.metadata.labels["app"] = "late-guard"
        env.store.update(pod)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        make_pdb(env, {"app": "late-guard"}, max_unavailable="0")
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        env.settle()
        assert env.node_exists(node.name)

    def test_nomination_during_ttl_aborts(self):
        """:3301-3390 'should not consolidate if the candidate is nominated
        for a pending pod during the TTL wait' (the parallelization race,
        :4255+)."""
        env = make_env()
        nc, node, _ = self._expensive_node_with_pod(env)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        env.cluster.nominate_node_for_pod(node.name, make_pod(cpu="100m"))
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        env.settle()
        assert env.node_exists(node.name)

    def test_candidate_deleted_during_ttl_aborts(self):
        """:3391-3498: the candidate vanishing mid-TTL abandons the
        command instead of crashing."""
        env = make_env()
        nc, node, _ = self._expensive_node_with_pod(env)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        env.store.delete(nc)
        env.settle()
        env.clock.step(16)
        env.disruption.reconcile()  # must not raise
        env.queue.reconcile()


class TestMultiNodeClaim:
    """consolidation_test.go:3499-3984."""

    @pytest.mark.parametrize("spot_to_spot", [False, True])
    def test_merge_3_nodes_into_1(self, spot_to_spot):
        """:3545-3657 'can merge 3 nodes into 1': three lightly-loaded
        expensive nodes collapse into one replacement."""
        ct = SPOT if spot_to_spot else OD
        env = make_env(spot_to_spot=spot_to_spot)
        trio = [make_nodeclaim_and_node(
            env, capacity_type=ct,
            instance_type=most_expensive_instance(ct)) for _ in range(3)]
        for _, node in trio:
            bind_pod(env, node, cpu="300m")
        env.clock.step(600)
        env.run_disruption(rounds=6)
        assert len(env.nodes()) == 1
        for _, node in trio:
            assert not env.node_exists(node.name)
        assert _it_label(env.nodes()[0]) != most_expensive_instance(ct).name

    def test_wont_merge_2_nodes_into_1_of_same_type(self):
        """:3658-3740 'won't merge 2 nodes into 1 of the same type':
        replacing [cheap, cheap] with one cheap node is just deleting one —
        the delete path handles it; the REPLACE decision must not launch a
        same-type replacement (multinodeconsolidation.go:180-217)."""
        env = make_env()
        it = cheapest_instance(OD)
        (nc0, node0), (nc1, node1) = [
            make_nodeclaim_and_node(
                env, instance_type=it,
                allocatable={"cpu": "4", "memory": "16Gi", "pods": "100"})
            for _ in range(2)]
        # each node half-full: both sets of pods fit on ONE node of the
        # same type, but a replacement launch of that type is forbidden
        for node in (node0, node1):
            bind_pod(env, node, cpu="1500m")
        env.clock.step(600)
        env.run_disruption(rounds=6)
        nodes = env.nodes()
        assert len(nodes) == 1
        # delete-not-replace: the survivor is one of the originals
        assert nodes[0].name in (node0.name, node1.name)

    def test_multi_validation_failure_falls_through(self):
        """:3813-3984 'should continue to single/multi consolidation when
        the earlier method fails validation after the node ttl': blocking
        one candidate mid-TTL doesn't wedge the controller; the next pass
        still consolidates the other."""
        env = make_env()
        (nc0, node0) = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        (nc1, node1) = make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
        bind_pod(env, node0, cpu="300m")
        bind_pod(env, node1, cpu="300m")
        env.clock.step(600)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        # poison node0 mid-TTL
        guarded = make_pod(cpu="100m")
        guarded.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        bind_pod(env, node0, guarded)
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        env.settle()
        assert env.node_exists(node0.name)
        # subsequent passes consolidate node1 alone
        env.run_disruption(rounds=6)
        assert not env.node_exists(node1.name)
        assert env.node_exists(node0.name)


class TestNodeLifetime:
    """consolidation_test.go:3985-4065 'Node Lifetime Consideration'."""

    def test_prefers_node_nearer_expiry(self):
        """:3985-4065: with expireAfter set, the candidate ordering weights
        disruption cost by remaining lifetime — the older node (less
        lifetime left) consolidates first."""
        pool = consolidation_nodepool()
        pool.spec.template.spec.expire_after = 3600.0
        env = make_env(pool)
        it = cheapest_instance(SPOT)
        nc_old, node_old = make_nodeclaim_and_node(
            env, instance_type=it, capacity_type=SPOT, expire_after=3600.0)
        env.clock.step(3000)  # old node: 600 s of life left
        nc_new, node_new = make_nodeclaim_and_node(
            env, instance_type=it, capacity_type=SPOT, expire_after=3600.0)
        bind_pod(env, node_old, cpu="500m")
        bind_pod(env, node_new, cpu="500m")
        env.clock.step(60)
        env.settle()
        # single-node pass: both nodes' pods fit on the other; the OLD one
        # must be chosen
        env.run_disruption(rounds=1)
        if len(env.nodes()) == 2:  # multi pass declined; drive more rounds
            env.run_disruption(rounds=4)
        assert env.node_exists(node_new.name)
        assert not env.node_exists(node_old.name)


class TestTopologyConsideration:
    """consolidation_test.go:4066-4254."""

    def test_zonal_spread_blocks_skew_breaking_delete(self):
        """:4066-4150 'can replace node maintaining zonal topology spread':
        three spread pods across three zones; deleting a zone's node would
        break maxSkew=1, so the replacement must stay in the same zone (or
        nothing is disrupted) — the pod set never collapses to two zones."""
        from factories import spread_zone
        env = make_env()
        zones = ("test-zone-a", "test-zone-b", "test-zone-c")
        spread = [spread_zone(key="app", value="spread-demo")]
        trio = []
        for z in zones:
            nc, node = make_nodeclaim_and_node(
                env, zone=z, instance_type=most_expensive_instance(OD))
            pod = make_pod(cpu="500m", labels={"app": "spread-demo"},
                           spread=spread)
            bind_pod(env, node, pod)
            trio.append((nc, node, pod))
        env.clock.step(600)
        env.run_disruption(rounds=6)
        # wherever consolidation landed, the spread constraint holds: pods
        # still cover three distinct zones
        pod_zones = set()
        for p in env.store.list(type(make_pod())):
            if not p.spec.node_name:
                continue
            n = env.store.get(Node, p.spec.node_name)
            if n is not None:
                pod_zones.add(
                    n.metadata.labels.get(api_labels.LABEL_TOPOLOGY_ZONE))
        assert len(pod_zones) == 3, f"skew broken: {pod_zones}"


class TestEventsContext:
    """consolidation_test.go:102-179 'Events'."""

    def test_no_unconsolidatable_event_when_policy_allows(self):
        """:103-117: WhenEmptyOrUnderutilized + 0s consolidateAfter fires
        NO ConsolidationDisabled-style event."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(env)
        env.clock.step(600)
        env.disruption.reconcile()
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert not any("consolidation disabled" in m for m in msgs), msgs

    def test_unconsolidatable_event_when_when_empty_and_pods(self):
        """:118-141: WhenEmpty policy + a non-empty node fires the
        'non-empty consolidation disabled' event from the underutilized
        methods."""
        from karpenter_tpu.api.nodepool import WHEN_EMPTY
        pool = consolidation_nodepool(consolidate_after=60.0)
        pool.spec.disruption.consolidation_policy = WHEN_EMPTY
        env = make_env(pool)
        nc, node = make_nodeclaim_and_node(env)
        bind_pod(env, node, cpu="500m")
        env.clock.step(600)
        env.disruption.reconcile()
        msgs = [e.message for e in env.events("Unconsolidatable")]
        assert any("non-empty consolidation disabled" in m for m in msgs), msgs


class TestTerminationGracePeriodClass:
    """consolidation_test.go:2565-2660: with a TerminationGracePeriod set,
    the graceful consolidation class still refuses do-not-disrupt/PDB
    candidates (only the EVENTUAL class may override blockers; graceful
    never bypasses them)."""

    def test_do_not_disrupt_still_blocks_with_tgp(self):
        """:2565-2612: every pod annotated do-not-disrupt, claims carry a
        300 s TGP — graceful consolidation must not touch either node."""
        env = make_env()
        it = cheapest_instance(SPOT)
        duo = []
        for _ in range(2):
            nc, node = make_nodeclaim_and_node(
                env, capacity_type=SPOT, instance_type=it)
            nc.spec.termination_grace_period = 300.0
            env.store.update(nc)
            duo.append((nc, node))
        for _, node in duo:
            p = make_pod(cpu="500m")
            p.metadata.annotations[
                api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
            bind_pod(env, node, p)
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert len(env.nodes()) == 2, "graceful class bypassed do-not-disrupt"

    def test_blocking_pdb_still_blocks_with_tgp(self):
        """:2613-2660: a maxUnavailable=0 PDB over the pods blocks
        consolidation even when the claims have a TGP."""
        env = make_env()
        it = cheapest_instance(SPOT)
        duo = []
        for _ in range(2):
            nc, node = make_nodeclaim_and_node(
                env, capacity_type=SPOT, instance_type=it)
            nc.spec.termination_grace_period = 300.0
            env.store.update(nc)
            duo.append((nc, node))
        for _, node in duo:
            bind_pod(env, node, cpu="500m", labels={"app": "tgp-guard"})
        make_pdb(env, {"app": "tgp-guard"}, max_unavailable="0")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert len(env.nodes()) == 2, "graceful class bypassed the PDB"


class TestMixedCapacityMerge:
    """consolidation_test.go:3597-3657."""

    def test_merge_mixed_spot_and_od_candidates(self):
        """'can merge 3 nodes into 1 if the candidates have both spot and
        on-demand': two OD expensive nodes + one spot expensive node, all
        lightly loaded, collapse into one replacement (the not-all-spot
        rule: the spot-to-spot gate does NOT apply to mixed sets)."""
        env = make_env(spot_to_spot=False)  # gate off: mixed must still work
        trio = [
            make_nodeclaim_and_node(env, capacity_type=OD,
                                    instance_type=most_expensive_instance(OD)),
            make_nodeclaim_and_node(env, capacity_type=OD,
                                    instance_type=most_expensive_instance(OD)),
            make_nodeclaim_and_node(
                env, capacity_type=SPOT,
                instance_type=most_expensive_instance(SPOT)),
        ]
        for _, node in trio:
            bind_pod(env, node, cpu="300m")
        env.clock.step(600)
        env.run_disruption(rounds=6)
        assert len(env.nodes()) == 1
        for _, node in trio:
            assert not env.node_exists(node.name)


class TestSpotOrderingBeforeFlexibility:
    """consolidation_test.go:1121-1236 'spot to spot consolidation should
    order the instance types by price before enforcing minimum
    flexibility'."""

    def test_floor_counts_strictly_cheaper_types(self):
        """The >=15 floor counts STRICTLY-CHEAPER types (price filter
        first): a candidate with 20 cheaper spot types consolidates; one
        with only 8 cheaper does not. (The kwok catalog prices tie in
        groups of 4 — 2 OS x 2 arch — so indices are chosen clear of the
        boundary; the launch-list ordering property itself is pinned by
        test_spot_to_spot_launch_list_capped_at_15_cheapest above, which
        inspects the truncated list the Go scenario :1121-1236 audits.)"""
        spot_sorted = sorted_by_price(SPOT)
        for idx, expect_replace in ((20, True), (8, False)):
            env = make_env(spot_to_spot=True)
            nc, node = make_nodeclaim_and_node(
                env, capacity_type=SPOT, instance_type=spot_sorted[idx],
                allocatable={"cpu": "2", "memory": "8Gi", "pods": "100"})
            bind_pod(env, node, cpu="100m")
            env.clock.step(600)
            env.run_disruption(rounds=3)
            if expect_replace:
                assert not env.node_exists(node.name), idx
            else:
                assert env.node_exists(node.name), idx


class TestMultiNodeTTL:
    """consolidation_test.go:3741-3812 'should wait for the node TTL for
    non-empty nodes before consolidating (multi-node)'."""

    def test_multi_node_command_waits_for_ttl(self):
        env = make_env()
        trio = [make_nodeclaim_and_node(
            env, instance_type=most_expensive_instance(OD))
            for _ in range(3)]
        for _, node in trio:
            bind_pod(env, node, cpu="300m")
        env.clock.step(600)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        for _, node in trio:
            assert env.node_exists(node.name), "deleted before the TTL"
        env.clock.step(7)
        env.disruption.reconcile()
        # the command must STILL be held mid-TTL (not executed-and-queued):
        # pending is the direct witness that the TTL gate fired, immune to
        # the queue/manager lag that keeps nodes alive a few passes anyway
        assert env.disruption.pending is not None, "TTL gate bypassed"
        for _, node in trio:
            assert env.node_exists(node.name), "deleted mid-TTL"
        env.run_disruption(rounds=6)
        assert len(env.nodes()) == 1


class TestDeletePathGates:
    """consolidation_test.go:2405-2564: the delete path honors PDBs and
    node-level do-not-disrupt exactly like replace."""

    def test_delete_considers_pdb(self):
        """:2405-2467 'can delete nodes, considers PDB': minAvailable
        pinning every pod keeps both nodes."""
        env = make_env()
        it = cheapest_instance(SPOT)
        duo = [make_nodeclaim_and_node(env, capacity_type=SPOT,
                                       instance_type=it) for _ in range(2)]
        for _, node in duo:
            bind_pod(env, node, cpu="500m", labels={"app": "del-guard"})
        make_pdb(env, {"app": "del-guard"}, min_available="2")
        env.clock.step(600)
        env.run_disruption(rounds=2)
        assert len(env.nodes()) == 2

    def test_delete_considers_node_do_not_disrupt(self):
        """:2468-2515 'considers karpenter.sh/do-not-disrupt on nodes':
        the annotated node survives; the other may consolidate into it."""
        env = make_env()
        it = cheapest_instance(SPOT)
        nc0, node0 = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            annotations={api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"})
        nc1, node1 = make_nodeclaim_and_node(env, capacity_type=SPOT,
                                             instance_type=it)
        bind_pod(env, node0, cpu="500m")
        bind_pod(env, node1, cpu="500m")
        env.clock.step(600)
        env.run_disruption()
        assert env.node_exists(node0.name), "annotated node was disrupted"
