"""Real-apiserver adapter (VERDICT r4 #5): k8s wire-shape codec round
trips (always run) + a gated integration test that provisions one
NodeClaim through a live/kwok apiserver (skipped without a cluster)."""

import os

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import COND_LAUNCHED, NodeClaim, NodeClaimSpec
from karpenter_tpu.api.nodepool import Budget, NodePool
from karpenter_tpu.api.objects import (HostPort, Node, NodeSpec, NodeStatus,
                                       ObjectMeta, Pod, PVCRef, Taint,
                                       Toleration)
from karpenter_tpu.kube import k8s_codec as kc
from karpenter_tpu.provisioning.scheduler import _SelectorReq
from karpenter_tpu.utils import resources as res

from factories import (affinity_term, make_nodepool, make_pod, spread_zone)


class TestScalars:
    def test_durations(self):
        assert kc.duration_to_k8s(None) == "Never"
        assert kc.duration_to_k8s(300.0) == "5m"
        assert kc.duration_to_k8s(3661.0) == "1h1m1s"
        assert kc.duration_to_k8s(0.0) == "0s"
        assert kc.duration_from_k8s("Never") is None
        assert kc.duration_from_k8s("5m") == 300.0
        assert kc.duration_from_k8s("1h1m1s") == 3661.0
        assert kc.duration_from_k8s("720h") == 720 * 3600.0

    def test_timestamps(self):
        t = 1_700_000_000.0
        assert kc.ts_from_k8s(kc.ts_to_k8s(t)) == t
        assert kc.ts_to_k8s(0.0) is None
        assert kc.ts_from_k8s(None) == 0.0

    def test_quantities(self):
        rl = res.parse_list({"cpu": "500m", "memory": "1Gi", "pods": "110"})
        back = kc.resources_from_k8s(kc.resources_to_k8s(rl))
        assert back == rl


class TestPodRoundTrip:
    def test_full_pod(self):
        pod = make_pod(cpu="500m", memory="1Gi", labels={"app": "x"},
                       node_selector={"zone": "a"},
                       tolerations=[Toleration(key="k", operator="Exists",
                                               effect="NoSchedule")],
                       spread=[spread_zone(key="app", value="x")],
                       pod_anti_affinity=[
                           affinity_term(api_labels.LABEL_HOSTNAME,
                                         key="app", value="x")],
                       host_ports=[HostPort(port=8080)])
        pod.spec.volumes.append(PVCRef(claim_name="data"))
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True,
                                       storage_class_name="fast"))
        back = kc.pod_from_k8s(kc.pod_to_k8s(pod))
        assert back.name == pod.name and back.namespace == pod.namespace
        assert back.labels == pod.labels
        assert back.requests() == pod.requests()
        assert back.spec.node_selector == pod.spec.node_selector
        assert back.spec.tolerations == pod.spec.tolerations
        assert len(back.spec.topology_spread_constraints) == 1
        assert back.spec.topology_spread_constraints[0].label_selector \
            == pod.spec.topology_spread_constraints[0].label_selector
        assert back.spec.affinity.pod_anti_affinity.required[0].topology_key \
            == api_labels.LABEL_HOSTNAME
        assert [hp.port for hp in back.spec.host_ports] == [8080]
        assert back.spec.volumes[0].claim_name == "data"
        assert back.spec.volumes[1].ephemeral
        assert back.spec.volumes[1].storage_class_name == "fast"

    def test_daemonset_owner_detected(self):
        d = kc.pod_to_k8s(make_pod(cpu="100m"))
        d["metadata"]["ownerReferences"] = [{"kind": "DaemonSet",
                                             "name": "ds", "uid": "u1"}]
        assert kc.pod_from_k8s(d).is_daemonset_pod


class TestNodeAndClaimRoundTrip:
    def test_node(self):
        alloc = res.parse_list({"cpu": "4", "memory": "8Gi"})
        n = Node(metadata=ObjectMeta(name="n1", namespace="",
                                     labels={api_labels.LABEL_HOSTNAME: "n1"}),
                 spec=NodeSpec(provider_id="kwok://n1",
                               taints=[Taint(key="k", effect="NoSchedule")]),
                 status=NodeStatus(capacity=dict(alloc), allocatable=alloc))
        back = kc.node_from_k8s(kc.node_to_k8s(n))
        assert back.spec.provider_id == "kwok://n1"
        assert back.spec.taints == n.spec.taints
        assert back.status.allocatable == alloc

    def test_nodeclaim(self):
        nc = NodeClaim(
            metadata=ObjectMeta(name="nc1", namespace="",
                                labels={api_labels.NODEPOOL_LABEL_KEY:
                                        "default"}),
            spec=NodeClaimSpec(
                requirements=[_SelectorReq(api_labels.LABEL_ARCH, "In",
                                           ("amd64",)),
                              _SelectorReq(api_labels.LABEL_INSTANCE_TYPE,
                                           "In", ("a", "b"), 2)],
                resources_requests=res.parse_list({"cpu": "2"}),
                taints=[Taint(key="t", effect="NoSchedule")],
                expire_after=720 * 3600.0,
                termination_grace_period=300.0))
        nc.status.provider_id = "kwok://x"
        nc.conditions.set_true(COND_LAUNCHED, now=123.0)
        back = kc.nodeclaim_from_k8s(kc.nodeclaim_to_k8s(nc))
        assert back.spec.requirements[0].key == api_labels.LABEL_ARCH
        assert back.spec.requirements[1].min_values == 2
        assert back.spec.resources_requests == nc.spec.resources_requests
        assert back.spec.expire_after == nc.spec.expire_after
        assert back.spec.termination_grace_period == 300.0
        assert back.status.provider_id == "kwok://x"
        assert back.conditions.is_true(COND_LAUNCHED)

    def test_nodepool(self):
        pool = make_nodepool(name="p1", limits={"cpu": "100"}, weight=7,
                             taints=[Taint(key="k", effect="NoSchedule")])
        pool.spec.disruption.budgets = [
            Budget(nodes="10%", schedule="0 9 * * 1", duration=3600.0)]
        back = kc.nodepool_from_k8s(kc.nodepool_to_k8s(pool))
        assert back.name == "p1"
        assert back.spec.limits == pool.spec.limits
        assert back.spec.weight == 7
        assert back.spec.template.spec.taints == pool.spec.template.spec.taints
        b = back.spec.disruption.budgets[0]
        assert (b.nodes, b.schedule, b.duration) == ("10%", "0 9 * * 1",
                                                     3600.0)


class TestEnvtest:
    """The adapter + codec + admission against a LIVE HTTP apiserver in the
    default suite (kube/envtest.py — the reference's envtest strategy,
    pkg/test/environment.go:41-49). No gate, no cluster."""

    @pytest.fixture
    def env_store(self):
        from karpenter_tpu.kube.apiserver import KubeApiStore
        from karpenter_tpu.kube.envtest import EnvtestServer
        from karpenter_tpu.utils.clock import Clock
        with EnvtestServer() as srv:
            store = KubeApiStore(srv.url, clock=Clock())
            store._envtest = srv
            yield store
            store.stop_watches()

    def test_crud_round_trip_over_http(self, env_store):
        pod = make_pod(cpu="250m", name="envtest-pod", labels={"app": "x"})
        env_store.create(pod)
        live = env_store.get(Pod, "envtest-pod", "default")
        assert live is not None and live.labels == {"app": "x"}
        assert live.metadata.uid and live.metadata.resource_version
        live.spec.node_name = "some-node"
        env_store.update(live)
        again = env_store.get(Pod, "envtest-pod", "default")
        assert again.spec.node_name == "some-node"
        env_store.delete(again)
        assert env_store.get(Pod, "envtest-pod", "default") is None

    def test_stale_resource_version_conflicts(self, env_store):
        from karpenter_tpu.kube.store import ConflictError
        node = Node(metadata=ObjectMeta(name="rv-node", namespace=""),
                    spec=NodeSpec(provider_id="t://rv"))
        env_store.create(node)
        first = env_store.get(Node, "rv-node")
        env_store.update(env_store.get(Node, "rv-node"))  # bumps RV
        first.metadata.labels["stale"] = "write"
        with pytest.raises(ConflictError):
            env_store.update(first)

    def test_finalizer_gates_deletion(self, env_store):
        node = Node(metadata=ObjectMeta(name="fin-node", namespace="",
                                        finalizers=["karpenter.sh/test"]),
                    spec=NodeSpec(provider_id="t://fin"))
        env_store.create(node)
        env_store.delete(env_store.get(Node, "fin-node"))
        live = env_store.get(Node, "fin-node")
        assert live is not None, "finalized object removed prematurely"
        assert live.metadata.deletion_timestamp is not None
        env_store.remove_finalizer(live, "karpenter.sh/test")
        assert env_store.get(Node, "fin-node") is None

    def test_admission_rejects_over_http(self, env_store):
        from karpenter_tpu.kube.store import InvalidError
        bad = make_nodepool(name="bad-pool")
        bad.spec.disruption.budgets = [Budget(nodes="150%")]
        with pytest.raises(InvalidError):
            env_store.create(bad)
        assert env_store.get(NodePool, "bad-pool") is None

    def test_recorder_sink_posts_real_events(self, env_store):
        from karpenter_tpu.events.catalog import evict_pod
        from karpenter_tpu.events.recorder import Recorder
        from karpenter_tpu.utils.clock import FakeClock
        rec = Recorder(FakeClock(), sink=env_store.post_event)
        rec.publish(evict_pod(make_pod(name="evicted-pod")))
        [ev] = env_store._envtest.state.events
        assert ev["reason"] == "Evicted"
        assert ev["involvedObject"]["name"] == "evicted-pod"
        assert ev["source"] == {"component": "karpenter"}

    def test_watch_streams_store_changes(self, env_store):
        import time as _time
        seen = []
        env_store.watch(seen.append)
        env_store.start_watches(kinds=(Pod,))
        env_store.create(make_pod(cpu="100m", name="watched-pod"))
        deadline = _time.time() + 10
        while _time.time() < deadline:
            env_store.pump_events()
            if any(e.obj.metadata.name == "watched-pod" for e in seen):
                break
            _time.sleep(0.05)
        assert any(e.obj.metadata.name == "watched-pod" for e in seen), \
            "watch stream never delivered the pod"

    def test_operator_provision_loop_e2e(self, env_store):
        """The full loop against the live wire: NodePool + pending Pod in,
        NodeClaim launched, Node fabricated, pod bound — the gated
        TestLiveApiserver scenario, un-gated (round 5 item 7)."""
        import time as _time

        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import Manager
        from karpenter_tpu.controllers.nodeclaim_lifecycle import \
            NodeClaimLifecycle
        from karpenter_tpu.provisioning.provisioner import (Binder,
                                                            PodTrigger,
                                                            Provisioner)
        from karpenter_tpu.state.cluster import Cluster
        from karpenter_tpu.state.informers import wire_informers
        from karpenter_tpu.utils.clock import Clock

        store = env_store
        clock = Clock()
        cluster = Cluster(store, clock)
        wire_informers(store, cluster)
        provider = KwokCloudProvider(store=store)
        mgr = Manager(store, clock)
        provisioner = Provisioner(store, cluster, provider, clock)
        mgr.register(provisioner, PodTrigger(provisioner),
                     Binder(store, cluster, provisioner),
                     NodeClaimLifecycle(store, cluster, provider, clock))
        store.start_watches()
        store.apply(make_nodepool(name="envtest-default"))
        pod = make_pod(cpu="100m", name="envtest-e2e-pod")
        store.apply(pod)
        deadline = _time.time() + 60
        bound = None
        while _time.time() < deadline:
            store.pump_events()
            mgr.run_until_quiet()
            live = store.get(Pod, pod.metadata.name, pod.metadata.namespace)
            if live is not None and live.spec.node_name:
                bound = live
                break
            _time.sleep(0.2)
        assert bound is not None, "pod never bound through the apiserver"
        claims = store.list(NodeClaim)
        assert any(c.metadata.labels.get(api_labels.NODEPOOL_LABEL_KEY)
                   == "envtest-default" for c in claims)
        assert store.list(Node), "no node materialized through the wire"


_E2E = os.environ.get("KARPENTER_TPU_KUBE_E2E", "")


@pytest.mark.skipif(not _E2E, reason="set KARPENTER_TPU_KUBE_E2E=1 with a "
                    "reachable cluster (KUBECONFIG) to run")
class TestLiveApiserver:
    """Provision one NodeClaim through a real/kwok apiserver: NodePool +
    pending Pod in, NodeClaim + fabricated Node out, pod bound."""

    def test_provision_one_nodeclaim(self, tmp_path):
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import Manager
        from karpenter_tpu.controllers.nodeclaim_lifecycle import \
            NodeClaimLifecycle
        from karpenter_tpu.kube.apiserver import KubeApiStore
        from karpenter_tpu.provisioning.provisioner import (Binder,
                                                            PodTrigger,
                                                            Provisioner)
        from karpenter_tpu.state.cluster import Cluster
        from karpenter_tpu.state.informers import wire_informers
        from karpenter_tpu.utils.clock import Clock

        store = KubeApiStore.from_kubeconfig()
        self._ensure_crds(store)
        clock = Clock()
        cluster = Cluster(store, clock)
        wire_informers(store, cluster)
        provider = KwokCloudProvider(store=store)
        mgr = Manager(store, clock)
        provisioner = Provisioner(store, cluster, provider, clock)
        mgr.register(provisioner, PodTrigger(provisioner),
                     Binder(store, cluster, provisioner),
                     NodeClaimLifecycle(store, cluster, provider, clock))
        store.start_watches()
        try:
            store.apply(make_nodepool(name="e2e-default"))
            pod = make_pod(cpu="100m", name="e2e-pod")
            store.apply(pod)
            import time as _time
            deadline = _time.time() + 120
            bound = None
            while _time.time() < deadline:
                store.pump_events()
                mgr.run_until_quiet()
                live = store.get(Pod, pod.name, pod.namespace)
                if live is not None and live.spec.node_name:
                    bound = live
                    break
                _time.sleep(1.0)
            assert bound is not None, "pod never bound through the apiserver"
            claims = store.list(NodeClaim)
            assert any(c.metadata.labels.get(api_labels.NODEPOOL_LABEL_KEY)
                       == "e2e-default" for c in claims)
        finally:
            store.stop_watches()

    def _ensure_crds(self, store) -> None:
        """Apply the generated CRDs through the apiextensions API."""
        import glob
        import json as _json
        import urllib.error

        import yaml
        crd_dir = os.path.join(os.path.dirname(__file__), "..",
                               "karpenter_tpu", "api", "crds")
        for path in sorted(glob.glob(os.path.join(crd_dir, "*.yaml"))):
            with open(path) as f:
                body = yaml.safe_load(f)
            url = (f"{store.base_url}/apis/apiextensions.k8s.io/v1/"
                   "customresourcedefinitions")
            try:
                store._request("POST", url, body)
            except urllib.error.HTTPError as e:
                if e.code != 409:
                    raise


def test_node_conditions_round_trip():
    """Kubelet conditions must survive the codec: NotReady budget accounting
    and repair policies read them."""
    from karpenter_tpu.api.objects import Node, NodeStatus, ObjectMeta
    from karpenter_tpu.kube.k8s_codec import node_from_k8s, node_to_k8s
    n = Node(metadata=ObjectMeta(name="n1", namespace=""),
             status=NodeStatus(conditions=[
                 {"type": "Ready", "status": "False",
                  "last_transition_time": 12345.0}]))
    out = node_from_k8s(node_to_k8s(n))
    [cond] = out.status.conditions
    assert cond["type"] == "Ready" and cond["status"] == "False"
    assert cond["last_transition_time"] == 12345.0
