"""Device and memory truth (ISSUE 12 tentpole b): the dispatch/execute
split with per-executable attribution, the XLA memory watermark gauges,
and the promoted jax.profiler facility (obs/profile.py + /debug/profile +
`python -m karpenter_tpu.obs profile`)."""

import os
import urllib.request

import pytest

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.obs.device import DEVICE_TIME
from karpenter_tpu.obs.profile import PROFILER, ProfileError, Profiler
from karpenter_tpu.obs.tracer import TRACER
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import make_nodepool, make_pods


def _solve(n=12):
    ts = TensorScheduler([make_nodepool(name="default")],
                         {"default": construct_instance_types()[:n]})
    ts.solve(make_pods(8, cpu="250m"))
    assert ts.fallback_reason == ""
    return ts


class TestDeviceTimeAttribution:
    def test_solve_records_per_executable_stats(self):
        DEVICE_TIME.clear()
        _solve()
        snap = DEVICE_TIME.snapshot()
        assert snap, "no executable registered by the solve"
        st = snap[0]
        assert st["executable"].startswith("x")
        assert st["kind"] == "single"
        assert st["dispatches"] >= 1
        assert st["dispatch_seconds"] >= 0.0
        assert st["device_seconds"] >= 0.0
        assert st["peak_bytes"] > 0, "memory_analysis produced no peak"
        assert st["shapes"], "no arg-shape summary"

    def test_spans_split_dispatch_from_execute(self):
        _solve()
        trace = TRACER.last()
        names = [s.name for s in trace.spans]
        assert "device.dispatch" in names
        assert "device.execute" in names
        dispatch = next(s for s in trace.spans
                        if s.name == "device.dispatch")
        execute = next(s for s in trace.spans if s.name == "device.execute")
        # both carry the executable label and nest under precompute
        assert dispatch.attrs["executable"] == execute.attrs["executable"]
        assert dispatch.attrs["compile_cache"] in ("hit", "miss")

    def test_memory_watermark_gauges_set(self):
        from karpenter_tpu.metrics.registry import DEVICE_MEMORY_PEAK
        DEVICE_TIME.clear()
        _solve()
        marks = DEVICE_TIME.watermarks()
        assert marks, "no per-device watermark recorded"
        for dev, peak in marks.items():
            assert peak > 0
            assert DEVICE_MEMORY_PEAK.value({"device": dev}) == float(peak)

    def test_watermark_is_monotonic_max(self):
        DEVICE_TIME.clear()
        _solve(n=12)
        first = dict(DEVICE_TIME.watermarks())
        _solve(n=24)  # a bigger catalog compiles a bigger program
        second = DEVICE_TIME.watermarks()
        for dev in first:
            assert second.get(dev, 0) >= first[dev]

    def test_disabled_tracer_records_nothing_and_stays_async(self):
        DEVICE_TIME.clear()
        saved = TRACER.enabled
        try:
            TRACER.enabled = False
            _solve()
        finally:
            TRACER.enabled = saved
        assert DEVICE_TIME.snapshot() == []

    def test_metrics_families_move(self):
        from karpenter_tpu.metrics.registry import (DEVICE_DISPATCHES,
                                                    DEVICE_EXECUTE_SECONDS)
        DEVICE_TIME.clear()
        _solve()
        st = DEVICE_TIME.snapshot()[0]
        labels = {"executable": st["executable"]}
        assert DEVICE_DISPATCHES.value(labels) >= 1
        assert DEVICE_EXECUTE_SECONDS.value(labels) >= 0.0


class TestProfiler:
    def test_start_without_sanctioned_dir_rejected(self, monkeypatch):
        monkeypatch.delenv("KARPENTER_PROFILE_DIR", raising=False)
        p = Profiler()
        with pytest.raises(ProfileError, match="KARPENTER_PROFILE_DIR"):
            p.start()

    def test_start_stop_lifecycle(self, tmp_path):
        from karpenter_tpu.metrics.registry import PROFILE_ACTIVE
        p = Profiler()
        out = p.start(str(tmp_path / "prof"))
        try:
            assert p.active and out == str(tmp_path / "prof")
            assert PROFILE_ACTIVE.value() == 1.0
            with pytest.raises(ProfileError, match="already running"):
                p.start(str(tmp_path / "other"))
        finally:
            stopped = p.stop()
        assert stopped == out and not p.active
        assert PROFILE_ACTIVE.value() == 0.0
        assert os.path.isdir(out)
        with pytest.raises(ProfileError, match="no device profile"):
            p.stop()

    def test_env_dir_is_the_default(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KARPENTER_PROFILE_DIR", str(tmp_path / "env"))
        p = Profiler()
        assert p.start() == str(tmp_path / "env")
        p.stop()

    def test_pass_scope_noop_while_session_active(self, tmp_path):
        p = Profiler()
        p.start(str(tmp_path / "ses"))
        try:
            # the provisioner's per-pass hook must not crash into
            # jax.profiler's single-session assertion
            with p.pass_scope(str(tmp_path / "pass")):
                pass
            assert not os.path.exists(str(tmp_path / "pass"))
        finally:
            p.stop()

    def test_debug_profile_device_start_stop(self, tmp_path, monkeypatch):
        from karpenter_tpu.operator.server import ServingGroup
        monkeypatch.setenv("KARPENTER_PROFILE_DIR", str(tmp_path / "ep"))
        group = ServingGroup(0, 0, profiling=True).start()
        base = f"http://127.0.0.1:{group.metrics_port}/debug/profile"
        try:
            with urllib.request.urlopen(f"{base}?device=start",
                                        timeout=10) as resp:
                body = resp.read().decode()
            assert "started" in body and str(tmp_path / "ep") in body
            assert PROFILER.active
            # double start: 409, not a crash
            req = urllib.request.Request(f"{base}?device=start")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 409
            with urllib.request.urlopen(f"{base}?device=stop",
                                        timeout=10) as resp:
                assert "stopped" in resp.read().decode()
            assert not PROFILER.active
        finally:
            if PROFILER.active:
                PROFILER.stop()
            group.stop()

    def test_obs_profile_cli(self, tmp_path, monkeypatch):
        from karpenter_tpu.obs.__main__ import main as obs_main
        from karpenter_tpu.operator.server import ServingGroup
        monkeypatch.setenv("KARPENTER_PROFILE_DIR", str(tmp_path / "cli"))
        group = ServingGroup(0, 0, profiling=True).start()
        try:
            rc = obs_main(["profile",
                           "--url", f"http://127.0.0.1:{group.metrics_port}",
                           "--seconds", "0.05"])
            assert rc == 0
            assert not PROFILER.active
            assert os.path.isdir(str(tmp_path / "cli"))
        finally:
            if PROFILER.active:
                PROFILER.stop()
            group.stop()
