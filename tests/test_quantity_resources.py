from karpenter_tpu.utils import quantity as q
from karpenter_tpu.utils import resources as res
from karpenter_tpu.api.objects import Pod


def test_parse_plain():
    assert q.parse("1") == 1000
    assert q.parse(2) == 2000
    assert q.parse("100m") == 100
    assert q.parse("1500m") == 1500
    assert q.parse("0") == 0


def test_parse_binary_suffixes():
    assert q.parse("1Ki") == 1024 * 1000
    assert q.parse("1Gi") == 1024**3 * 1000
    assert q.parse("20Gi") == 20 * 1024**3 * 1000


def test_parse_decimal_suffixes():
    assert q.parse("1k") == 1000 * 1000
    assert q.parse("1M") == 10**6 * 1000
    assert q.parse("1.5") == 1500


def test_parse_fractional_exact():
    # 3 x 100m must exactly equal 300m (float would drift)
    total = sum([q.parse("100m")] * 3)
    assert total == q.parse("300m")


def test_format_roundtrip():
    assert q.format_milli(q.parse("1500m")) == "1500m"
    assert q.format_milli(q.parse("2")) == "2"


def test_fits():
    reqs = res.parse_list({"cpu": "1", "memory": "1Gi"})
    avail = res.parse_list({"cpu": "2", "memory": "2Gi", "pods": "10"})
    assert res.fits(reqs, avail)
    assert not res.fits(res.parse_list({"cpu": "3"}), avail)
    # zero-valued requests fit even when resource missing from available
    assert res.fits({"gpu": 0}, avail)
    # exact boundary fits
    assert res.fits(res.parse_list({"cpu": "2"}), avail)
    assert not res.fits({"cpu": 2001}, avail)


def test_subtract_and_exceeds():
    a = res.parse_list({"cpu": "4"})
    b = res.parse_list({"cpu": "1", "memory": "1Gi"})
    d = res.subtract(a, b)
    assert d["cpu"] == 3000
    assert d["memory"] < 0
    assert res.exceeds({"cpu": 5000}, res.parse_list({"cpu": "4"})) == ["cpu"]
    assert res.exceeds({"cpu": 4000}, res.parse_list({"cpu": "4"})) == []


def test_pod_requests_includes_pod_slot():
    p = Pod(container_requests=[res.parse_list({"cpu": "100m"}), res.parse_list({"cpu": "200m"})])
    r = p.requests()
    assert r["cpu"] == 300
    assert r[res.PODS] == 1000


def test_pod_requests_init_containers_max():
    p = Pod(
        container_requests=[res.parse_list({"cpu": "100m"})],
        init_container_requests=[res.parse_list({"cpu": "1"})],
    )
    assert p.requests()["cpu"] == 1000


class TestSidecarInterleavings:
    """utils/resources/suite_test.go:344-530: element-wise max over
    interleaved init/sidecar sequences, including per-resource divergence."""

    GI = 1024 ** 3 * 1000  # memory milliunits per Gi

    def _pod(self, container, inits):
        from karpenter_tpu.api.objects import Pod
        p = Pod()
        p.container_requests = [
            {"cpu": container[0] * 1000, "memory": container[1] * self.GI}]
        p.init_container_requests = [
            ({"cpu": c * 1000, "memory": m * self.GI}, True) if sidecar
            else {"cpu": c * 1000, "memory": m * self.GI}
            for c, m, sidecar in inits]
        return p

    def test_interspersed_sidecars_and_inits(self):
        """suite_test.go:344-424: containers 3/3Gi, inits
        2,s1,3,1,s5,1,1,s1,2 -> 10 cpu / 10Gi."""
        p = self._pod((3, 3), [
            (2, 2, False), (1, 1, True), (3, 3, False), (1, 1, False),
            (5, 5, True), (1, 1, False), (1, 1, False), (1, 1, True),
            (2, 1, False)])
        r = p.requests()
        assert r["cpu"] == 10_000
        assert r["memory"] == 10 * self.GI

    def test_first_init_exceeds_cpu_but_not_memory(self):
        """suite_test.go:425-463: containers 3/3Gi, init 25/4Gi, sidecars
        1/1Gi + 5/5Gi -> 25 cpu / 9Gi (per-resource max diverges)."""
        p = self._pod((3, 3), [
            (25, 4, False), (1, 1, True), (5, 5, True)])
        r = p.requests()
        assert r["cpu"] == 25_000
        assert r["memory"] == 9 * self.GI

    def test_first_init_exceeds_memory_but_not_cpu(self):
        """suite_test.go:464-502: containers 3/3Gi, init 4/25Gi, sidecars
        1/1Gi + 5/5Gi -> 9 cpu / 25Gi."""
        p = self._pod((3, 3), [
            (4, 25, False), (1, 1, True), (5, 5, True)])
        r = p.requests()
        assert r["cpu"] == 9_000
        assert r["memory"] == 25 * self.GI

    def test_init_after_sidecar_exceeds_cpu_only(self):
        """suite_test.go:503-530: containers 2/4Gi, sidecar 4/2Gi, init
        10/2Gi -> 14 cpu / 6Gi."""
        p = self._pod((2, 4), [(4, 2, True), (10, 2, False)])
        r = p.requests()
        assert r["cpu"] == 14_000
        assert r["memory"] == 6 * self.GI
