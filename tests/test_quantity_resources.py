from karpenter_tpu.utils import quantity as q
from karpenter_tpu.utils import resources as res
from karpenter_tpu.api.objects import Pod


def test_parse_plain():
    assert q.parse("1") == 1000
    assert q.parse(2) == 2000
    assert q.parse("100m") == 100
    assert q.parse("1500m") == 1500
    assert q.parse("0") == 0


def test_parse_binary_suffixes():
    assert q.parse("1Ki") == 1024 * 1000
    assert q.parse("1Gi") == 1024**3 * 1000
    assert q.parse("20Gi") == 20 * 1024**3 * 1000


def test_parse_decimal_suffixes():
    assert q.parse("1k") == 1000 * 1000
    assert q.parse("1M") == 10**6 * 1000
    assert q.parse("1.5") == 1500


def test_parse_fractional_exact():
    # 3 x 100m must exactly equal 300m (float would drift)
    total = sum([q.parse("100m")] * 3)
    assert total == q.parse("300m")


def test_format_roundtrip():
    assert q.format_milli(q.parse("1500m")) == "1500m"
    assert q.format_milli(q.parse("2")) == "2"


def test_fits():
    reqs = res.parse_list({"cpu": "1", "memory": "1Gi"})
    avail = res.parse_list({"cpu": "2", "memory": "2Gi", "pods": "10"})
    assert res.fits(reqs, avail)
    assert not res.fits(res.parse_list({"cpu": "3"}), avail)
    # zero-valued requests fit even when resource missing from available
    assert res.fits({"gpu": 0}, avail)
    # exact boundary fits
    assert res.fits(res.parse_list({"cpu": "2"}), avail)
    assert not res.fits({"cpu": 2001}, avail)


def test_subtract_and_exceeds():
    a = res.parse_list({"cpu": "4"})
    b = res.parse_list({"cpu": "1", "memory": "1Gi"})
    d = res.subtract(a, b)
    assert d["cpu"] == 3000
    assert d["memory"] < 0
    assert res.exceeds({"cpu": 5000}, res.parse_list({"cpu": "4"})) == ["cpu"]
    assert res.exceeds({"cpu": 4000}, res.parse_list({"cpu": "4"})) == []


def test_pod_requests_includes_pod_slot():
    p = Pod(container_requests=[res.parse_list({"cpu": "100m"}), res.parse_list({"cpu": "200m"})])
    r = p.requests()
    assert r["cpu"] == 300
    assert r[res.PODS] == 1000


def test_pod_requests_init_containers_max():
    p = Pod(
        container_requests=[res.parse_list({"cpu": "100m"})],
        init_container_requests=[res.parse_list({"cpu": "1"})],
    )
    assert p.requests()["cpu"] == 1000
