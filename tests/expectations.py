"""Expectations-style test harness.

The analog of /root/reference/pkg/test/expectations/expectations.go (736
LoC) + pkg/test/nodeclaim.go NodeClaimAndNode: fabricate NodeClaim+Node
pairs DIRECTLY — with any instance type, capacity type, zone, and
allocatable — instead of provisioning them through pods, then drive the
controller roster deterministically. This is what makes porting the
reference's 4,000-LoC scenario suites cheap: a consolidation scenario is
three lines of setup, not a provisioning round-trip.

The environment registers the full operator roster (informers + lifecycle +
termination + disruption + provisioner) around a shared recorder, exactly
like operator.py, so fabricated objects flow through the same machinery the
judge's e2e path uses; fabricated claims carry complete conditions/labels so
lifecycle reconciles are no-ops until something real happens to them.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import (COND_CONSOLIDATABLE, COND_DRIFTED,
                                         COND_INITIALIZED, COND_LAUNCHED,
                                         COND_REGISTERED, NodeClaim,
                                         NodeClaimSpec, NodeClaimStatus)
from karpenter_tpu.api.nodepool import NODEPOOL_HASH_VERSION, Budget, NodePool
from karpenter_tpu.api.objects import (LabelSelector, Node, NodeSpec, Taint,
                                       NodeStatus, ObjectMeta, Pod)
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.cloudprovider.kwok import (KwokCloudProvider,
                                              construct_instance_types)
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_disruption import \
    NodeClaimDisruptionMarker
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.disruption.controller import (DisruptionController,
                                                 OrchestrationQueue)
from karpenter_tpu.disruption.validation import CONSOLIDATION_TTL_SECONDS
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import (Binder, PodTrigger,
                                                    Provisioner)
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod

_seq = itertools.count(1)

OD = api_labels.CAPACITY_TYPE_ON_DEMAND
SPOT = api_labels.CAPACITY_TYPE_SPOT


class MinValuesReq:
    """NodeSelectorRequirementWithMinValues analog for pool templates
    (NodeSelectorRequirement is frozen and has no min_values field; template
    ingestion duck-types via getattr(req, 'min_values', None))."""

    def __init__(self, key: str, operator: str, values=(), min_values=None):
        self.key = key
        self.operator = operator
        self.values = tuple(values)
        self.min_values = min_values


class Env:
    """Everything a scenario needs, wired like the operator."""

    def __init__(self, spot_to_spot: bool = False, clock=None, store=None,
                 provider=None):
        """`store`/`provider` are injectable so chaos scenarios can swap in
        the fault-injecting variants (kube/chaos.ChaosStore,
        cloudprovider/chaos.ChaosCloudProvider) without re-wiring the
        roster; a custom provider may be a factory taking the store."""
        self.clock = clock or FakeClock()
        self.store = store if store is not None else Store(self.clock)
        self.cluster = Cluster(self.store, self.clock)
        wire_informers(self.store, self.cluster)
        self.provider = (provider(self.store) if callable(provider)
                         else provider) if provider is not None \
            else KwokCloudProvider(store=self.store)
        # capacity-failure feedback registry, wired like the operator:
        # lifecycle ICEs mark it, both solvers mask it, providers that
        # support it skip cached-dry offerings at create
        from karpenter_tpu.state.unavailable import UnavailableOfferings
        self.unavailable = UnavailableOfferings(clock=self.clock)
        if hasattr(self.provider, "unavailable"):
            self.provider.unavailable = self.unavailable
        self.recorder = Recorder(self.clock)
        self.mgr = Manager(self.store, self.clock, recorder=self.recorder)
        # crash isolation would silently absorb a regressed reconciler that
        # raises (pre-isolation it crashed the test); settle() compensates
        # by asserting no reconcile errors fired unless a scenario opts in
        self.allow_reconcile_errors = False
        self._reconcile_errors_mark = self._reconcile_errors_total()
        self.provisioner = Provisioner(self.store, self.cluster,
                                       self.provider, self.clock,
                                       recorder=self.recorder,
                                       unavailable=self.unavailable)
        self.queue = OrchestrationQueue(self.store, self.cluster, self.clock,
                                        recorder=self.recorder)
        self.disruption = DisruptionController(
            self.store, self.cluster, self.provisioner, self.queue,
            self.clock, spot_to_spot_enabled=spot_to_spot,
            recorder=self.recorder)
        self.mgr.register(
            self.provisioner, PodTrigger(self.provisioner),
            Binder(self.store, self.cluster, self.provisioner),
            NodeClaimLifecycle(self.store, self.cluster, self.provider,
                               self.clock, recorder=self.recorder,
                               unavailable=self.unavailable,
                               trigger=self.provisioner.trigger),
            NodeClaimDisruptionMarker(self.store, self.cluster, self.provider,
                                      self.clock),
            NodeTermination(self.store, self.cluster, self.clock,
                            cloud_provider=self.provider,
                            recorder=self.recorder))

    # -- drive helpers ------------------------------------------------------

    @staticmethod
    def _reconcile_errors_total() -> float:
        from karpenter_tpu.metrics.registry import RECONCILE_ERRORS
        return sum(RECONCILE_ERRORS._values.values())

    def _assert_no_reconcile_errors(self) -> None:
        if self.allow_reconcile_errors:
            return
        total = self._reconcile_errors_total()
        assert total == self._reconcile_errors_mark, (
            "a reconciler raised during the scenario (crash isolation "
            "absorbed it — set env.allow_reconcile_errors = True if "
            "injected faults are the point of the test)")

    def settle(self, rounds: int = 4) -> None:
        for _ in range(rounds):
            self.mgr.run_until_quiet()
            self.clock.step(1.1)
        assert self.mgr.run_until_quiet(), "manager did not quiesce"
        self._assert_no_reconcile_errors()

    def reconcile_disruption(self) -> None:
        """One full disruption decision: the compute pass, the
        consolidation-TTL wait (validation.go:83-215), and the validated
        execution, then the orchestration queue."""
        self.disruption.reconcile()
        if self.disruption.pending is not None:
            self.clock.step(CONSOLIDATION_TTL_SECONDS + 0.1)
            self.disruption.reconcile()
        self.queue.reconcile()
        assert self.mgr.run_until_quiet(), "manager did not quiesce"
        self._assert_no_reconcile_errors()

    def run_disruption(self, rounds: int = 4) -> None:
        for _ in range(rounds):
            self.reconcile_disruption()
            self.settle(rounds=2)
            self.clock.step(8)

    # -- assertions ---------------------------------------------------------

    def node_exists(self, name: str) -> bool:
        return self.store.get(Node, name) is not None

    def nodeclaim_exists(self, name: str) -> bool:
        return self.store.get(NodeClaim, name) is not None

    def nodes(self) -> List[Node]:
        return self.store.list(Node)

    def nodeclaims(self) -> List[NodeClaim]:
        return self.store.list(NodeClaim)

    def events(self, reason: str) -> list:
        return [e for e in self.recorder.events if e.reason == reason]


def make_env(*nodepools, spot_to_spot: bool = False) -> Env:
    """Environment with the given NodePools applied. With no pools, applies
    a default 100%-budget WhenEmptyOrUnderutilized pool (the
    consolidation_test.go:60-71 BeforeEach shape)."""
    env = Env(spot_to_spot=spot_to_spot)
    if not nodepools:
        nodepools = (consolidation_nodepool(),)
    for np in nodepools:
        env.store.create(np)
    return env


def consolidation_nodepool(name: str = "default", budgets=("100%",),
                           consolidate_after: Optional[float] = 0.0):
    """consolidation_test.go:60-71: WhenEmptyOrUnderutilized, 0s
    consolidateAfter, explicit budgets."""
    pool = make_nodepool(name=name)
    pool.spec.disruption.budgets = [Budget(nodes=b) for b in budgets]
    pool.spec.disruption.consolidate_after = consolidate_after
    return pool


# -- catalog helpers ---------------------------------------------------------

_CATALOG = None


def catalog() -> list:
    global _CATALOG
    if _CATALOG is None:
        _CATALOG = construct_instance_types()
    return _CATALOG


def _min_price(it, capacity_type: Optional[str] = None) -> float:
    offs = [o for o in it.offerings
            if capacity_type is None or o.capacity_type == capacity_type]
    return min(o.price for o in offs) if offs else float("inf")


def sorted_by_price(capacity_type: Optional[str] = None) -> list:
    return sorted(catalog(), key=lambda it: (_min_price(it, capacity_type),
                                             it.name))


def cheapest_instance(capacity_type: Optional[str] = None):
    return sorted_by_price(capacity_type)[0]


def most_expensive_instance(capacity_type: Optional[str] = None):
    return sorted_by_price(capacity_type)[-1]


def instance_named(name: str):
    return next(it for it in catalog() if it.name == name)


# -- object fabrication ------------------------------------------------------

def make_nodeclaim_and_node(
        env: Env, nodepool: str = "default", instance_type=None,
        capacity_type: str = OD, zone: str = "test-zone-a",
        allocatable: Optional[dict] = None, consolidatable: bool = True,
        drifted: bool = False, initialized: bool = True,
        annotations: Optional[dict] = None, expire_after: Optional[float] = None,
        name: Optional[str] = None) -> Tuple[NodeClaim, Node]:
    """test.NodeClaimAndNode (pkg/test/nodeclaim.go:65-68): a fully-formed
    claim + linked node, registered with the cloud provider so GC leaves
    them alone, conditions/labels complete so lifecycle reconciles no-op."""
    if instance_type is None:
        instance_type = most_expensive_instance(capacity_type)
    it_name = instance_type if isinstance(instance_type, str) \
        else instance_type.name
    n = next(_seq)
    name = name or f"fab-{n:04d}"
    pid = f"fab://{name}"
    alloc = res.parse_list(allocatable or {"cpu": "32", "memory": "128Gi",
                                           "pods": "110"})
    labels = {
        api_labels.NODEPOOL_LABEL_KEY: nodepool,
        api_labels.LABEL_INSTANCE_TYPE: it_name,
        api_labels.CAPACITY_TYPE_LABEL_KEY: capacity_type,
        api_labels.LABEL_TOPOLOGY_ZONE: zone,
        api_labels.LABEL_HOSTNAME: name,
    }
    # stamp the owning pool's hash (what launch does) or the drift marker
    # immediately flags the fabricated claim Drifted and the Drift method
    # swallows every scenario before consolidation runs
    nc_annotations = dict(annotations or {})
    pool = env.store.get(NodePool, nodepool)
    if pool is not None and not drifted:
        nc_annotations.setdefault(api_labels.NODEPOOL_HASH_ANNOTATION_KEY,
                                  pool.static_hash())
        nc_annotations.setdefault(
            api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY,
            NODEPOOL_HASH_VERSION)
    # initialized=False must SURVIVE the roster: the lifecycle controller
    # would stamp the initialized label on the next reconcile, so an
    # uncleared startup taint holds initialization off (initialization.go
    # requires startup taints gone)
    startup_taints = [] if initialized else [
        Taint(key="fab.test/uninitialized", value="true")]
    nc = NodeClaim(
        metadata=ObjectMeta(name=name, labels=dict(labels),
                            annotations=nc_annotations),
        spec=NodeClaimSpec(expire_after=expire_after,
                           startup_taints=list(startup_taints)),
        status=NodeClaimStatus(provider_id=pid, node_name=name,
                               capacity=dict(alloc),
                               allocatable=dict(alloc)))
    now = env.clock.now()
    nc.conditions.set_true(COND_LAUNCHED, reason="Launched", now=now)
    nc.conditions.set_true(COND_REGISTERED, reason="Registered", now=now)
    if initialized:
        nc.conditions.set_true(COND_INITIALIZED, reason="Initialized", now=now)
    if consolidatable:
        nc.conditions.set_true(COND_CONSOLIDATABLE, reason="Consolidatable",
                               now=now)
    if drifted:
        nc.conditions.set_true(COND_DRIFTED, reason="Drifted", now=now)
    node_labels = dict(labels)
    if initialized:
        node_labels[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
    node = Node(
        metadata=ObjectMeta(name=name, labels=node_labels,
                            annotations=dict(annotations or {}),
                            # registration stamps this on real nodes
                            # (lifecycle:173-174); without it a delete
                            # skips the drain entirely
                            finalizers=[api_labels.TERMINATION_FINALIZER]),
        spec=NodeSpec(provider_id=pid, taints=list(startup_taints)),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)))
    env.provider.created[pid] = (nc, node)
    env.store.create(nc)
    env.store.create(node)
    env.mgr.run_until_quiet()
    return nc, node


def bind_pod(env: Env, node: Node, pod: Optional[Pod] = None,
             **pod_kwargs) -> Pod:
    """A running pod bound to the node (ExpectManualBinding analog)."""
    if pod is None:
        pod = make_pod(**pod_kwargs)
    pod.spec.node_name = node.name
    pod.status.phase = "Running"
    env.store.create(pod)
    env.mgr.run_until_quiet()
    return pod


def make_pdb(env: Env, match_labels: Dict[str, str],
             max_unavailable: Optional[str] = None,
             min_available: Optional[str] = None,
             namespace: str = "default",
             name: str = "pdb") -> PodDisruptionBudget:
    pdb = PodDisruptionBudget(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=PDBSpec(selector=LabelSelector(match_labels=dict(match_labels)),
                     max_unavailable=max_unavailable,
                     min_available=min_available))
    env.store.create(pdb)
    env.mgr.run_until_quiet()
    return pdb


def make_replacements_ready(env: Env) -> None:
    """ExpectMakeNewNodeClaimsReady (expectations.go:660-685): stamp every
    launched-but-uninitialized replacement claim initialized so the
    orchestration queue can finish its command."""
    for nc in env.store.list(NodeClaim):
        if not nc.initialized():
            now = env.clock.now()
            nc.conditions.set_true(COND_LAUNCHED, reason="Launched", now=now)
            nc.conditions.set_true(COND_REGISTERED, reason="Registered",
                                   now=now)
            nc.conditions.set_true(COND_INITIALIZED, reason="Initialized",
                                   now=now)
            env.store.update(nc)
    env.mgr.run_until_quiet()
