"""Topology scenario corpus, ported from
/root/reference/pkg/controllers/provisioning/scheduling/topology_test.go
(2,502 LoC) — the spread/affinity families the round-4 topology suite left
thin. Go source ranges cited per test; kernel-expressible shapes run BOTH
paths (tensor + host oracle) through the test_binpack_parity helpers,
kernel-inexpressible keys (capacity-type spread) pin the production
fallback's host-path verdicts.
"""

import collections

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (LabelSelector, NodeSelectorRequirement,
                                       TopologySpreadConstraint)
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import (StaticClusterView, make_nodepool, make_pod, make_pods,
                       make_scheduler, running_on, spread_hostname,
                       spread_zone)
from test_binpack_parity import both, host_solve, tensor_solve

ZONE = api_labels.LABEL_TOPOLOGY_ZONE
HOST = api_labels.LABEL_HOSTNAME


def _its(n=48):
    return kwok.construct_instance_types()[:n]


def zone_counts(results, label_key="app", label_val="demo"):
    """ExpectSkew analog: pods matching the selector per committed zone."""
    out = collections.Counter()
    for nc in results.new_nodeclaims:
        req = nc.requirements.get(ZONE)
        vals = req.values_list() if req is not None else []
        n = sum(1 for p in nc.pods
                if p.metadata.labels.get(label_key) == label_val)
        if n and len(vals) == 1:
            out[vals[0]] += n
    return sorted(out.values())


class TestSpreadBasics:
    def test_unknown_topology_key_fails_that_pod_only(self):
        """topology_test.go:59-76: an unknown topology key never schedules;
        unrelated pods are untouched."""
        its = {"default": _its()}
        pods = [make_pod(cpu="100m", labels={"app": "demo"},
                         spread=[TopologySpreadConstraint(
                             topology_key="unknown", max_skew=1,
                             label_selector=LabelSelector(
                                 match_labels={"app": "demo"}))]),
                make_pod(cpu="100m")]
        ts = TensorScheduler([make_nodepool()], its)
        r = ts.solve(pods)
        assert len(r.pod_errors) == 1
        assert pods[0].uid in r.pod_errors

    @pytest.mark.parametrize("use_expressions", [False, True])
    def test_balance_across_zones(self, use_expressions):
        """:94-127 'should balance pods across zones' (match labels and
        match expressions)."""
        if use_expressions:
            sel = LabelSelector(match_expressions=(
                NodeSelectorRequirement(key="app", operator="In",
                                        values=("demo",)),))
            spread = [TopologySpreadConstraint(
                topology_key=ZONE, max_skew=1, label_selector=sel)]
        else:
            spread = [spread_zone(key="app", value="demo")]
        t, h = both(lambda: make_pods(6, cpu="100m", labels={"app": "demo"},
                                      spread=spread))
        assert not t.pod_errors and not h.pod_errors
        # the kwok catalog spans FOUR zones (a-d): 6 pods balance (2,2,1,1)
        assert zone_counts(t) == zone_counts(h) == [1, 1, 2, 2]

    def test_pool_requirement_subsets_spread_domains(self):
        """:143-158: a pool restricted to two zones spreads over exactly
        those two."""
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            key=ZONE, operator="In",
            values=("test-zone-a", "test-zone-b"))])
        t, h = both(lambda: make_pods(4, cpu="100m", labels={"app": "demo"},
                                      spread=[spread_zone(key="app",
                                                          value="demo")]),
                    nodepools=[pool])
        assert not t.pod_errors and not h.pod_errors
        assert zone_counts(t) == zone_counts(h) == [2, 2]

    def test_pool_label_pins_single_domain(self):
        """:159-173: a pool LABELED into one zone leaves one spread domain —
        everything lands there at skew 0."""
        pool = make_nodepool(labels={ZONE: "test-zone-b"})
        t, h = both(lambda: make_pods(4, cpu="100m", labels={"app": "demo"},
                                      spread=[spread_zone(key="app",
                                                          value="demo")]),
                    nodepools=[pool])
        assert not t.pod_errors and not h.pod_errors
        assert zone_counts(t) == zone_counts(h) == [4]

    def test_spread_across_nodepools_unions_domains(self):
        """:190-217: two pools covering DISJOINT zone sets — the spread
        domains are the union, so pods balance across both pools' zones."""
        pool_a = make_nodepool(name="pool-a", requirements=[
            NodeSelectorRequirement(key=ZONE, operator="In",
                                    values=("test-zone-a",))])
        pool_b = make_nodepool(name="pool-b", requirements=[
            NodeSelectorRequirement(key=ZONE, operator="In",
                                    values=("test-zone-b",))])
        its = _its()
        def pods():
            return make_pods(4, cpu="100m", labels={"app": "demo"},
                             spread=[spread_zone(key="app", value="demo")])
        t = tensor_solve([pool_a, pool_b],
                         {"pool-a": its, "pool-b": its}, pods())
        h = host_solve([pool_a, pool_b],
                       {"pool-a": its, "pool-b": its}, pods())
        assert not t.pod_errors and not h.pod_errors
        assert zone_counts(t) == zone_counts(h) == [2, 2]


class TestExistingCounts:
    """Scheduled cluster pods seed the domain counts."""

    def _cluster(self, per_zone):
        """A ClusterView with `per_zone[zone]` running matching pods."""
        pods = []
        node_labels = {}
        i = 0
        for zone, n in per_zone.items():
            name = f"live-{zone}"
            node_labels[name] = {ZONE: zone, HOST: name}
            pods += running_on(
                [make_pod(cpu="100m", labels={"app": "demo"},
                          name=f"live-{zone}-{j}") for j in range(n)], name)
            i += 1
        return StaticClusterView(pods, node_labels)

    def test_new_pods_fill_low_count_zones(self):
        """:218-251 family: counts (3,0,0) pull the next 3 pods into the
        empty zones before the occupied one grows."""
        cluster = self._cluster({"test-zone-a": 3})
        def solve(fn):
            return fn([make_nodepool()], _its(),
                      make_pods(3, cpu="100m", labels={"app": "demo"},
                                spread=[spread_zone(key="app",
                                                    value="demo")]),
                      cluster=cluster)
        t, h = solve(tensor_solve), solve(host_solve)
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            counts = zone_counts(r)
            assert "test-zone-a" not in [
                nc.requirements.get(ZONE).values_list()[0]
                for nc in r.new_nodeclaims
                if nc.requirements.get(ZONE) is not None
                and len(nc.requirements.get(ZONE).values_list()) == 1
            ] or counts == [1, 2], counts

    def test_max_skew_blocks_overflow_into_hot_zone(self):
        """:333-365 'should not violate max-skew when unsat = do not
        schedule': with counts (2,0,0) and maxSkew=1, six new pods land
        (2,3,3)-ish — never pushing the hot zone beyond min+skew."""
        cluster = self._cluster({"test-zone-a": 2})
        def solve(fn):
            return fn([make_nodepool()], _its(),
                      make_pods(6, cpu="100m", labels={"app": "demo"},
                                spread=[spread_zone(key="app",
                                                    value="demo")]),
                      cluster=cluster)
        t, h = solve(tensor_solve), solve(host_solve)
        assert not t.pod_errors and not h.pod_errors
        # total per zone incl. the 2 existing: max-min <= 1
        for r in (t, h):
            totals = collections.Counter({"test-zone-a": 2})
            for nc in r.new_nodeclaims:
                req = nc.requirements.get(ZONE)
                if req is not None and len(req.values_list()) == 1:
                    totals[req.values_list()[0]] += sum(
                        1 for p in nc.pods
                        if p.metadata.labels.get("app") == "demo")
            vals = list(totals.values())
            assert max(vals) - min(vals) <= 1, totals


class TestHostnameSpread:
    def test_balance_across_nodes(self):
        """:531-543: maxSkew=1 hostname spread -> one pod per node."""
        t, h = both(lambda: make_pods(4, cpu="100m", labels={"app": "demo"},
                                      spread=[spread_hostname(
                                          key="app", value="demo")]))
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 4

    def test_max_skew_2_allows_pairs(self):
        """:544-556 'balance pods on the same hostname up to maxskew':
        maxSkew=2 lets nodes take up to two pods."""
        t, h = both(lambda: make_pods(6, cpu="100m", labels={"app": "demo"},
                                      spread=[spread_hostname(
                                          max_skew=2, key="app",
                                          value="demo")]))
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            assert max(len(nc.pods) for nc in r.new_nodeclaims) <= 2
            assert len(r.new_nodeclaims) >= 3

    def test_multiple_deployments_with_hostname_spread(self):
        """:557-592 'balance multiple deployments with hostname topology
        spread': two spread deployments share nodes without breaking either
        constraint."""
        def pods():
            return (make_pods(3, cpu="100m", labels={"app": "d1"},
                              spread=[spread_hostname(key="app",
                                                      value="d1")])
                    + make_pods(3, cpu="100m", labels={"app": "d2"},
                                spread=[spread_hostname(key="app",
                                                        value="d2")]))
        t, h = both(pods)
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            for nc in r.new_nodeclaims:
                per_app = collections.Counter(
                    p.metadata.labels.get("app") for p in nc.pods)
                assert all(v <= 1 for v in per_app.values()), per_app


class TestCapacityTypeSpread:
    """topology_test.go:638-925: capacity-type (and arch) spread keys are
    NOT kernel-expressible — the production scheduler must fall back to the
    host oracle and still honor the constraint."""

    def _spread(self, max_skew=1):
        return [TopologySpreadConstraint(
            topology_key=api_labels.CAPACITY_TYPE_LABEL_KEY,
            max_skew=max_skew,
            label_selector=LabelSelector(match_labels={"app": "demo"}))]

    def test_balances_across_capacity_types_via_fallback(self):
        """:639-651 'should balance pods across capacity types'."""
        ts = TensorScheduler([make_nodepool()], {"default": _its()})
        r = ts.solve(make_pods(4, cpu="100m", labels={"app": "demo"},
                               spread=self._spread()))
        assert ts.fallback_reason != "", "captype spread rode the kernel?"
        assert not r.pod_errors
        counts = collections.Counter()
        for nc in r.new_nodeclaims:
            req = nc.requirements.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
            if req is not None and len(req.values_list()) == 1:
                counts[req.values_list()[0]] += len(nc.pods)
        assert sorted(counts.values()) == [2, 2], counts

    def test_pool_capacity_type_constraint_respected(self):
        """:652-666: a pool pinned to on-demand leaves one domain."""
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            key=api_labels.CAPACITY_TYPE_LABEL_KEY, operator="In",
            values=("on-demand",))])
        ts = TensorScheduler([pool], {"default": _its()})
        r = ts.solve(make_pods(4, cpu="100m", labels={"app": "demo"},
                               spread=self._spread()))
        assert not r.pod_errors
        for nc in r.new_nodeclaims:
            req = nc.requirements.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
            assert req is not None and req.values_list() == ["on-demand"]


class TestCombinedConstraints:
    def test_hostname_and_zonal_layered(self):
        """:926-966 'should spread pods while respecting both constraints
        (hostname and zonal)': zone maxSkew=1 AND hostname maxSkew=1."""
        def pods():
            return make_pods(4, cpu="100m", labels={"app": "demo"},
                             spread=[spread_zone(key="app", value="demo"),
                                     spread_hostname(key="app",
                                                     value="demo")])
        t, h = both(pods)
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            assert len(r.new_nodeclaims) == 4  # hostname: one pod per node
            zc = zone_counts(r)
            assert max(zc) - min(zc) <= 1     # zonal skew holds too


class TestSpreadLimitedByAffinity:
    """topology_test.go:1206-1322 Combined Zonal Topology and Node
    Affinity: the POD's own selector/affinity filters its spread domains
    (nextDomainTopologySpread's podDomains — the seed-1032 regression
    class)."""

    def test_node_selector_limits_domains(self):
        """:1207-1232: selector zone-b + zonal spread -> everything lands
        in zone-b at skew 0 (domains = {b}, not the pool's three)."""
        def pods():
            return make_pods(4, cpu="100m", labels={"app": "demo"},
                             node_selector={ZONE: "test-zone-b"},
                             spread=[spread_zone(key="app", value="demo")])
        t, h = both(pods)
        assert not t.pod_errors and not h.pod_errors, (
            "selector-pinned spread treated the unreachable zones as "
            "skew-bearing domains")
        assert zone_counts(t) == zone_counts(h) == [4]

    def test_required_affinity_limits_domains(self):
        """:1255-1298: required zone In [a, b] -> spread over exactly those
        two domains."""
        def pods():
            return make_pods(4, cpu="100m", labels={"app": "demo"},
                             required_affinity=[[NodeSelectorRequirement(
                                 key=ZONE, operator="In",
                                 values=("test-zone-a", "test-zone-b"))]],
                             spread=[spread_zone(key="app", value="demo")])
        t, h = both(pods)
        assert not t.pod_errors and not h.pod_errors
        assert zone_counts(t) == zone_counts(h) == [2, 2]

    def test_preferred_affinity_does_not_limit_domains(self):
        """:1299-1322: a PREFERRED zone must not shrink the spread domain
        set — all three zones stay usable (the preference relaxes when the
        skew demands it)."""
        pods = make_pods(6, cpu="100m", labels={"app": "demo"},
                         preferred_affinity=[(10, [NodeSelectorRequirement(
                             key=ZONE, operator="In",
                             values=("test-zone-a",))])],
                         spread=[spread_zone(key="app", value="demo")])
        ts = TensorScheduler([make_nodepool()], {"default": _its()})
        r = ts.solve(pods)
        assert not r.pod_errors
        zones = {nc.requirements.get(ZONE).values_list()[0]
                 for nc in r.new_nodeclaims
                 if nc.requirements.get(ZONE) is not None
                 and len(nc.requirements.get(ZONE).values_list()) == 1}
        # 6 pods over the kwok catalog's four zones at maxSkew=1: every
        # zone must be used — a preference-shrunk domain set can't
        assert len(zones) == 4, (
            f"preference shrank the spread domains to {zones}")
