"""Device-cache reuse and eviction behavior (VERDICT r2 weak #9): the
catalog-encoding cache must reuse device-resident tensors across solves of
the same catalog, evict least-recently-used under churn, and stay correct
after eviction (a re-encoded catalog must produce identical decisions)."""

import pytest

from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning import tensor_scheduler as ts_mod
from karpenter_tpu.provisioning.tensor_scheduler import (_CATALOG_CACHE,
                                                         TensorScheduler)

from factories import make_nodepool, make_pods


@pytest.fixture(autouse=True)
def clean_cache():
    saved = dict(_CATALOG_CACHE)
    _CATALOG_CACHE.clear()
    yield
    _CATALOG_CACHE.clear()
    _CATALOG_CACHE.update(saved)


def solve(catalog, n=8):
    ts = TensorScheduler([make_nodepool()], {"default": list(catalog)},
                         force_tensor=True)
    r = ts.solve(make_pods(n, cpu="500m"))
    assert ts.fallback_reason == ""
    return r


def catalogs(k, size=12):
    its = kwok.construct_instance_types()
    return [its[i:i + size] for i in range(k)]


class TestCatalogCache:
    def test_same_catalog_reuses_encoding(self):
        cat = catalogs(1)[0]
        solve(cat)
        assert len(_CATALOG_CACHE) == 1
        enc = next(iter(_CATALOG_CACHE.values()))
        solve(cat)
        assert len(_CATALOG_CACHE) == 1
        assert next(iter(_CATALOG_CACHE.values())) is enc  # no re-encode

    def test_lru_eviction_keeps_hot_entry(self):
        cats = catalogs(ts_mod._CATALOG_CACHE_MAX + 1)
        hot = cats[0]
        solve(hot)
        hot_enc = next(iter(_CATALOG_CACHE.values()))
        for c in cats[1:-1]:
            solve(c)
            solve(hot)  # keep the hot catalog recently used
        assert len(_CATALOG_CACHE) == ts_mod._CATALOG_CACHE_MAX
        solve(cats[-1])  # one past the cap: evicts the LRU, not the hot one
        assert len(_CATALOG_CACHE) == ts_mod._CATALOG_CACHE_MAX
        assert any(v is hot_enc for v in _CATALOG_CACHE.values())

    def test_results_identical_after_eviction(self):
        cat = catalogs(1)[0]
        r1 = solve(cat)
        key1 = [(nc.template.nodepool_name,
                 tuple(it.name for it in nc.instance_type_options),
                 len(nc.pods)) for nc in r1.new_nodeclaims]
        # churn enough distinct catalogs to evict cat's encoding
        for c in catalogs(ts_mod._CATALOG_CACHE_MAX + 1, size=10)[1:]:
            solve(c)
        r2 = solve(cat)  # re-encoded from scratch
        key2 = [(nc.template.nodepool_name,
                 tuple(it.name for it in nc.instance_type_options),
                 len(nc.pods)) for nc in r2.new_nodeclaims]
        assert key1 == key2

    def test_catalog_mutation_invalidates(self):
        """Mutating an instance type in place must never reuse stale
        complement-encoded masks (the cache key digests requirements,
        capacity, and offerings)."""
        cat = catalogs(1)[0]
        solve(cat)
        assert len(_CATALOG_CACHE) == 1
        cat[0].offerings[0].price *= 2  # repricing changes the content key
        solve(cat)
        assert len(_CATALOG_CACHE) == 2
