"""Delta-aware sidecar sessions (ISSUE 8): codec round-trips for every
delta message kind (seeded from the parity fuzzer's generator corpus), the
content-digest handshake + resync paths, a loud failure on unknown delta
schema versions, session eviction under load, tenant-fair admission, and
per-tenant observability."""

import json
import random
import threading

import grpc
import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.sidecar import codec, wire
from karpenter_tpu.sidecar import server as srv
from karpenter_tpu.sidecar.client import RemoteScheduler, SolverSession

from factories import make_nodepool, make_pods, make_state_node
from test_parity_fuzzer import gen_nodepools, gen_pods


@pytest.fixture(scope="module")
def sidecar():
    server, port = srv.serve(port=0)
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


def _session_pair(sidecar, its, pool, tenant="", **kw):
    session = SolverSession(sidecar, tenant=tenant)
    return RemoteScheduler(sidecar, [pool], {"default": its},
                           session=session, **kw), session


def _mirror_apply(mirror, header, blobs):
    """Server-side shadow of _apply_session_delta's pod/template half, over
    plain dicts — the codec property tests run the wire WITHOUT grpc."""
    if header.get("full_state"):
        mirror.update(template_list=[], template_keys=[], rows=[],
                      state_tokens={}, ds_token="", cluster_token="")
    for tid, d in header.get("templates_new", ()):
        assert tid == len(mirror["template_list"])
        mirror["template_list"].append(d)
        mirror["template_keys"].append(codec.template_content_key(d))
    mirror["rows"] = codec.apply_pod_delta(mirror["rows"], header, blobs)
    for d in header.get("state_upsert", ()):
        mirror["state_tokens"][d["name"]] = str(
            header.get("state_revs", {}).get(d["name"], ""))
    for name in header.get("state_remove", ()):
        mirror["state_tokens"].pop(name, None)
    if "ds_token" in header:
        mirror["ds_token"] = str(header["ds_token"])
    if "cluster_token" in header:
        mirror["cluster_token"] = str(header["cluster_token"])
    return codec.batch_digest(
        [r[0] for r in mirror["rows"]], [r[1] for r in mirror["rows"]],
        codec.templates_digest(mirror["template_keys"]),
        mirror["state_tokens"], mirror["ds_token"], mirror["cluster_token"])


def _offline_session():
    """A SolverSession used purely as the delta-request assembler (no RPC
    ever issued; the channel never connects)."""
    s = SolverSession("127.0.0.1:1")
    s._session_id = "offline"
    return s


class TestDeltaCodec:
    """Pure-codec property tests: the client's request assembly and the
    server's apply must agree on state and digest through arbitrary churn
    (the wire equivalent of the ProblemState churn fuzzer)."""

    @pytest.mark.parametrize("seed", [3, 11, 29])
    def test_churned_batches_round_trip_and_digest_agree(self, seed):
        rng = random.Random(seed)
        pools = gen_nodepools(rng)
        pods = gen_pods(rng, pools)
        sess = _offline_session()
        mirror = dict(template_list=[], template_keys=[], rows=[],
                      state_tokens={}, ds_token="", cluster_token="")
        for round_ in range(6):
            header, blobs, commit, order = sess._delta_request(
                pods, [], [], None, None, False)
            digest = _mirror_apply(mirror, header, blobs)
            assert digest == header["digest"], f"round {round_} diverged"
            commit()
            # decoded server batch must be content-identical to a direct
            # encode of the same pod order
            tids = [r[0] for r in mirror["rows"]]
            tss = [r[1] for r in mirror["rows"]]
            back = codec.build_wire_pods(mirror["template_list"], tids, tss)
            assert len(back) == len(order)
            for wp, p in zip(back, order):
                assert wp.requests() == p.requests()
                assert wp.metadata.labels == p.metadata.labels
                assert wp.namespace == p.namespace
            # churn: drop a slice, add fresh shapes, keep the rest
            rng.shuffle(pods)
            pods = pods[rng.randint(0, max(1, len(pods) // 3)):]
            pods += gen_pods(rng, pools)[:rng.randint(1, 20)]

    def test_pod_remove_only_delta(self):
        sess = _offline_session()
        pods = make_pods(6, cpu="500m")
        h1, b1, commit, _ = sess._delta_request(pods, [], [], None, None,
                                                False)
        assert h1.get("pods_full") == 1 and h1.get("full_state") == 1
        commit()
        h2, b2, commit2, order = sess._delta_request(pods[:4], [], [], None,
                                                     None, False)
        assert "pods_full" not in h2 and "templates_new" not in h2
        assert wire.unpack_u32(b2["pod_remove"]).tolist() == [4, 5]
        assert "pod_add_tid" not in b2
        assert [p.uid for p in order] == [p.uid for p in pods[:4]]

    def test_pod_add_only_delta_reuses_templates(self):
        sess = _offline_session()
        pods = make_pods(4, cpu="500m")
        _, _, commit, _ = sess._delta_request(pods, [], [], None, None,
                                              False)
        commit()
        grown = pods + make_pods(2, cpu="500m")
        h, b, _, order = sess._delta_request(grown, [], [], None, None,
                                             False)
        # same deployment shape: the existing template id is reused, only
        # the two new rows ride the wire
        assert "templates_new" not in h
        assert "pod_remove" not in b
        assert len(wire.unpack_u32(b["pod_add_tid"])) == 2
        assert [p.uid for p in order] == [p.uid for p in grown]

    def test_degenerate_diff_falls_back_to_snapshot(self):
        sess = _offline_session()
        pods = make_pods(8, cpu="500m")
        _, _, commit, _ = sess._delta_request(pods, [], [], None, None,
                                              False)
        commit()
        replaced = make_pods(8, cpu="250m")  # every row churned
        h, b, _, _ = sess._delta_request(replaced, [], [], None, None,
                                         False)
        assert h.get("pods_full") == 1
        # the template table is still valid: NOT a full_state resync
        assert "full_state" not in h
        assert "pod_remove" not in b

    def test_state_and_ds_tokens_move_the_digest(self):
        sess = _offline_session()
        pods = make_pods(3, cpu="250m")
        h1, _, commit, _ = sess._delta_request(pods, [], [], None, None,
                                               False)
        commit()
        sn = make_state_node("delta-n1", zone="test-zone-a")
        h2, _, commit2, _ = sess._delta_request(pods, [sn], [], None, None,
                                                False)
        assert [d["name"] for d in h2["state_upsert"]] == ["delta-n1"]
        assert "delta-n1" in h2["state_revs"]
        assert h2["digest"] != h1["digest"]
        commit2()
        ds = make_pods(1, cpu="100m")
        h3, _, _, _ = sess._delta_request(pods, [sn], ds, None, None, False)
        assert "daemonset" in h3 and h3["ds_token"]
        assert h3["digest"] != h2["digest"]
        # removing the node flows as a remove + digest move
        h4, _, _, _ = sess._delta_request(pods, [], [], None, None, False)
        assert h4["state_remove"] == ["delta-n1"]
        assert h4["digest"] != h2["digest"]

    def test_apply_pod_delta_rejects_malformed_removals(self):
        rows = [(0, 1.0), (0, 2.0), (1, 3.0)]
        for bad in ([2, 1], [3], [1, 1]):
            with pytest.raises(ValueError):
                codec.apply_pod_delta(
                    rows, {}, {"pod_remove": wire.pack_u32(bad)})
        with pytest.raises(ValueError):
            codec.apply_pod_delta(rows, {}, {
                "pod_add_tid": wire.pack_u32([0, 1]),
                "pod_add_ts": wire.pack_f64([1.0])})

    def test_unknown_schema_version_is_loud(self):
        with pytest.raises(codec.DeltaVersionError):
            codec.check_delta_version({"v": 99})
        with pytest.raises(codec.DeltaVersionError):
            codec.check_delta_version({})
        codec.check_delta_version({"v": codec.DELTA_SCHEMA_VERSION})


class TestDeltaSession:
    """The delta wire against a live server: parity under churn, delta
    residency, digest-mismatch + eviction resyncs, parity probes."""

    def test_parity_with_local_under_churn(self, sidecar):
        its = construct_instance_types()[:48]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool, tenant="parity-t")
        pods = make_pods(12, cpu="500m") + make_pods(5, cpu="1000m",
                                                     labels={"app": "x"})
        key = lambda nc: (tuple(it.name for it in nc.instance_type_options),
                          len(nc.pods))
        for round_ in range(4):
            remote = rs.solve(pods)
            local = TensorScheduler([pool], {"default": its}).solve(pods)
            assert remote.pod_errors == local.pod_errors
            assert sorted(map(key, remote.new_nodeclaims)) == \
                sorted(map(key, local.new_nodeclaims)), f"round {round_}"
            if round_ > 0:
                assert session.last_encode_kind == "delta"
            pods = pods[2:] + make_pods(3, cpu=f"{250 + round_ * 50}m")
        assert session.resyncs == 0
        session.close()

    def test_steady_state_wire_shrinks(self, sidecar):
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        sizes = []
        orig_call = session._call

        def spy(method, payload, _orig=orig_call):
            if method == "SolveSession":
                sizes.append(len(payload))
            return _orig(method, payload)

        session._call = spy
        pods = make_pods(200, cpu="500m")
        rs.solve(pods)
        pods[0:2] = make_pods(2, cpu="500m")  # 1% churn
        rs.solve(pods)
        assert len(sizes) == 2
        # the steady-state delta ships a handful of rows, not the batch
        assert sizes[1] < sizes[0] / 4, sizes
        session.close()

    def test_digest_mismatch_transparent_resync(self, sidecar):
        from karpenter_tpu.metrics.registry import SIDECAR_RESYNCS
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        pods = make_pods(6, cpu="500m")
        r1 = rs.solve(pods)
        before = SIDECAR_RESYNCS.value({"reason": "digest_mismatch"})
        session._rows = session._rows[1:]  # corrupt the client mirror
        r2 = rs.solve(pods)
        assert session.resyncs == 1
        assert SIDECAR_RESYNCS.value({"reason": "digest_mismatch"}) == \
            before + 1
        assert r2.pod_errors == r1.pod_errors
        assert len(r2.new_nodeclaims) == len(r1.new_nodeclaims)
        # and the session is delta-resident again right after
        r3 = rs.solve(pods)
        assert session.last_encode_kind == "delta"
        assert session.resyncs == 1
        session.close()

    def test_eviction_transparent_resync(self, sidecar):
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        r1 = rs.solve(make_pods(4, cpu="500m"))
        assert not r1.pod_errors
        with srv._SESSIONS_LOCK:
            srv._SESSIONS.clear()  # server restart / eviction
        r2 = rs.solve(make_pods(4, cpu="500m"))
        assert not r2.pod_errors
        assert session.resyncs == 1
        assert session._session_id is not None
        rs.solve(make_pods(4, cpu="500m"))
        assert session.last_encode_kind == "delta"
        session.close()

    def test_lost_response_desync_heals_via_resync(self, sidecar):
        """A solve whose RESPONSE is lost leaves the client mirrors BEHIND
        the server (the server applied the delta; commit never ran). The
        re-sent template registrations then violate the server's
        contiguity check (INVALID_ARGUMENT, fired before the digest
        handshake) — the client must treat that as a resync trigger, not
        a hard failure."""
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        rs.solve(make_pods(4, cpu="500m"))
        # snapshot the mirrors, advance the server with a NEW template,
        # then roll the mirrors back — exactly a lost response
        saved = (dict(session._tmpl_ids), list(session._tmpl_keys),
                 list(session._tmpl_constrained), session._tmpl_digest,
                 list(session._rows), dict(session._pod_rows))
        grown = make_pods(4, cpu="500m") + make_pods(2, cpu="123m")
        rs.solve(grown)
        (session._tmpl_ids, session._tmpl_keys, session._tmpl_constrained,
         session._tmpl_digest, session._rows, session._pod_rows) = saved
        r = rs.solve(grown)  # re-registers an already-known template id
        assert not r.pod_errors
        assert session.resyncs == 1
        r2 = rs.solve(grown)
        assert session.last_encode_kind == "delta"
        assert session.resyncs == 1
        session.close()

    def test_parity_probe_is_byte_identical(self, sidecar):
        its = construct_instance_types()[:48]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        session.parity_every = 1
        pods = (make_pods(8, cpu="500m")
                + make_pods(4, cpu="250m", labels={"app": "s"}))
        for _ in range(3):
            rs.solve(pods)
            assert session.last_parity == "byte-identical", \
                session.last_parity
            pods = pods[1:] + make_pods(1, cpu="750m")
        session.close()

    def test_state_node_revision_skips_reserialization(self, sidecar,
                                                       monkeypatch):
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        sn = make_state_node("rev-n1", zone="test-zone-a")
        assert sn.identity is not None and sn.revision is not None
        calls = []
        orig = codec.state_node_to_dict
        monkeypatch.setattr(codec, "state_node_to_dict",
                            lambda s, store=None: calls.append(s.name())
                            or orig(s, store=store))
        rs2 = RemoteScheduler(rs.address, [pool], {"default": its},
                              state_nodes=[sn], session=session)
        rs2.solve(make_pods(2, cpu="500m"))
        assert calls == ["rev-n1"]
        rs2.solve(make_pods(2, cpu="500m"))
        assert calls == ["rev-n1"], "unchanged revision re-serialized"
        sn.revision += 1  # a cluster mutation would bump this
        rs2.solve(make_pods(2, cpu="500m"))
        assert calls == ["rev-n1", "rev-n1"]
        assert session.resyncs == 0
        session.close()

    def test_unknown_version_over_the_wire(self, sidecar):
        its = construct_instance_types()[:8]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        rs.solve(make_pods(2, cpu="500m"))  # establishes the session
        bad = wire.pack({"session": session._session_id, "v": 99,
                         "digest": ""}, {})
        with pytest.raises(grpc.RpcError) as exc:
            session._call("SolveSession", bad)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "schema version" in exc.value.details()
        session.close()

    def test_noncontiguous_template_registration_rejected(self, sidecar):
        its = construct_instance_types()[:8]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool)
        rs.solve(make_pods(2, cpu="500m"))
        bad = wire.pack({"session": session._session_id,
                         "v": codec.DELTA_SCHEMA_VERSION,
                         "templates_new": [[57, {"bogus": True}]]}, {})
        with pytest.raises(grpc.RpcError) as exc:
            session._call("SolveSession", bad)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "out of order" in exc.value.details()
        session.close()

    def test_legacy_session_wire_still_served(self, sidecar):
        """Pre-delta clients (no "v" in the header) keep working: full
        template list + row columns per solve."""
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        session = SolverSession(sidecar)
        payload = codec.encode_session_request([pool], {"default": its})
        sid = json.loads(
            session._call("CreateSession", payload).decode())["session"]
        pods = make_pods(5, cpu="500m")
        templates, tmpl_idx, ts = codec.encode_pod_rows(pods)
        request = wire.pack({"session": sid, "templates": templates},
                            {"tmpl_idx": wire.pack_u32(tmpl_idx),
                             "ts": wire.pack_f64(ts)})
        response = session._call("SolveSession", request)
        from karpenter_tpu.sidecar.client import decode_results_rows
        results = decode_results_rows(response, pods,
                                      codec.union_catalog({"default": its}))
        assert not results.pod_errors
        assert results.new_nodeclaims
        session.close()


class TestEvictionUnderLoad:
    """Satellite: eviction must never reap a session with a queued or
    in-flight solve, and idle reaping respects the same guard."""

    def _mk_session(self, name):
        its = construct_instance_types()[:4]
        pool = make_nodepool(name="default")
        payload = codec.encode_session_request([pool], {"default": its},
                                               tenant=name)
        sid = json.loads(srv._create_session(payload).decode())["session"]
        with srv._SESSIONS_LOCK:
            return srv._SESSIONS[sid]

    def test_create_overflow_skips_busy_sessions(self, monkeypatch):
        with srv._SESSIONS_LOCK:
            saved = dict(srv._SESSIONS)
            srv._SESSIONS.clear()
        monkeypatch.setattr(srv, "_SESSIONS_MAX", 2)
        try:
            s1 = self._mk_session("busy")
            s1.active = 1  # a queued/in-flight solve
            s2 = self._mk_session("idle")
            s3 = self._mk_session("new")
            with srv._SESSIONS_LOCK:
                alive = set(srv._SESSIONS)
            # the busy session survives; the idle LRU one was evicted
            assert s1.id in alive
            assert s2.id not in alive
            assert s3.id in alive
            # all-busy: the cap is exceeded rather than reaping live state
            s3.active = 1
            s4 = self._mk_session("another")
            with srv._SESSIONS_LOCK:
                assert {s1.id, s3.id, s4.id} <= set(srv._SESSIONS)
        finally:
            with srv._SESSIONS_LOCK:
                srv._SESSIONS.clear()
                srv._SESSIONS.update(saved)

    def test_idle_reap_skips_busy_sessions(self):
        with srv._SESSIONS_LOCK:
            saved = dict(srv._SESSIONS)
            srv._SESSIONS.clear()
        try:
            busy = self._mk_session("busy")
            idle = self._mk_session("idle")
            busy.active = 1
            old = busy.last_used
            reaped = srv._reap_idle_sessions(
                now=old + srv.SESSION_IDLE_SECONDS + 60)
            assert reaped == [idle.id]
            with srv._SESSIONS_LOCK:
                assert busy.id in srv._SESSIONS
                assert idle.id not in srv._SESSIONS
            # once released AND idle long enough, it goes too
            busy.active = 0
            reaped = srv._reap_idle_sessions(
                now=busy.last_used + srv.SESSION_IDLE_SECONDS + 60)
            assert reaped == [busy.id]
        finally:
            with srv._SESSIONS_LOCK:
                srv._SESSIONS.clear()
                srv._SESSIONS.update(saved)

    def test_concurrent_tenants_share_the_server(self, sidecar):
        """N tenant sessions solving concurrently: every solve lands, no
        resyncs, every tenant's admission wait is measured."""
        from karpenter_tpu.metrics.registry import SIDECAR_QUEUE_WAIT
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        errors = []

        def tenant(name):
            try:
                rs, session = _session_pair(sidecar, its, pool, tenant=name)
                pods = make_pods(10, cpu="500m")
                for w in range(4):
                    r = rs.solve(pods)
                    assert not r.pod_errors
                    pods[w] = make_pods(1, cpu="500m")[0]
                assert session.resyncs == 0
                assert session.last_encode_kind == "delta"
                session.close()
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((name, repr(e)))

        names = [f"load-{i}" for i in range(3)]
        threads = [threading.Thread(target=tenant, args=(n,))
                   for n in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for n in names:
            assert SIDECAR_QUEUE_WAIT.count({"tenant": n}) >= 4


class TestAdmissionQueue:
    def test_round_robin_fairness_across_tenants(self):
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=16)
        assert q.acquire("A") == 0.0  # slot taken
        grants = []

        def waiter(tag, tenant):
            q.acquire(tenant)
            grants.append(tag)
            q.release()  # hand the slot down the chain

        threads = []
        for tag, tenant in (("A2", "A"), ("A3", "A"), ("B1", "B")):
            t = threading.Thread(target=waiter, args=(tag, tenant))
            t.start()
            threads.append(t)
            while True:  # deterministic enqueue order
                with q._lock:
                    if q._queued == len(threads):
                        break
        # the holder releases ONCE; each granted waiter records its grant
        # and releases in turn, so the recorded order IS the grant order
        q.release()
        for t in threads:
            t.join()
        # one tenant's burst never head-of-line-blocks the other: the
        # grant order interleaves A and B instead of draining A first
        assert grants == ["A2", "B1", "A3"]

    def test_queue_bound_rejects_loudly(self):
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=1)
        assert q.acquire("A") == 0.0
        t = threading.Thread(target=q.acquire, args=("A",))
        t.start()
        while True:
            with q._lock:
                if q._queued == 1:
                    break
        with pytest.raises(srv.QueueFullError):
            q.acquire("B")
        q.release()
        t.join()
        q.release()

    def test_overload_and_cancellation_surface_as_grpc_codes(self,
                                                             monkeypatch):
        """A full queue must map to RESOURCE_EXHAUSTED (not UNKNOWN) on
        BOTH solve paths, and a request whose client cancelled while
        queued must be skipped (CANCELLED) instead of burning the device."""
        class _Abort(Exception):
            pass

        class _Ctx:
            def __init__(self, active=True):
                self.active = active
                self.code = None

            def is_active(self):
                return self.active

            def abort(self, code, msg):
                self.code = code
                raise _Abort(msg)

        its = construct_instance_types()[:4]
        pool = make_nodepool(name="default")
        payload = codec.encode_session_request([pool], {"default": its})
        sid = json.loads(srv._create_session(payload).decode())["session"]
        frame = wire.pack({"session": sid, "v": codec.DELTA_SCHEMA_VERSION,
                           "pods_full": 1, "full_state": 1}, {})
        # saturate the admission queue: slot held + queue full
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=1)
        monkeypatch.setattr(srv, "ADMISSION", q)
        q.acquire("holder")
        t = threading.Thread(target=q.acquire, args=("holder",))
        t.start()
        while True:
            with q._lock:
                if q._queued == 1:
                    break
        ctx = _Ctx()
        with pytest.raises(_Abort):
            srv._solve_session(frame, ctx)
        assert ctx.code == grpc.StatusCode.RESOURCE_EXHAUSTED
        ctx2 = _Ctx()
        with pytest.raises(_Abort):
            srv._solve(codec.encode_solve_request([pool], {"default": its},
                                                  make_pods(1, cpu="100m")),
                       ctx2)
        assert ctx2.code == grpc.StatusCode.RESOURCE_EXHAUSTED
        q.release()   # holder done: grants the queued waiter
        t.join()
        q.release()   # the granted waiter's slot
        # cancelled-while-queued: the slot is granted but the solve is
        # skipped and the slot freed for live requests
        ctx3 = _Ctx(active=False)
        with pytest.raises(_Abort):
            srv._solve_session(frame, ctx3)
        assert ctx3.code == grpc.StatusCode.CANCELLED
        with q._lock:
            assert q._active == 0 and q._queued == 0

    def test_depth_gauge_tracks_waiters(self):
        from karpenter_tpu.metrics.registry import SIDECAR_QUEUE_DEPTH
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=8)
        q.acquire("depth-t")
        t = threading.Thread(target=q.acquire, args=("depth-t",))
        t.start()
        while True:
            with q._lock:
                if q._queued == 1:
                    break
        assert SIDECAR_QUEUE_DEPTH.value({"tenant": "depth-t"}) == 1.0
        q.release()
        t.join()
        assert SIDECAR_QUEUE_DEPTH.value({"tenant": "depth-t"}) == 0.0
        q.release()


class TestTenantObservability:
    def test_tenant_label_is_bounded(self, monkeypatch):
        from karpenter_tpu.metrics import registry as reg
        # fresh bound set: the real one is process-lifetime, and filling
        # its cap here would demote every later test's tenants to overflow
        monkeypatch.setattr(reg, "_TENANT_LABELS", set())
        out = {reg.tenant_label(f"cap-tenant-{i}") for i in range(100)}
        # at most the cap's worth of real names; the rest collapse
        assert len(out) <= reg.TENANT_LABEL_CAP + 1
        assert reg.TENANT_OVERFLOW in out
        # established names stay stable
        first = reg.tenant_label("cap-tenant-0")
        assert first == reg.tenant_label("cap-tenant-0")

    def test_sidecar_solve_emits_tenant_phase_series(self, sidecar):
        from karpenter_tpu.metrics.registry import REGISTRY
        its = construct_instance_types()[:8]
        pool = make_nodepool(name="default")
        rs, session = _session_pair(sidecar, its, pool, tenant="obs-t")
        rs.solve(make_pods(3, cpu="500m"))
        session.close()
        text = REGISTRY.expose()
        assert 'tenant="obs-t"' in text
        # the sidecar root span itself lands in the phase histogram
        assert 'phase="sidecar.solve"' in text

    def test_slo_snapshot_filters_by_tenant(self):
        from karpenter_tpu.obs.slo import SLOWatcher
        from karpenter_tpu.obs.tracer import Tracer
        tracer = Tracer()
        watcher = SLOWatcher({"sidecar.solve": 10.0})
        tracer.watcher = watcher
        with tracer.span("sidecar.solve", tenant="a"):
            pass
        with tracer.span("sidecar.solve", tenant="a"):
            pass
        with tracer.span("sidecar.solve", tenant="b"):
            pass
        snap_all = watcher.snapshot()
        assert snap_all["budgets"]["sidecar.solve"]["observed"] == 3
        snap_a = watcher.snapshot(tenant="a")
        assert snap_a["budgets"]["sidecar.solve"]["observed"] == 2
        assert snap_a["tenant"] == "a"
        assert watcher.snapshot(
            tenant="zzz")["budgets"]["sidecar.solve"]["observed"] == 0

    def test_debug_traces_filters_by_tenant_and_session(self):
        from karpenter_tpu.obs.tracer import Tracer
        from karpenter_tpu.operator.server import _debug_traces_factory
        tracer = Tracer()
        with tracer.span("sidecar.solve", tenant="a", session="s1"):
            pass
        with tracer.span("sidecar.solve", tenant="b", session="s2"):
            pass
        fn = _debug_traces_factory(tracer)
        status, _, body = fn({"tenant": ["a"]})
        assert status == 200
        assert "traces 1" in body
        status, _, body = fn({"session": ["s2"]})
        assert "traces 1" in body
        status, _, body = fn({"tenant": ["a"], "session": ["s2"]})
        assert "traces 0" in body

    def test_debug_slo_accepts_tenant_query(self):
        from karpenter_tpu.obs.slo import SLOWatcher
        from karpenter_tpu.operator.server import _debug_slo_factory
        watcher = SLOWatcher({"solve": 1.0})
        fn = _debug_slo_factory(watcher)
        status, ctype, body = fn({"tenant": ["a"]})
        assert status == 200 and ctype == "application/json"
        assert json.loads(body)["tenant"] == "a"
        status, _, body = fn({})
        assert json.loads(body)["tenant"] is None
