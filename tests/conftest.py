"""Test configuration: run JAX on a virtual 8-device CPU mesh so multi-chip
sharding paths are exercised without TPU hardware.

NOTE: the JAX_PLATFORMS env var is clobbered by this image's axon TPU plugin;
the config API before first jax use is the only reliable switch."""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run")
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tests (deterministic: fixed seed, "
        "fake clock, no sleeps — tier-1 eligible by construction)")
    config.addinivalue_line(
        "markers",
        "replay: flight-recorder record/replay tests (deterministic "
        "offline re-solves of captured traces — tier-1 eligible)")
    config.addinivalue_line(
        "markers",
        "churn: incremental delta-solver tests (persistent ProblemState, "
        "seeded churn streams asserting delta == cold at every step — "
        "deterministic, tier-1 eligible)")
    config.addinivalue_line(
        "markers",
        "sim: fleet-simulator tests (seeded scenario replays through the "
        "full operator loop on the accelerated FakeClock — deterministic; "
        "tier-1 eligible EXCEPT multi-minute scenario soaks, which also "
        "carry `slow`)")
    config.addinivalue_line(
        "markers",
        "fleet: multi-replica sidecar fleet tests (checkpoint migration, "
        "consistent-hash failover, rolling restarts across N in-process "
        "replicas — deterministic; tier-1 eligible except soaks that also "
        "carry `slow`)")
    config.addinivalue_line(
        "markers",
        "audit: anti-entropy tests (seeded state corruption + device-loss "
        "chaos against the StateAuditor and the degradation ladder — "
        "deterministic: fixed seeds, fake clock — tier-1 eligible)")
