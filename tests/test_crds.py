"""CRD manifest generation (pkg/apis/crds parity): the checked-in YAML must
match the generator, and the schema must encode the validation battery's
accept/reject rules."""

import os

import pytest
import yaml

from karpenter_tpu.api import crds

HERE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "karpenter_tpu", "api", "crds")


class TestManifests:
    def test_checked_in_files_match_generator(self):
        for name, content in crds.manifests().items():
            with open(os.path.join(HERE, name)) as f:
                assert f.read() == content, \
                    f"{name} is stale; regenerate with python -m karpenter_tpu.api.crds"

    def test_crd_structure(self):
        for crd in (crds.nodepool_crd(), crds.nodeclaim_crd()):
            assert crd["apiVersion"] == "apiextensions.k8s.io/v1"
            assert crd["spec"]["scope"] == "Cluster"
            v = crd["spec"]["versions"][0]
            assert v["name"] == "v1" and v["served"] and v["storage"]
            assert "status" in v["subresources"]
            schema = v["schema"]["openAPIV3Schema"]
            assert set(schema["properties"]) >= {"spec", "status", "metadata"}

    def test_yaml_round_trips(self):
        for name, content in crds.manifests().items():
            assert yaml.safe_load(content)["kind"] == \
                "CustomResourceDefinition"


class TestSchemaRules:
    """The schema mirrors api/validation.py's battery."""

    def _req_schema(self):
        spec = crds.nodeclaim_crd()["spec"]["versions"][0]["schema"][
            "openAPIV3Schema"]["properties"]["spec"]
        return spec["properties"]["requirements"]["items"]

    def test_operator_enum_matches_validation(self):
        assert self._req_schema()["properties"]["operator"]["enum"] == \
            ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]

    def test_cel_rules_cover_value_constraints(self):
        rules = {r["message"]
                 for r in self._req_schema()["x-kubernetes-validations"]}
        assert any("In requires values" in m for m in rules)
        assert any("forbids values" in m for m in rules)
        assert any("Gt/Lt" in m for m in rules)

    def test_budget_pattern(self):
        import re
        pool = crds.nodepool_crd()["spec"]["versions"][0]["schema"][
            "openAPIV3Schema"]["properties"]["spec"]
        pat = pool["properties"]["disruption"]["properties"]["budgets"][
            "items"]["properties"]["nodes"]["pattern"]
        for ok in ("0", "10", "100%", "30%", "0%"):
            assert re.fullmatch(pat, ok), ok
        for bad in ("101%", "-1", "ten", "10%%", ""):
            assert not re.fullmatch(pat, bad), bad

    def test_duration_pattern(self):
        import re
        pat = crds._duration_schema()["pattern"]
        for ok in ("10m", "1h30m", "90s", "Never"):
            assert re.fullmatch(pat, ok), ok
        for bad in ("10", "never", "1d", ""):
            assert not re.fullmatch(pat, bad), bad
