"""Disruption solver: emptiness, consolidation, drift, budgets, PDB blocking
(reference shapes: disruption/{suite,consolidation,drift}_test.go)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED, NodeClaim
from karpenter_tpu.api.nodepool import Budget
from karpenter_tpu.api.objects import LabelSelector, Node, ObjectMeta, Pod
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_disruption import NodeClaimDisruptionMarker
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.disruption.controller import (DisruptionController,
                                                 OrchestrationQueue)
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    queue = OrchestrationQueue(store, cluster, clock)
    disruption = DisruptionController(store, cluster, provisioner, queue, clock)
    mgr.register(provisioner,
                 PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock),
                 NodeClaimDisruptionMarker(store, cluster, provider, clock),
                 NodeTermination(store, cluster, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.provisioner, e.queue, e.disruption = provisioner, queue, disruption
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def disrupt(env, rounds=8):
    """One disruption pass plus enough loop rounds to land its fallout
    (graceful commands wait the 15 s validation TTL before executing)."""
    for _ in range(rounds):
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        env.clock.step(8)  # cover the consolidation validation TTL


class TestEmptiness:
    def test_empty_node_deleted(self, env):
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        env.store.create(pod)
        settle(env)
        assert len(env.store.list(Node)) == 1
        env.store.delete(pod)
        settle(env)
        disrupt(env)
        assert env.store.list(Node) == []
        assert env.store.list(NodeClaim) == []

    def test_nonempty_node_not_deleted_by_emptiness(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="3000m", memory="128Mi"))
        settle(env)
        n_nodes = len(env.store.list(Node))
        disrupt(env, rounds=2)
        # consolidation may replace, but pods always stay scheduled
        assert len(env.store.list(Node)) >= 1
        for p in env.store.list(Pod):
            assert p.spec.node_name

    def test_do_not_disrupt_annotation_blocks(self, env):
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        env.store.create(pod)
        settle(env)
        nc = env.store.list(NodeClaim)[0]
        nc.metadata.annotations[api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.update(nc)
        node = env.store.list(Node)[0]
        node.metadata.annotations[api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.update(node)
        env.store.delete(pod)
        settle(env)
        disrupt(env, rounds=2)
        assert len(env.store.list(Node)) == 1


class TestConsolidation:
    def test_underutilized_node_replaced_by_cheaper(self, env):
        od = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}
        env.store.create(make_nodepool(name="default"))
        big = make_pod(cpu="3000m", memory="2Gi", node_selector=od)
        env.store.create(big)
        settle(env)
        first_node = env.store.list(Node)[0]
        big_it = first_node.metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
        # the big pod leaves; a tiny pod reuses the now-oversized node
        env.store.delete(big)
        small = make_pod(cpu="200m", memory="128Mi", node_selector=od)
        env.store.create(small)
        settle(env)
        assert env.store.get(Pod, small.name, small.namespace).spec.node_name \
            == first_node.name
        env.clock.step(21)  # past the nomination window (cluster.go nomination)
        disrupt(env)
        # consolidated onto a cheaper instance type
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        new_it = nodes[0].metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
        assert new_it != big_it
        pod = env.store.get(Pod, small.name, small.namespace)
        assert pod.spec.node_name == nodes[0].name

    def test_multi_node_consolidation_merges_three_into_one(self, env):
        od = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}
        env.store.create(make_nodepool(name="default"))
        bigs = []
        # three rounds, each filling one node with a big + small pair
        for i in range(3):
            big = make_pod(cpu="2500m", node_selector=od, name=f"big-{i}")
            env.store.create(big)
            env.store.create(make_pod(cpu="1000m", node_selector=od,
                                      name=f"small-{i}"))
            settle(env)
            bigs.append(big)
        assert len(env.store.list(Node)) == 3
        for big in bigs:
            env.store.delete(big)
        settle(env)
        env.clock.step(21)
        disrupt(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1, [n.name for n in nodes]
        for p in env.store.list(Pod):
            assert p.spec.node_name == nodes[0].name
        assert env.disruption.last_command is not None

    def test_budget_zero_blocks_consolidation(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        env.store.create(pool)
        big = make_pod(cpu="3000m")
        env.store.create(big)
        settle(env)
        env.store.delete(big)
        small = make_pod(cpu="200m")
        env.store.create(small)
        settle(env)
        env.clock.step(21)
        before = {n.name for n in env.store.list(Node)}
        disrupt(env, rounds=2)
        assert {n.name for n in env.store.list(Node)} == before

    def test_pdb_blocks_consolidation(self, env):
        env.store.create(make_nodepool(name="default"))
        big = make_pod(cpu="3000m")
        env.store.create(big)
        settle(env)
        env.store.delete(big)
        small = make_pod(cpu="200m", labels={"app": "guarded"})
        env.store.create(small)
        settle(env)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "guarded"}),
                         max_unavailable="0")))
        env.clock.step(21)
        before = {n.name for n in env.store.list(Node)}
        disrupt(env, rounds=2)
        assert {n.name for n in env.store.list(Node)} == before


class TestPrefixSimulator:
    def test_prefix_sim_matches_full_simulation(self, env):
        """PrefixSimulator must reproduce simulate_scheduling's results for
        every prefix length."""
        from karpenter_tpu.disruption.helpers import (get_candidates,
                                                      simulate_scheduling)
        from karpenter_tpu.disruption.prefix import PrefixSimulator
        od = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}
        env.store.create(make_nodepool(name="default"))
        for i in range(3):
            env.store.create(make_pod(cpu="2500m", node_selector=od,
                                      name=f"b-{i}"))
            env.store.create(make_pod(cpu="1000m", node_selector=od,
                                      name=f"s-{i}"))
            settle(env)
        for i in range(3):
            env.store.delete(env.store.get(Pod, f"b-{i}", "default"))
        settle(env)
        env.clock.step(21)
        method = env.disruption.methods[2]  # multi-node
        candidates = get_candidates(env.cluster, env.provisioner,
                                    method.should_disrupt)
        candidates = sorted(candidates, key=lambda c: c.disruption_cost)
        assert len(candidates) == 3
        sim = PrefixSimulator(env.cluster, env.provisioner, candidates)
        for mid in (1, 2, 3):
            fast, fast_err = sim.simulate(mid)
            slow, slow_err = simulate_scheduling(env.cluster, env.provisioner,
                                                 candidates[:mid])
            assert len(fast.new_nodeclaims) == len(slow.new_nodeclaims), mid
            assert fast_err == slow_err, mid
            fast_fill = sorted(len(nc.pods) for nc in fast.new_nodeclaims)
            slow_fill = sorted(len(nc.pods) for nc in slow.new_nodeclaims)
            assert fast_fill == slow_fill, mid


class TestValidation:
    def test_stale_empty_command_dropped_when_pod_lands(self, env):
        """A pod arriving during the 15s validation TTL invalidates the
        emptiness decision (validation.go candidates re-check)."""
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        env.store.create(pod)
        settle(env)
        node = env.store.list(Node)[0]
        env.store.delete(pod)
        settle(env)
        env.clock.step(21)
        # compute the emptiness command; it is now pending validation
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        # cluster moves: a new pod lands on the candidate before the TTL
        newpod = make_pod(cpu="500m")
        newpod.spec.node_name = node.name
        env.store.create(newpod)
        env.clock.step(16)
        env.disruption.reconcile()
        settle(env, rounds=2)
        # node survived: command was invalidated, nothing executed
        assert env.store.get(Node, node.name) is not None
        assert env.queue.items == []

    def test_empty_command_executes_after_ttl_when_still_valid(self, env):
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        env.store.create(pod)
        settle(env)
        env.store.delete(pod)
        settle(env)
        env.clock.step(21)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=3)
        assert env.store.list(Node) == []


class TestDrift:
    def test_drifted_nodeclaim_replaced(self, env):
        pool = make_nodepool(name="default")
        env.store.create(pool)
        pod = make_pod(cpu="500m")
        env.store.create(pod)
        settle(env)
        old_node = env.store.list(Node)[0].name
        # change the pool template -> static hash diff -> Drifted
        pool.spec.template.metadata_labels["team"] = "platform"
        env.store.update(pool)
        # marker recomputes on nodeclaim events; force a pass
        nc = env.store.list(NodeClaim)[0]
        env.store.update(nc)
        settle(env)
        assert env.store.list(NodeClaim)[0].conditions.is_true(COND_DRIFTED)
        disrupt(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].name != old_node
        pod_live = env.store.get(Pod, pod.name, pod.namespace)
        assert pod_live.spec.node_name == nodes[0].name
        # replacement carries the new template label
        assert nodes[0].metadata.labels.get("team") == "platform"
