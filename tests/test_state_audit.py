"""Anti-entropy tests (ISSUE 20): the StateAuditor corruption matrix and
the device-loss degradation ladder.

One contract throughout: a seeded fault injected into the warm state
(state/audit.py layer list) is DETECTED before the corrupt entry reaches
a solve, quarantined with exactly one incident (metric + StateCorruption
event + flight dump), and the pass still makes decisions bit-identical
to a cold solve — ``ChurnEnv.solve_pair`` asserts that parity on every
call, so every test here is also a decision-parity test. The device half
drives ``resilient_precompute`` down the ladder (mesh -> carve -> single
-> host oracle) with per-device breakers and half-open re-admission.

Everything is deterministic: fixed corruptor/auditor seeds, FakeClock
breaker clocks, the conftest 8-device CPU mesh. Tier-1 eligible.
"""

import random

import jax
import numpy as np
import pytest

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.metrics.registry import STATE_AUDIT
from karpenter_tpu.ops import binpack
from karpenter_tpu.parallel import mesh as mesh_mod
from karpenter_tpu.parallel.mesh import (DeviceLadderExhausted,
                                         device_breaker, make_solver_mesh,
                                         resilient_precompute)
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.sim import ScenarioError, parse_scenario
from karpenter_tpu.state.audit import LAYERS, StateAuditor, content_digest
from karpenter_tpu.utils.chaos import DeviceKiller, StateCorruptor
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pods
from test_parallel_mesh import _problem
from test_problem_state import ChurnEnv, deployment
from test_sim import _doc

pytestmark = pytest.mark.audit


class _FakeFlightRec:
    def __init__(self):
        self.captures = []

    def capture_corruption(self, layer, detail, seq=0):
        self.captures.append((layer, detail, seq))


def _warm_env(n_nodes=6, auditor_seed=3, recorder=None, flightrec=None):
    """A ChurnEnv with an attached auditor, warmed for two passes so every
    layer is hot (cached rows + recorded digests + resident stacks + topo
    memos + a warm-pack seed) before a fault is injected."""
    env = ChurnEnv(n_nodes=n_nodes, pods_per_node=2)
    auditor = StateAuditor(seed=auditor_seed, now=env.clock.now,
                           recorder=recorder,
                           flightrec=flightrec).attach(env.ps.plane)
    # zone spread keeps the topo-memo layer live; the plain group keeps
    # multiple group rows cached
    batch = deployment("web", 6, spread_key="zone") + deployment("api", 3)
    env.solve_pair(batch)
    env.solve_pair(batch)
    return env, auditor, batch


def _corrupt_metric():
    return {layer: STATE_AUDIT.value({"layer": layer, "outcome": "corrupt"})
            for layer in LAYERS}


# -- the per-layer corruption matrix -----------------------------------------


class TestCorruptionMatrix:
    @pytest.mark.parametrize("layer", LAYERS)
    def test_fault_detected_quarantined_healed(self, layer):
        rec = Recorder()
        flight = _FakeFlightRec()
        env, auditor, batch = _warm_env(recorder=rec, flightrec=flight)
        injected = StateCorruptor(seed=7).corrupt(
            env.ps.plane, handle=env.ps, layer=layer, count=1)
        assert injected and injected[0]["layer"] == layer, \
            f"no live candidate in layer {layer} after warmup"
        before = _corrupt_metric()
        n_events = len(rec.events)

        # the corrupted pass: detection BEFORE serve, decisions still
        # bit-identical to the cold control (solve_pair asserts parity)
        env.solve_pair(batch)
        assert len(auditor.incidents) == 1, auditor.incidents
        assert auditor.incidents[0]["layer"] == layer
        after = _corrupt_metric()
        assert after[layer] == before[layer] + 1
        for other in LAYERS:
            if other != layer:
                assert after[other] == before[other], other
        published = rec.events[n_events:]
        assert [e.reason for e in published] == ["StateCorruption"]
        assert published[0].object_name == layer
        assert published[0].type == "Warning"
        assert flight.captures == [
            (layer, auditor.incidents[0]["detail"], 1)]

        # heal within one pass: the quarantined layer rebuilt cold, so
        # the next clean pass detects nothing and stays in parity
        env.solve_pair(batch)
        assert len(auditor.incidents) == 1
        assert _corrupt_metric()[layer] == before[layer] + 1

    @pytest.mark.parametrize("kind", StateCorruptor.KINDS)
    def test_node_rows_every_fault_kind_detected(self, kind):
        """Directed kinds on the highest-traffic layer: the in-place byte
        flip, the token-preserving stale value, and the torn-write
        truncation all fail the serve-time digest."""
        env, auditor, batch = _warm_env()
        corruptor = StateCorruptor(seed=11)
        rec = corruptor._corrupt_node_rows(env.ps.plane, kind)
        assert rec is not None and rec["kind"] == kind
        env.solve_pair(batch)
        assert [i["layer"] for i in auditor.incidents] == ["node_rows"]

    def test_prev_generation_row_served_is_digest_checked(self):
        """A row served from the PREV generation (cur misses, prev hits —
        the cross-pass reuse path) passes through the same serve-time
        digest check as a cur hit. The corruptor only targets cur, so
        this pins the prev branch by hand."""
        env, auditor, batch = _warm_env()
        cache = next(iter(env.ps.plane._node_caches.values()))
        assert cache.cur, "warmup left no cur-generation rows"
        key = sorted(cache.cur, key=repr)[0]
        row = cache.cur.pop(key)
        assert len(row) > 5, "auditor-attached rows must carry a digest"
        # stale_value analog: content perturbed, rev token + digest kept
        cache.prev[key] = row[:3] + (int(row[3]) + 1,) + row[4:]
        env.solve_pair(batch)
        assert [i["layer"] for i in auditor.incidents] == ["node_rows"]

    def test_repeat_incidents_defeat_event_dedupe(self):
        """Two distinct corruptions of the SAME layer publish two
        StateCorruption events through a real Recorder: the incident
        sequence number rides the dedupe key, so the 120s TTL dedupe
        (same object, same reason) cannot swallow the second one."""
        rec = Recorder()
        env, auditor, batch = _warm_env(recorder=rec)
        corruptor = StateCorruptor(seed=5)
        for expected in (1, 2):
            injected = corruptor.corrupt(env.ps.plane, layer="node_rows",
                                         count=1)
            assert injected, "no node row left to corrupt"
            env.solve_pair(batch)
            got = [e for e in rec.events if e.reason == "StateCorruption"]
            assert len(got) == expected, [e.message for e in got]
        assert len(auditor.incidents) == 2

    def test_shadow_audit_covers_clean_passes(self):
        """Fault-free passes still pay the sampled shadow audits: cold
        re-encodes byte-compared against the caches, counted under
        outcome="audited" — the stale-build detector that digest checks
        alone cannot provide."""
        env, auditor, batch = _warm_env()
        env.solve_pair(batch)
        assert not auditor.incidents
        assert auditor.stats["audited:node_rows"] > 0
        assert auditor.stats["audited:group_rows"] > 0
        assert auditor.stats["audited:topo_memo"] > 0
        assert auditor.stats["audited:warm_checkpoint"] > 0


# -- the seeded soak ---------------------------------------------------------


class TestSoak:
    def test_soak_detects_every_fault_with_zero_wrong_decisions(self):
        """24 churn-free passes with seeded faults injected on ~40% of
        them (every layer, every kind, cur-targeted): each fault is
        detected within the pass it would first be served in, exactly
        once, and every pass — corrupted or clean — stays bit-identical
        to the cold control."""
        env, auditor, batch = _warm_env(auditor_seed=5)
        corruptor = StateCorruptor(seed=13)
        schedule = random.Random(99)
        injected_total = 0
        for _ in range(24):
            if schedule.random() < 0.4:
                injected_total += len(corruptor.corrupt(
                    env.ps.plane, handle=env.ps, layer="all", count=1))
            env.solve_pair(batch)  # parity asserted inside
            # detect-within-one-pass AND exactly-once, checked every pass
            assert len(auditor.incidents) == injected_total
        assert injected_total >= 5, "soak schedule injected too little"
        assert {i["layer"] for i in auditor.incidents} >= \
            {"node_rows", "group_rows"}


# -- the device-loss degradation ladder --------------------------------------


@pytest.fixture
def killer():
    k = DeviceKiller()
    prev = binpack.install_device_chaos(k)
    mesh_mod.reset_device_breakers()
    yield k
    binpack.install_device_chaos(prev)
    mesh_mod.reset_device_breakers()


def _device_ids(mesh):
    return sorted(int(d.id) for d in mesh.devices.flat)


PARITY_FIELDS = ("compat_tm", "it_ok", "ppn", "it_ok_z", "zone_adm")


class TestDeviceLadder:
    def test_mid_solve_kill_degrades_to_carve_with_parity(self, killer):
        problem = _problem()
        mesh = make_solver_mesh(8)
        ids = _device_ids(mesh)
        ref = binpack.precompute(problem)
        before = STATE_AUDIT.value({"layer": "device", "outcome": "killed"})
        killer.kill(ids[0])
        out = resilient_precompute(problem, mesh)
        for f in PARITY_FIELDS:
            np.testing.assert_array_equal(getattr(out, f), getattr(ref, f))
        assert STATE_AUDIT.value(
            {"layer": "device", "outcome": "killed"}) == before + 1
        # the dead device fed its OWN breaker; survivors stayed clean
        assert device_breaker(ids[0])._failures == 1
        assert all(device_breaker(i)._failures == 0 for i in ids[1:])

    def test_all_but_one_dead_lands_on_single_rung(self, killer):
        problem = _problem()
        mesh = make_solver_mesh(8)
        ids = _device_ids(mesh)
        ref = binpack.precompute(problem)
        before = STATE_AUDIT.value({"layer": "device", "outcome": "single"})
        for i in ids[:-1]:
            killer.kill(i)
        out = resilient_precompute(problem, mesh)
        for f in PARITY_FIELDS:
            np.testing.assert_array_equal(getattr(out, f), getattr(ref, f))
        assert STATE_AUDIT.value(
            {"layer": "device", "outcome": "single"}) == before + 1

    def test_breaker_opens_for_dead_device_only(self, killer):
        problem = _problem()
        mesh = make_solver_mesh(8)
        ids = _device_ids(mesh)
        killer.kill(ids[0])
        for _ in range(mesh_mod.DEVICE_BREAKER_THRESHOLD):
            resilient_precompute(problem, mesh)
        assert device_breaker(ids[0]).state == "open"
        assert all(device_breaker(i).state == "closed" for i in ids[1:])
        # with the breaker open the dead device is excluded up-front:
        # the pass degrades without even probing it
        counted = killer.counts[ids[0]]
        resilient_precompute(problem, mesh)
        assert killer.counts[ids[0]] == counted

    def test_half_open_probe_readmits_revived_device(self, killer):
        problem = _problem()
        mesh = make_solver_mesh(8)
        ids = _device_ids(mesh)
        clock = FakeClock()
        # pre-create the dead device's breaker on the fake clock so the
        # cooldown is drivable (device_breaker caches by id)
        b = device_breaker(ids[0], now=clock.now)
        killer.kill(ids[0])
        for _ in range(mesh_mod.DEVICE_BREAKER_THRESHOLD):
            resilient_precompute(problem, mesh)
        assert b.state == "open"
        killer.revive(ids[0])
        # still open inside the cooldown: the revived device waits
        resilient_precompute(problem, mesh)
        assert b.state == "open"
        clock.step(mesh_mod.DEVICE_BREAKER_COOLDOWN + 1)
        before = STATE_AUDIT.value(
            {"layer": "device", "outcome": "readmitted"})
        ref = binpack.precompute(problem)
        out = resilient_precompute(problem, mesh)
        for f in PARITY_FIELDS:
            np.testing.assert_array_equal(getattr(out, f), getattr(ref, f))
        assert b.state == "closed"
        assert STATE_AUDIT.value(
            {"layer": "device", "outcome": "readmitted"}) == before + 1

    def test_exhausted_ladder_raises(self, killer):
        problem = _problem()
        mesh = make_solver_mesh(8)
        for i in _device_ids(mesh):
            killer.kill(i)
        with pytest.raises(DeviceLadderExhausted):
            resilient_precompute(problem, mesh)

    def test_exhausted_ladder_serves_host_without_global_breaker(
            self, killer):
        """Every device dead: the solve completes through the host oracle
        and the GLOBAL solver breaker stays untouched — each lost device
        already fed its own, and double-counting would condemn the next
        healthy pass to the host path too."""
        its = construct_instance_types()[:30]
        ts = TensorScheduler([make_nodepool(name="default")],
                             {"default": its})
        ts.mesh = make_solver_mesh(8)
        for d in ts.mesh.devices.flat:
            killer.kill(int(d.id))
        results = ts.solve(make_pods(5, cpu="500m"))
        assert "device ladder exhausted" in ts.fallback_reason
        assert not results.pod_errors, results.pod_errors
        assert results.new_nodeclaims
        assert ts.circuit.state == "closed"
        assert ts.circuit._failures == 0


# -- sim integration: schema rejects + ledger digest parity ------------------


class TestSimChaosEvents:
    def test_corrupt_state_requires_tensor_backend(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 50, "kind": "corrupt_state"})
        with pytest.raises(ScenarioError,
                           match=r"requires 'backend: tensor'"):
            parse_scenario(doc)

    def test_kill_device_requires_tensor_backend(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 50, "kind": "kill_device",
                              "duration": 60})
        with pytest.raises(ScenarioError,
                           match=r"requires 'backend: tensor'"):
            parse_scenario(doc)

    def test_bad_layer_rejected(self):
        doc = _doc()
        doc["events"].append({"at": 50, "kind": "corrupt_state",
                              "layer": "node_rowz"})
        with pytest.raises(ScenarioError,
                           match=r"'layer'.*one of 'node_rows'"):
            parse_scenario(doc)

    def test_kill_device_missing_duration_rejected(self):
        doc = _doc()
        doc["events"].append({"at": 50, "kind": "kill_device"})
        with pytest.raises(ScenarioError, match=r"'duration'"):
            parse_scenario(doc)

    def test_chaos_run_ledger_digest_matches_fault_free_run(self):
        """The unledgered-chaos contract end to end: a scenario with
        corrupt_state and kill_device events produces a ledger digest
        byte-identical to the same scenario with the chaos stripped —
        audits detect and heal without changing one decision, and the
        ladder re-places the killed window's solves with parity."""
        from karpenter_tpu.sim import FleetSimulator

        def doc(with_chaos):
            events = [
                {"at": 5, "kind": "deploy", "name": "web", "replicas": 8,
                 "cpu": "500m", "memory": "256Mi"},
                {"at": 180, "kind": "scale", "name": "web", "replicas": 11},
                {"at": 330, "kind": "scale", "name": "web", "replicas": 14},
                {"at": 480, "kind": "scale", "name": "web", "replicas": 9},
            ]
            if with_chaos:
                events += [
                    {"at": 150, "kind": "corrupt_state", "count": 2},
                    {"at": 300, "kind": "kill_device", "device": 0,
                     "duration": 150},
                ]
            return _doc(duration=600.0, seed=20, events=events)

        reports = {}
        for with_chaos in (True, False):
            sim = FleetSimulator(parse_scenario(doc(with_chaos)))
            reports[with_chaos] = sim.run()
            if with_chaos:
                assert sim.state_corruptor.injected, \
                    "chaos run injected nothing"
        assert reports[True]["ledger_digest"] == \
            reports[False]["ledger_digest"]
        assert reports[True]["final"] == reports[False]["final"]


# -- digest unit properties --------------------------------------------------


class TestContentDigest:
    def test_ndarray_content_and_dtype_sensitive(self):
        a = np.arange(8, dtype=np.int64)
        assert content_digest(a) == content_digest(a.copy())
        assert content_digest(a) != content_digest(a.astype(np.int32))
        b = a.copy()
        b[3] ^= 1
        assert content_digest(a) != content_digest(b)

    def test_noncontiguous_view_digests_like_its_copy(self):
        a = np.arange(16, dtype=np.float64).reshape(4, 4)
        view = a[:, ::2]
        assert not view.flags.c_contiguous
        assert content_digest(view) == content_digest(
            np.ascontiguousarray(view))

    def test_container_order_and_type_sensitivity(self):
        assert content_digest((1, 2.0, "x")) == content_digest((1, 2.0, "x"))
        assert content_digest([1, 2]) != content_digest([2, 1])
        assert content_digest({"a": 1, "b": 2}) == \
            content_digest({"b": 2, "a": 1})
        assert content_digest(1) != content_digest(True)
        assert content_digest(None) != content_digest(0)
