"""Observability floor: structured logging, /metrics + health HTTP serving,
cloudprovider metrics decorator (VERDICT r2 missing #1-#3).

Reference shapes: operator/logging/logging.go:55-124, operator.go:142-175,
cloudprovider/metrics/cloudprovider.go:33-272."""

import io
import json
import urllib.request

import pytest

from karpenter_tpu import logging as klog
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.metrics import (ERRORS_TOTAL, METHOD_DURATION,
                                                 decorate)
from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_tpu.metrics.registry import REGISTRY, Registry
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.operator.server import ServingGroup
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.injection import controller_name, with_controller

from factories import make_nodepool, make_pod, make_pods
from test_operator import settle


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestLogging:
    def test_json_line_structure(self):
        buf = io.StringIO()
        klog.configure("info", stream=buf)
        klog.get_logger("provisioner").info("scheduled pod batch",
                                            pods=12, nodeclaims=3)
        rec = json.loads(buf.getvalue().strip())
        assert rec["level"] == "INFO"
        assert rec["logger"] == "karpenter.provisioner"
        assert rec["message"] == "scheduled pod batch"
        assert rec["pods"] == 12 and rec["nodeclaims"] == 3
        assert "time" in rec

    def test_level_filtering(self):
        buf = io.StringIO()
        klog.configure("error", stream=buf)
        log = klog.get_logger("x")
        log.info("quiet")
        log.debug("quieter")
        assert buf.getvalue() == ""
        log.error("loud")
        assert json.loads(buf.getvalue())["level"] == "ERROR"

    def test_with_values_binds_context(self):
        buf = io.StringIO()
        klog.configure("info", stream=buf)
        log = klog.get_logger("y").with_values(node="n-1")
        log.info("terminated node")
        assert json.loads(buf.getvalue())["node"] == "n-1"

    def test_nop_logger_silent(self):
        buf = io.StringIO()
        klog.configure("debug", stream=buf)
        klog.NOP.error("should vanish")
        assert buf.getvalue() == ""


class TestInjection:
    def test_controller_name_scoped(self):
        assert controller_name() == ""
        with with_controller("provisioner"):
            assert controller_name() == "provisioner"
            with with_controller("inner"):
                assert controller_name() == "inner"
            assert controller_name() == "provisioner"
        assert controller_name() == ""


class TestServing:
    def test_metrics_endpoint_serves_registry(self):
        reg = Registry()
        reg.counter("test_serving_total", "t").inc()
        sg = ServingGroup(0, 0, registry=reg).start()
        try:
            status, body = _get(
                f"http://127.0.0.1:{sg.metrics_port}/metrics")
            assert status == 200
            assert "test_serving_total 1.0" in body
        finally:
            sg.stop()

    def test_health_probes(self):
        ready = {"ok": False}
        sg = ServingGroup(0, 0, ready=lambda: ready["ok"]).start()
        try:
            status, body = _get(f"http://127.0.0.1:{sg.health_port}/healthz")
            assert status == 200 and body == "ok"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{sg.health_port}/readyz")
            assert ei.value.code == 503
            ready["ok"] = True
            status, _ = _get(f"http://127.0.0.1:{sg.health_port}/readyz")
            assert status == 200
        finally:
            sg.stop()

    def test_unknown_path_404(self):
        sg = ServingGroup(0, 0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{sg.metrics_port}/nope")
            assert ei.value.code == 404
        finally:
            sg.stop()


class TestOperatorServing:
    def test_operator_e2e_metrics_over_http(self):
        """VERDICT done-criterion: curl :PORT/metrics works against a live
        operator after a solve."""
        op = Operator(options=Options(metrics_port=0, health_probe_port=0),
                      clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        for p in make_pods(3, cpu="500m"):
            op.store.create(p)
        settle(op)
        sg = op.start_serving()
        try:
            status, body = _get(
                f"http://127.0.0.1:{sg.metrics_port}/metrics")
            assert status == 200
            assert "karpenter_nodeclaims_created_total" in body
            assert "karpenter_cloudprovider_duration_seconds" in body
            status, _ = _get(f"http://127.0.0.1:{sg.health_port}/healthz")
            assert status == 200
        finally:
            op.stop_serving()

    def test_solve_logs_summary_line(self):
        op = Operator(clock=FakeClock())
        buf = io.StringIO()
        klog.configure("info", stream=buf)  # after Operator's configure
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        batch = [l for l in lines if l["message"] == "scheduled pod batch"]
        assert batch, lines
        assert batch[0]["pods"] >= 1
        assert batch[0]["logger"] == "karpenter.provisioner"
        assert "fallback_reason" in batch[0]


class TestCloudProviderDecorator:
    def test_spi_calls_timed_with_controller_label(self):
        cp = decorate(FakeCloudProvider())
        labels = {"controller": "provisioner", "method": "get_instance_types",
                  "provider": "fake"}
        before = METHOD_DURATION.count(labels)
        with with_controller("provisioner"):
            cp.get_instance_types(make_nodepool())
        assert METHOD_DURATION.count(labels) == before + 1

    def test_typed_errors_counted_and_propagated(self):
        cp = decorate(FakeCloudProvider())
        cp.next_get_err = NodeClaimNotFoundError("gone")
        labels = {"controller": "", "method": "get", "provider": "fake",
                  "error": "NodeClaimNotFoundError"}
        before = ERRORS_TOTAL.value(labels)
        with pytest.raises(NodeClaimNotFoundError):
            cp.get("fake://nope")
        assert ERRORS_TOTAL.value(labels) == before + 1

    def test_passthrough_attributes(self):
        inner = FakeCloudProvider()
        cp = decorate(inner)
        cp.next_create_err = ValueError("boom")   # set through the proxy
        assert inner.next_create_err is not None
        assert cp.name == "fake"
        assert cp.created is inner.created


class TestDebugEndpoints:
    def test_debug_stacks_and_timers_gated_by_profiling(self):
        """pprof analog (operator.go:159-175): /debug/* serves only with
        --enable-profiling."""
        import urllib.error
        import urllib.request

        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options

        op = Operator(options=Options(metrics_port=0, health_probe_port=0,
                                      enable_profiling=True))
        op.start_serving()
        try:
            base = f"http://127.0.0.1:{op.serving.metrics_port}"
            stacks = urllib.request.urlopen(f"{base}/debug/stacks", timeout=5).read()
            assert b"Thread" in stacks or b"File" in stacks
            timers = urllib.request.urlopen(f"{base}/debug/timers", timeout=5).read()
            assert b"pending_timers" in timers
        finally:
            op.stop_serving()

        off = Operator(options=Options(metrics_port=0, health_probe_port=0))
        off.start_serving()
        try:
            base = f"http://127.0.0.1:{off.serving.metrics_port}"
            try:
                urllib.request.urlopen(f"{base}/debug/stacks", timeout=5)
                raise AssertionError("expected 404 without profiling")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            off.stop_serving()

    def test_debug_profile_samples_busy_thread(self):
        """curl :PORT/debug/profile?seconds=N returns a usable sampling
        profile (folded stacks incl. the busy function) — VERDICT r4 #10."""
        import threading
        import urllib.request

        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options

        stop = threading.Event()

        def busy_spinning_loop():
            x = 0
            while not stop.is_set():
                x += 1
            return x

        t = threading.Thread(target=busy_spinning_loop, daemon=True,
                             name="busy-worker")
        t.start()
        op = Operator(options=Options(metrics_port=0, health_probe_port=0,
                                      enable_profiling=True))
        op.start_serving()
        try:
            base = f"http://127.0.0.1:{op.serving.metrics_port}"
            body = urllib.request.urlopen(
                f"{base}/debug/profile?seconds=0.4", timeout=15).read()
            text = body.decode()
            assert "folded stacks" in text
            assert "busy_spinning_loop" in text
            # folded format: semicolon-joined frames, trailing sample count
            line = next(l for l in text.splitlines()
                        if "busy_spinning_loop" in l)
            assert line.rsplit(" ", 1)[1].isdigit()
            # bad input is a 400, not a crash
            import urllib.error
            try:
                urllib.request.urlopen(f"{base}/debug/profile?seconds=x",
                                       timeout=5)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            stop.set()
            op.stop_serving()
