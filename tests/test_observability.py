"""Observability floor: structured logging, /metrics + health HTTP serving,
cloudprovider metrics decorator (VERDICT r2 missing #1-#3).

Reference shapes: operator/logging/logging.go:55-124, operator.go:142-175,
cloudprovider/metrics/cloudprovider.go:33-272."""

import io
import json
import urllib.request

import pytest

from karpenter_tpu import logging as klog
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.metrics import (ERRORS_TOTAL, METHOD_DURATION,
                                                 decorate)
from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_tpu.metrics.registry import REGISTRY, Registry
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.operator.server import ServingGroup
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.injection import controller_name, with_controller

from factories import make_nodepool, make_pod, make_pods
from test_operator import settle


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestLogging:
    def test_json_line_structure(self):
        buf = io.StringIO()
        klog.configure("info", stream=buf)
        klog.get_logger("provisioner").info("scheduled pod batch",
                                            pods=12, nodeclaims=3)
        rec = json.loads(buf.getvalue().strip())
        assert rec["level"] == "INFO"
        assert rec["logger"] == "karpenter.provisioner"
        assert rec["message"] == "scheduled pod batch"
        assert rec["pods"] == 12 and rec["nodeclaims"] == 3
        assert "time" in rec

    def test_level_filtering(self):
        buf = io.StringIO()
        klog.configure("error", stream=buf)
        log = klog.get_logger("x")
        log.info("quiet")
        log.debug("quieter")
        assert buf.getvalue() == ""
        log.error("loud")
        assert json.loads(buf.getvalue())["level"] == "ERROR"

    def test_with_values_binds_context(self):
        buf = io.StringIO()
        klog.configure("info", stream=buf)
        log = klog.get_logger("y").with_values(node="n-1")
        log.info("terminated node")
        assert json.loads(buf.getvalue())["node"] == "n-1"

    def test_nop_logger_silent(self):
        buf = io.StringIO()
        klog.configure("debug", stream=buf)
        klog.NOP.error("should vanish")
        assert buf.getvalue() == ""


class TestInjection:
    def test_controller_name_scoped(self):
        assert controller_name() == ""
        with with_controller("provisioner"):
            assert controller_name() == "provisioner"
            with with_controller("inner"):
                assert controller_name() == "inner"
            assert controller_name() == "provisioner"
        assert controller_name() == ""


class TestServing:
    def test_metrics_endpoint_serves_registry(self):
        reg = Registry()
        reg.counter("test_serving_total", "t").inc()
        sg = ServingGroup(0, 0, registry=reg).start()
        try:
            status, body = _get(
                f"http://127.0.0.1:{sg.metrics_port}/metrics")
            assert status == 200
            assert "test_serving_total 1.0" in body
        finally:
            sg.stop()

    def test_health_probes(self):
        ready = {"ok": False}
        sg = ServingGroup(0, 0, ready=lambda: ready["ok"]).start()
        try:
            status, body = _get(f"http://127.0.0.1:{sg.health_port}/healthz")
            assert status == 200 and body == "ok"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{sg.health_port}/readyz")
            assert ei.value.code == 503
            ready["ok"] = True
            status, _ = _get(f"http://127.0.0.1:{sg.health_port}/readyz")
            assert status == 200
        finally:
            sg.stop()

    def test_unknown_path_404(self):
        sg = ServingGroup(0, 0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{sg.metrics_port}/nope")
            assert ei.value.code == 404
        finally:
            sg.stop()


class TestOperatorServing:
    def test_operator_e2e_metrics_over_http(self):
        """VERDICT done-criterion: curl :PORT/metrics works against a live
        operator after a solve."""
        op = Operator(options=Options(metrics_port=0, health_probe_port=0),
                      clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        for p in make_pods(3, cpu="500m"):
            op.store.create(p)
        settle(op)
        sg = op.start_serving()
        try:
            status, body = _get(
                f"http://127.0.0.1:{sg.metrics_port}/metrics")
            assert status == 200
            assert "karpenter_nodeclaims_created_total" in body
            assert "karpenter_cloudprovider_duration_seconds" in body
            status, _ = _get(f"http://127.0.0.1:{sg.health_port}/healthz")
            assert status == 200
        finally:
            op.stop_serving()

    def test_solve_logs_summary_line(self):
        op = Operator(clock=FakeClock())
        buf = io.StringIO()
        klog.configure("info", stream=buf)  # after Operator's configure
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        batch = [l for l in lines if l["message"] == "scheduled pod batch"]
        assert batch, lines
        assert batch[0]["pods"] >= 1
        assert batch[0]["logger"] == "karpenter.provisioner"
        assert "fallback_reason" in batch[0]


class TestExpositionGolden:
    """Prometheus text-exposition golden test (ISSUE 7 satellite): the
    format was only ever eyeballed — pin counter/gauge/histogram rendering
    (_bucket/_sum/_count, cumulative bucket counts, label sorting and
    escaping) byte for byte."""

    def test_golden_rendering(self):
        reg = Registry()
        c = reg.counter("demo_total", "demo counter", ("op",))
        c.inc({"op": "read"})
        c.inc({"op": "read"}, 2.0)
        c.inc({"op": 'we"ird\\path\nx'})  # escaping: quote, backslash, LF
        g = reg.gauge("demo_gauge", "demo gauge")
        g.set(2.5)
        h = reg.histogram("demo_seconds", "demo histogram", ("k",),
                          buckets=(0.1, 1.0))
        h.observe(0.05, {"k": "a"})   # lands in both buckets
        h.observe(0.5, {"k": "a"})    # lands in 1.0 only
        h.observe(5.0, {"k": "a"})    # +Inf only
        expected = "\n".join([
            "# HELP demo_gauge demo gauge",
            "# TYPE demo_gauge gauge",
            "demo_gauge 2.5",
            "# HELP demo_seconds demo histogram",
            "# TYPE demo_seconds histogram",
            'demo_seconds_bucket{k="a",le="0.1"} 1',
            'demo_seconds_bucket{k="a",le="1.0"} 2',
            'demo_seconds_bucket{k="a",le="+Inf"} 3',
            'demo_seconds_sum{k="a"} 5.55',
            'demo_seconds_count{k="a"} 3',
            "# HELP demo_total demo counter",
            "# TYPE demo_total counter",
            'demo_total{op="read"} 3.0',
            'demo_total{op="we\\"ird\\\\path\\nx"} 1.0',
            "",
        ])
        assert reg.expose() == expected

    def test_series_pruning_drops_from_exposition(self):
        reg = Registry()
        g = reg.gauge("demo_prune", "g", ("n",))
        g.set(1.0, {"n": "a"})
        g.set(2.0, {"n": "b"})
        assert 'demo_prune{n="a"} 1.0' in reg.expose()
        g.prune([{"n": "b"}])
        text = reg.expose()
        assert 'n="a"' not in text
        assert 'demo_prune{n="b"} 2.0' in text

    def test_escaped_labels_stay_single_line(self):
        reg = Registry()
        c = reg.counter("demo_lines_total", "c", ("msg",))
        c.inc({"msg": "two\nlines"})
        lines = reg.expose().splitlines()
        series = [l for l in lines if l.startswith("demo_lines_total{")]
        assert series == ['demo_lines_total{msg="two\\nlines"} 1.0']


class TestMetricsReadmeDrift:
    """ISSUE 7 satellite: every registered karpenter_ metric family must
    appear in the README Observability table, or the docs have drifted."""

    def test_every_registered_metric_documented(self):
        import os
        # importing the registering modules populates the global REGISTRY
        import karpenter_tpu.cloudprovider.metrics  # noqa: F401
        import karpenter_tpu.controllers.metrics_exporters  # noqa: F401
        import karpenter_tpu.metrics.registry as registry
        readme = open(os.path.join(os.path.dirname(registry.__file__),
                                   "..", "..", "README.md")).read()
        names = [n for n in registry.REGISTRY._metrics
                 if n.startswith("karpenter_")]
        assert len(names) >= 35  # the roster as of this PR
        missing = [n for n in names if n not in readme]
        assert not missing, (
            f"metrics missing from the README Observability table: "
            f"{missing}")


class TestDebugEndpointsSmoke:
    """Consolidated smoke for every /debug/* operational surface against
    ONE live metrics server (ISSUE 7 satellite), including the
    HTTP-thread-vs-operator-loop materialize retry path."""

    @pytest.fixture()
    def live_op(self):
        from test_operator import settle

        from factories import make_pods
        op = Operator(options=Options(metrics_port=0, health_probe_port=0,
                                      slo_budgets="provisioner.pass=60.0"),
                      clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        for p in make_pods(2, cpu="500m"):
            op.store.create(p)
        settle(op)
        op.start_serving()
        yield op
        op.stop_serving()

    def test_all_debug_endpoints_serve(self, live_op, tmp_path, monkeypatch):
        base = f"http://127.0.0.1:{live_op.serving.metrics_port}"

        status, body = _get(f"{base}/debug/deadletter")
        assert status == 200 and body.startswith("quarantined")

        status, body = _get(f"{base}/debug/offerings")
        assert status == 200 and body.startswith("unavailable")

        status, body = _get(f"{base}/debug/flightrecorder")
        assert status == 200 and "records" in body

        status, body = _get(f"{base}/debug/traces")
        assert status == 200 and body.startswith("traces")
        assert "provisioner.pass" in body

        status, body = _get(f"{base}/debug/traces?format=chrome")
        assert status == 200
        doc = json.loads(body)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "provisioner.pass" in names and "solve" in names
        trace_id = next(e["args"]["trace_id"] for e in doc["traceEvents"]
                        if e["name"] == "provisioner.pass")
        status, body = _get(f"{base}/debug/traces?trace_id={trace_id}")
        assert status == 200 and trace_id in body

        status, body = _get(f"{base}/debug/slo")
        assert status == 200
        slo = json.loads(body)
        assert slo["budgets"]["provisioner.pass"]["observed"] >= 1
        assert slo["budgets"]["provisioner.pass"]["budget_seconds"] == 60.0
        assert slo["breaches"] == []

        # the serving-thread materialize retry: the first two encode
        # attempts observe a concurrently-mutating store and raise; the
        # endpoint must still serve (recorder.py materialize retries x3)
        import karpenter_tpu.flightrec.record as rec_codec
        real = rec_codec.encode_solve_payload
        fails = {"n": 0}

        def flaky(*args, **kwargs):
            if fails["n"] < 2:
                fails["n"] += 1
                raise RuntimeError("dictionary changed size during iteration")
            return real(*args, **kwargs)

        monkeypatch.setattr(rec_codec, "encode_solve_payload", flaky)
        monkeypatch.setenv("KARPENTER_FLIGHTREC_DIR", str(tmp_path))
        status, body = _get(
            f"{base}/debug/flightrecorder?dump=1&name=smoke.jsonl")
        assert status == 200 and "dumped" in body
        assert fails["n"] == 2  # the retry path actually exercised
        assert (tmp_path / "smoke.jsonl").exists()

    def test_debug_404_without_attachments(self):
        sg = ServingGroup(0, 0).start()
        try:
            for path in ("/debug/traces", "/debug/slo",
                         "/debug/flightrecorder", "/debug/offerings",
                         "/debug/deadletter"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(f"http://127.0.0.1:{sg.metrics_port}{path}")
                assert ei.value.code == 404, path
        finally:
            sg.stop()


class TestCloudProviderDecorator:
    def test_spi_calls_timed_with_controller_label(self):
        cp = decorate(FakeCloudProvider())
        labels = {"controller": "provisioner", "method": "get_instance_types",
                  "provider": "fake"}
        before = METHOD_DURATION.count(labels)
        with with_controller("provisioner"):
            cp.get_instance_types(make_nodepool())
        assert METHOD_DURATION.count(labels) == before + 1

    def test_typed_errors_counted_and_propagated(self):
        cp = decorate(FakeCloudProvider())
        cp.next_get_err = NodeClaimNotFoundError("gone")
        labels = {"controller": "", "method": "get", "provider": "fake",
                  "error": "NodeClaimNotFoundError"}
        before = ERRORS_TOTAL.value(labels)
        with pytest.raises(NodeClaimNotFoundError):
            cp.get("fake://nope")
        assert ERRORS_TOTAL.value(labels) == before + 1

    def test_passthrough_attributes(self):
        inner = FakeCloudProvider()
        cp = decorate(inner)
        cp.next_create_err = ValueError("boom")   # set through the proxy
        assert inner.next_create_err is not None
        assert cp.name == "fake"
        assert cp.created is inner.created


class TestDebugEndpoints:
    def test_debug_stacks_and_timers_gated_by_profiling(self):
        """pprof analog (operator.go:159-175): /debug/* serves only with
        --enable-profiling."""
        import urllib.error
        import urllib.request

        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options

        op = Operator(options=Options(metrics_port=0, health_probe_port=0,
                                      enable_profiling=True))
        op.start_serving()
        try:
            base = f"http://127.0.0.1:{op.serving.metrics_port}"
            stacks = urllib.request.urlopen(f"{base}/debug/stacks", timeout=5).read()
            assert b"Thread" in stacks or b"File" in stacks
            timers = urllib.request.urlopen(f"{base}/debug/timers", timeout=5).read()
            assert b"pending_timers" in timers
        finally:
            op.stop_serving()

        off = Operator(options=Options(metrics_port=0, health_probe_port=0))
        off.start_serving()
        try:
            base = f"http://127.0.0.1:{off.serving.metrics_port}"
            try:
                urllib.request.urlopen(f"{base}/debug/stacks", timeout=5)
                raise AssertionError("expected 404 without profiling")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            off.stop_serving()

    def test_debug_profile_samples_busy_thread(self):
        """curl :PORT/debug/profile?seconds=N returns a usable sampling
        profile (folded stacks incl. the busy function) — VERDICT r4 #10."""
        import threading
        import urllib.request

        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options

        stop = threading.Event()

        def busy_spinning_loop():
            x = 0
            while not stop.is_set():
                x += 1
            return x

        t = threading.Thread(target=busy_spinning_loop, daemon=True,
                             name="busy-worker")
        t.start()
        op = Operator(options=Options(metrics_port=0, health_probe_port=0,
                                      enable_profiling=True))
        op.start_serving()
        try:
            base = f"http://127.0.0.1:{op.serving.metrics_port}"
            body = urllib.request.urlopen(
                f"{base}/debug/profile?seconds=0.4", timeout=15).read()
            text = body.decode()
            assert "folded stacks" in text
            assert "busy_spinning_loop" in text
            # folded format: semicolon-joined frames, trailing sample count
            line = next(l for l in text.splitlines()
                        if "busy_spinning_loop" in l)
            assert line.rsplit(" ", 1)[1].isdigit()
            # bad input is a 400, not a crash
            import urllib.error
            try:
                urllib.request.urlopen(f"{base}/debug/profile?seconds=x",
                                       timeout=5)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            stop.set()
            op.stop_serving()
