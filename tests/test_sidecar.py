"""Sidecar boundary: codec round-trip, remote solve parity, operator loop
over the gRPC backend."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.sidecar import codec
from karpenter_tpu.sidecar.client import RemoteScheduler
from karpenter_tpu.sidecar.server import serve
from karpenter_tpu.utils.clock import FakeClock

from factories import (affinity_term, make_nodepool, make_pod, make_pods,
                       spread_zone)


@pytest.fixture(scope="module")
def sidecar():
    server, port = serve(port=0)
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


class TestCodec:
    def test_pod_round_trip(self):
        pod = make_pod(cpu="500m", memory="1Gi", labels={"app": "x"},
                       node_selector={"zone": "a"},
                       spread=[spread_zone(key="app", value="x")],
                       pod_anti_affinity=[
                           affinity_term(api_labels.LABEL_HOSTNAME,
                                         key="app", value="x")])
        d = codec.pod_to_dict(pod)
        back = codec.pod_from_dict(d)
        assert back.uid == pod.uid
        assert back.requests() == pod.requests()
        assert back.spec.node_selector == pod.spec.node_selector
        assert len(back.spec.topology_spread_constraints) == 1
        assert back.spec.affinity.pod_anti_affinity.required[0].topology_key \
            == api_labels.LABEL_HOSTNAME
        assert codec.pod_to_dict(back) == d

    def test_instance_type_round_trip(self):
        it = construct_instance_types()[0]
        back = codec.instance_type_from_dict(codec.instance_type_to_dict(it))
        assert back.name == it.name
        assert back.capacity == it.capacity
        assert len(back.offerings) == len(it.offerings)
        assert back.allocatable() == it.allocatable()

    def test_nodepool_round_trip(self):
        pool = make_nodepool(name="p1", limits={"cpu": "100"}, weight=7)
        back = codec.nodepool_from_dict(codec.nodepool_to_dict(pool))
        assert back.name == "p1"
        assert back.spec.limits == pool.spec.limits
        assert back.spec.weight == 7


class TestRemoteSolve:
    def test_parity_with_local(self, sidecar):
        its = construct_instance_types()[:48]
        pool = make_nodepool(name="default")
        pods = (make_pods(10, cpu="500m", memory="256Mi")
                + make_pods(6, cpu="1000m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")]))
        local = TensorScheduler([pool], {"default": its}).solve(pods)
        remote = RemoteScheduler(sidecar, [pool], {"default": its}).solve(pods)
        assert len(remote.new_nodeclaims) == len(local.new_nodeclaims)
        assert remote.pod_errors == local.pod_errors
        # per-claim pod partitions match sizes
        assert sorted(len(nc.pods) for nc in remote.new_nodeclaims) == \
            sorted(len(nc.pods) for nc in local.new_nodeclaims)
        # the emitted API claims carry instance-type requirements
        api_nc = remote.new_nodeclaims[0].to_nodeclaim()
        keys = {r.key for r in api_nc.spec.requirements}
        assert api_labels.LABEL_INSTANCE_TYPE in keys

    def test_operator_over_sidecar_backend(self, sidecar):
        op = Operator(options=Options(solver_backend="sidecar",
                                      solver_address=sidecar),
                      clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        for p in make_pods(5, cpu="500m"):
            op.store.create(p)
        for _ in range(6):
            op.step()
            op.clock.step(1.1)
        op.step()
        assert all(p.spec.node_name for p in op.store.list(Pod))
        assert op.store.list(Node)
