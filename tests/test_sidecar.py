"""Sidecar boundary: codec round-trip, remote solve parity, operator loop
over the gRPC backend."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.sidecar import codec
from karpenter_tpu.sidecar.client import RemoteScheduler
from karpenter_tpu.sidecar.server import serve
from karpenter_tpu.utils.clock import FakeClock

from factories import (affinity_term, make_nodepool, make_pod, make_pods,
                       spread_zone)


@pytest.fixture(scope="module")
def sidecar():
    server, port = serve(port=0)
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


class TestCodec:
    def test_pod_round_trip(self):
        pod = make_pod(cpu="500m", memory="1Gi", labels={"app": "x"},
                       node_selector={"zone": "a"},
                       spread=[spread_zone(key="app", value="x")],
                       pod_anti_affinity=[
                           affinity_term(api_labels.LABEL_HOSTNAME,
                                         key="app", value="x")])
        d = codec.pod_to_dict(pod)
        back = codec.pod_from_dict(d)
        assert back.uid == pod.uid
        assert back.requests() == pod.requests()
        assert back.spec.node_selector == pod.spec.node_selector
        assert len(back.spec.topology_spread_constraints) == 1
        assert back.spec.affinity.pod_anti_affinity.required[0].topology_key \
            == api_labels.LABEL_HOSTNAME
        assert codec.pod_to_dict(back) == d

    def test_pod_batch_dedup_round_trip(self):
        """encode_pod_batch collapses deployment-stamped pods to one
        template and rebuilds them with SHARED spec sub-objects, so the
        server-side grouping signature bucketing stays O(1) per pod."""
        spread = [spread_zone(key="app", value="d0")]
        a = [make_pod(cpu="500m", labels={"app": "d0"}, spread=spread,
                      name=f"a-{i}") for i in range(5)]
        b = [make_pod(cpu="250m", labels={"app": "d1"}, name=f"b-{i}")
             for i in range(3)]
        wire = codec.encode_pod_batch(a + b)
        assert len(wire["templates"]) == 2
        assert len(wire["rows"]) == 8
        back = codec.decode_pod_batch(wire)
        assert [p.name for p in back] == [p.name for p in a + b]
        assert [p.uid for p in back] == [p.uid for p in a + b]
        assert back[0].requests() == a[0].requests()
        assert len(back[0].spec.topology_spread_constraints) == 1
        # same-template pods share spec sub-objects after decode
        assert back[0].spec.topology_spread_constraints[0] is \
            back[1].spec.topology_spread_constraints[0]
        assert back[5].spec.affinity is back[6].spec.affinity
        # distinct host ports force distinct templates (conflict tracking)
        from karpenter_tpu.api.objects import HostPort
        ported = [make_pod(cpu="100m", host_ports=[HostPort(port=9000 + i)])
                  for i in range(2)]
        wire2 = codec.encode_pod_batch(ported)
        assert len(wire2["templates"]) == 2
        # volumes survive the batch path and key the templates: dropping
        # them server-side would bypass CSI attach-limit tracking entirely
        from karpenter_tpu.api.objects import PVCRef
        vol = make_pod(cpu="100m")
        vol.spec.volumes.append(PVCRef(claim_name="data"))
        plain = make_pod(cpu="100m")
        wire3 = codec.encode_pod_batch([vol, plain])
        assert len(wire3["templates"]) == 2
        back3 = codec.decode_pod_batch(wire3)
        assert back3[0].spec.volumes[0].claim_name == "data"
        assert not back3[1].spec.volumes

    def test_relax_after_decode_does_not_strip_siblings(self):
        """decode_pod_batch rebuilds pods of one template with SHARED
        affinity/spread objects; the host-fallback relaxation ladder pops
        terms in place. Relaxing one pod must not narrow its siblings'
        constraints (ADVICE r3: shared-mutable wire decode vs
        preferences.go:38-57 semantics)."""
        from karpenter_tpu.api.objects import SCHEDULE_ANYWAY, NodeSelectorRequirement
        from karpenter_tpu.provisioning.preferences import Preferences
        from factories import spread_zone

        term = [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                        "In", ("test-zone-a",))]
        tsc = spread_zone(key="app", value="d0")
        object.__setattr__(tsc, "when_unsatisfiable", SCHEDULE_ANYWAY)
        proto = make_pod(cpu="100m", labels={"app": "d0"}, spread=[tsc],
                         preferred_affinity=[(10, term)], name="rx-0")
        # deployment stamping: siblings share the SAME spec sub-objects
        from karpenter_tpu.api.objects import ObjectMeta, Pod, PodSpec
        pods = [proto] + [
            Pod(metadata=ObjectMeta(name=f"rx-{i}", namespace="default",
                                    labels=dict(proto.labels)),
                spec=PodSpec(
                    affinity=proto.spec.affinity,
                    topology_spread_constraints=
                        proto.spec.topology_spread_constraints),
                container_requests=list(proto.container_requests))
            for i in (1, 2)]
        back = codec.decode_pod_batch(codec.encode_pod_batch(pods))
        assert back[0].spec.affinity is back[1].spec.affinity  # wire sharing
        prefs = Preferences()
        assert prefs.relax(back[0])  # pops back[0]'s preferred node affinity
        assert prefs.relax(back[0])  # then its ScheduleAnyway spread
        for sibling in back[1:]:
            assert len(sibling.spec.topology_spread_constraints) == 1
            assert len(sibling.spec.affinity.node_affinity.preferred) == 1

    def test_instance_type_round_trip(self):
        it = construct_instance_types()[0]
        back = codec.instance_type_from_dict(codec.instance_type_to_dict(it))
        assert back.name == it.name
        assert back.capacity == it.capacity
        assert len(back.offerings) == len(it.offerings)
        assert back.allocatable() == it.allocatable()

    def test_nodepool_round_trip(self):
        pool = make_nodepool(name="p1", limits={"cpu": "100"}, weight=7)
        back = codec.nodepool_from_dict(codec.nodepool_to_dict(pool))
        assert back.name == "p1"
        assert back.spec.limits == pool.spec.limits
        assert back.spec.weight == 7


class TestRemoteSolve:
    def test_parity_with_local(self, sidecar):
        its = construct_instance_types()[:48]
        pool = make_nodepool(name="default")
        pods = (make_pods(10, cpu="500m", memory="256Mi")
                + make_pods(6, cpu="1000m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")]))
        local = TensorScheduler([pool], {"default": its}).solve(pods)
        remote = RemoteScheduler(sidecar, [pool], {"default": its}).solve(pods)
        assert len(remote.new_nodeclaims) == len(local.new_nodeclaims)
        assert remote.pod_errors == local.pod_errors
        # per-claim pod partitions match sizes
        assert sorted(len(nc.pods) for nc in remote.new_nodeclaims) == \
            sorted(len(nc.pods) for nc in local.new_nodeclaims)
        # the emitted API claims carry instance-type requirements
        api_nc = remote.new_nodeclaims[0].to_nodeclaim()
        keys = {r.key for r in api_nc.spec.requirements}
        assert api_labels.LABEL_INSTANCE_TYPE in keys

    def test_operator_over_sidecar_backend(self, sidecar):
        op = Operator(options=Options(solver_backend="sidecar",
                                      solver_address=sidecar),
                      clock=FakeClock())
        op.store.create(make_nodepool(name="default"))
        for p in make_pods(5, cpu="500m"):
            op.store.create(p)
        for _ in range(6):
            op.step()
            op.clock.step(1.1)
        op.step()
        assert all(p.spec.node_name for p in op.store.list(Pod))
        assert op.store.list(Node)


from factories import StaticClusterView  # noqa: E402 — shared stub


def _scaleup_fixture():
    """A deployment scale-up: 4 replicas of app=s already running in
    test-zone-a, 8 new spread-constrained replicas pending. The solver must
    count the existing replicas (topology.go:268-321) and skew new pods
    toward the other zones."""
    its = construct_instance_types()[:48]
    pool = make_nodepool(name="default")
    existing = make_pods(4, cpu="500m", labels={"app": "s"},
                         spread=[spread_zone(key="app", value="s")])
    for i, p in enumerate(existing):
        p.spec.node_name = "existing-node-a"
        p.status.phase = "Running"
    view = StaticClusterView(existing, {
        "existing-node-a": {api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-a"}})
    pending = make_pods(8, cpu="500m", labels={"app": "s"},
                        spread=[spread_zone(key="app", value="s")])
    return its, pool, view, pending


def _zones_of(results):
    zones = []
    for nc in results.new_nodeclaims:
        req = nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
        zs = sorted(req.values_list())
        zones.extend(zs * len(nc.pods) if len(zs) == 1 else [])
    return sorted(zones)


class TestClusterViewOverWire:
    def test_cluster_counts_shift_the_solution(self, sidecar):
        its, pool, view, pending = _scaleup_fixture()
        with_view = TensorScheduler([pool], {"default": its},
                                    cluster=view).solve(pending)
        assert not with_view.pod_errors
        # existing 4 pods in zone a: new 8 must backfill b/c/d first --
        # zone a receives strictly fewer new pods than the other zones' max
        zones = _zones_of(with_view)
        assert zones, "expected zonal placements"
        count_a = zones.count("test-zone-a")
        others = [zones.count(z) for z in
                  ("test-zone-b", "test-zone-c", "test-zone-d")]
        assert count_a < max(others)
        # host-oracle parity: same per-zone fill multiset (tie-break zone
        # naming may differ, as in the reference's map iteration)
        from factories import make_scheduler
        host = make_scheduler([pool], {"default": its}, pending, cluster=view)
        host_zones = _zones_of(host.solve(pending))
        multiset = lambda zs: sorted(
            zs.count(z) for z in set(zs))
        assert multiset(zones) == multiset(host_zones)

    def test_remote_matches_local_with_cluster_view(self, sidecar):
        its, pool, view, pending = _scaleup_fixture()
        local = TensorScheduler([pool], {"default": its},
                                cluster=view).solve(pending)
        remote = RemoteScheduler(sidecar, [pool], {"default": its},
                                 cluster=view).solve(pending)
        assert remote.pod_errors == local.pod_errors
        assert len(remote.new_nodeclaims) == len(local.new_nodeclaims)
        # zone assignment parity: the wire snapshot must carry the counts
        local_zones = _zones_of(local)
        remote_zones = []
        for nc in remote.new_nodeclaims:
            req = nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
            zs = sorted(req.values_list())
            remote_zones.extend(zs * len(nc.pods) if len(zs) == 1 else [])
        assert sorted(remote_zones) == local_zones


class TestSessionProtocol:
    """The session wire (VERDICT r3 #1): catalog/nodepools sent once,
    columnar pod rows per solve, row-referencing interned results."""

    def _session_pair(self, sidecar, its, pool, **kw):
        from karpenter_tpu.sidecar.client import SolverSession
        session = SolverSession(sidecar)
        return RemoteScheduler(sidecar, [pool], {"default": its},
                               session=session, **kw), session

    def test_session_parity_with_local(self, sidecar):
        its = construct_instance_types()[:48]
        pool = make_nodepool(name="default")
        pods = (make_pods(10, cpu="500m", memory="256Mi")
                + make_pods(6, cpu="1000m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
                + make_pods(3, cpu="250m", labels={"app": "anti"},
                            pod_anti_affinity=[
                                affinity_term(api_labels.LABEL_HOSTNAME,
                                              key="app", value="anti")]))
        local = TensorScheduler([pool], {"default": its}).solve(pods)
        rs, session = self._session_pair(sidecar, its, pool)
        remote = rs.solve(pods)
        assert rs.fallback_reason == ""
        assert remote.pod_errors == local.pod_errors
        key = lambda nc: (tuple(it.name for it in nc.instance_type_options),
                          len(nc.pods))
        assert sorted(map(key, remote.new_nodeclaims)) == \
            sorted(map(key, local.new_nodeclaims))
        # API claims are complete: instance-type values filled from options
        api_nc = remote.new_nodeclaims[0].to_nodeclaim()
        it_req = next(r for r in api_nc.spec.requirements
                      if r.key == api_labels.LABEL_INSTANCE_TYPE)
        assert 0 < len(it_req.values) <= 60
        assert it_req.values[0] == \
            remote.new_nodeclaims[0].instance_type_options[0].name
        # errors map back to REAL pod uids (server side is synthetic rows)
        for uid in remote.pod_errors:
            assert any(p.uid == uid for p in pods)
        session.close()

    def test_session_reused_across_solves(self, sidecar):
        from karpenter_tpu.sidecar import server as srv
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = self._session_pair(sidecar, its, pool)
        rs.solve(make_pods(4, cpu="500m"))
        sid = session._session_id
        assert sid is not None
        rs.solve(make_pods(5, cpu="250m"))
        assert session._session_id == sid  # no re-create
        # same catalog content in NEW list objects: still no re-create
        its2 = construct_instance_types()[:16]
        rs2 = RemoteScheduler(rs.address, [pool], {"default": its2},
                              session=session)
        rs2.solve(make_pods(2, cpu="100m"))
        assert session._session_id == sid
        # changed catalog content: a new session is created
        rs3 = RemoteScheduler(rs.address, [pool],
                              {"default": construct_instance_types()[:8]},
                              session=session)
        rs3.solve(make_pods(2, cpu="100m"))
        assert session._session_id != sid
        session.close()

    def test_session_eviction_recovery(self, sidecar):
        from karpenter_tpu.sidecar import server as srv
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = self._session_pair(sidecar, its, pool)
        r1 = rs.solve(make_pods(4, cpu="500m"))
        assert not r1.pod_errors
        # simulate server restart: drop all sessions
        with srv._SESSIONS_LOCK:
            srv._SESSIONS.clear()
        r2 = rs.solve(make_pods(4, cpu="500m"))  # NOT_FOUND -> retry once
        assert not r2.pod_errors
        assert session._session_id is not None
        session.close()

    def test_state_node_delta_updates(self, sidecar):
        """An existing node added between solves must be visible server-side
        via the delta (VERDICT: delta-update state nodes instead of
        re-sending)."""
        from factories import make_state_node
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        rs, session = self._session_pair(sidecar, its, pool)
        r1 = rs.solve(make_pods(2, cpu="500m"))
        assert r1.new_nodeclaims and not r1.existing_nodes
        sn = make_state_node("live-node-1", zone="test-zone-a")
        rs2 = RemoteScheduler(rs.address, [pool], {"default": its},
                              state_nodes=[sn], session=session)
        r2 = rs2.solve(make_pods(2, cpu="500m"))
        assert [en.name for en in r2.existing_nodes] == ["live-node-1"]
        assert not r2.new_nodeclaims
        # removing the node flows through as a delete delta
        rs3 = RemoteScheduler(rs.address, [pool], {"default": its},
                              session=session)
        r3 = rs3.solve(make_pods(2, cpu="500m"))
        assert r3.new_nodeclaims and not r3.existing_nodes
        session.close()

    def test_session_host_fallback_relax(self, sidecar):
        """Pods whose preferences must relax ride the host ladder server-side
        over FULLY-SHARED specs (build_wire_pods): relaxation must not strip
        siblings, and results must match the in-process solve."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        term = [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                        "In", ("no-such-zone",))]
        pods = make_pods(4, cpu="500m", labels={"app": "px"},
                         preferred_affinity=[(10, term)])
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        local = TensorScheduler([pool], {"default": its}).solve(
            [p for p in pods])
        rs, session = self._session_pair(sidecar, its, pool)
        remote = rs.solve(pods)
        assert remote.pod_errors == local.pod_errors == {}
        assert sorted(len(nc.pods) for nc in remote.new_nodeclaims) == \
            sorted(len(nc.pods) for nc in local.new_nodeclaims)
        session.close()

    def test_encode_pod_rows_dedup(self):
        spread = [spread_zone(key="app", value="d0")]
        a = [make_pod(cpu="500m", labels={"app": "d0"}, spread=spread,
                      name=f"a-{i}") for i in range(5)]
        b = [make_pod(cpu="250m", labels={"app": "d1"}, name=f"b-{i}")
             for i in range(3)]
        templates, tmpl_idx, ts = codec.encode_pod_rows(a + b)
        assert len(templates) <= 3  # shared elements may still merge content
        assert list(tmpl_idx[:5]) == [tmpl_idx[0]] * 5
        assert list(tmpl_idx[5:]) == [tmpl_idx[5]] * 3
        back = codec.build_wire_pods(templates, tmpl_idx, ts)
        assert len(back) == 8
        assert back[0].spec is back[1].spec  # fully shared spec per template
        assert back[0]._row == 0 and back[7]._row == 7
        assert back[0].requests() == a[0].requests()


class TestEncodeRowsFastPath:
    """encode_pod_rows' run-length fast path must stay exactly as
    discriminating as the slow-path key: consecutive pods differing in ANY
    keyed field must not merge, and a shuffled batch (no runs, pure slow
    path) must produce content-identical per-pod templates."""

    def _variants(self):
        from karpenter_tpu.api.objects import HostPort, PVCRef, Toleration
        from factories import (affinity_term, make_pod, spread_zone)
        base = dict(cpu="100m", memory="128Mi")
        return [
            make_pod(**base),
            make_pod(cpu="200m", memory="128Mi"),
            make_pod(**base, labels={"app": "x"}),
            make_pod(**base, node_selector={"k": "v"}),
            make_pod(**base, tolerations=[Toleration(key="t",
                                                     operator="Exists")]),
            make_pod(**base, labels={"app": "s"},
                     spread=[spread_zone(key="app", value="s")]),
            make_pod(**base, labels={"app": "a"},
                     pod_affinity=[affinity_term(
                         "topology.kubernetes.io/zone", key="app",
                         value="a")]),
            make_pod(**base, host_ports=[HostPort(port=9000)]),
            make_pod(**base, namespace="other"),
        ]

    def test_adjacent_differing_pods_never_merge(self):
        from karpenter_tpu.sidecar.codec import encode_pod_rows
        variants = self._variants()
        templates, idx, _ts = encode_pod_rows(variants)
        assert len(set(idx.tolist())) == len(variants), (
            "fast path merged pods the slow-path key separates")

    def test_shuffled_batch_agrees_with_run_ordered(self):
        import random
        from karpenter_tpu.sidecar.codec import encode_pod_rows
        rng = random.Random(7)
        runs = []
        for v in self._variants():
            runs.extend([v] * 5)  # contiguous runs: fast path exercised
        shuffled = list(runs)
        rng.shuffle(shuffled)  # no runs: slow path everywhere
        t1, i1, _ = encode_pod_rows(runs)
        t2, i2, _ = encode_pod_rows(shuffled)
        by_pod_1 = {id(p): t1[t] for p, t in zip(runs, i1.tolist())}
        by_pod_2 = {id(p): t2[t] for p, t in zip(shuffled, i2.tolist())}
        for pid in by_pod_1:
            assert by_pod_1[pid] == by_pod_2[pid], (
                "fast path assigned different template CONTENT than the "
                "slow path for the same pod")
