"""Scenario port of /root/reference/pkg/controllers/disruption/
consolidation_test.go (4,382 LoC): budget interplay (percent, absolute,
per-nodepool, consolidated-marker suppression), replace-vs-delete price
guards, uninitialized-node gating, do-not-disrupt pods, permanently-pending
pods, validation races during the 15 s TTL (catalog shrink, late PDB), and
multi-nodeclaim merges with mixed capacity types."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import (COND_CONSOLIDATABLE, COND_INITIALIZED,
                                         NodeClaim)
from karpenter_tpu.api.nodepool import Budget
from karpenter_tpu.api.objects import LabelSelector, Node, ObjectMeta, Pod
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_disruption import NodeClaimDisruptionMarker
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.node_termination import NodeTermination
from karpenter_tpu.disruption.controller import (DisruptionController,
                                                 OrchestrationQueue)
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods

OD = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    queue = OrchestrationQueue(store, cluster, clock)
    disruption = DisruptionController(store, cluster, provisioner, queue, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock),
                 NodeClaimDisruptionMarker(store, cluster, provider, clock),
                 NodeTermination(store, cluster, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.provisioner, e.queue, e.disruption = provisioner, queue, disruption
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def disrupt(env, rounds=8):
    for _ in range(rounds):
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        env.clock.step(8)


def make_empty_nodes(env, n, pool="default", prefix="e"):
    """Provision n single-pod nodes in `pool`, then strand them empty."""
    pods = []
    for i in range(n):
        p = make_pod(cpu="2500m", node_selector={
            **OD, api_labels.NODEPOOL_LABEL_KEY: pool}, name=f"{prefix}-{i}")
        env.store.create(p)
        pods.append(p)
        settle(env, rounds=3)
    for p in pods:
        env.store.delete(p)
    settle(env)
    env.clock.step(21)


class TestBudgets:
    """consolidation_test.go:217-860."""

    def test_percent_budget_limits_empty_disruption(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="30%")]
        env.store.create(pool)
        make_empty_nodes(env, 6)
        assert len(env.store.list(Node)) == 6
        # one disruption pass: ceil(30% of 6) = 2 nodes may go
        # (percent rounds UP, nodepool.go:330-334)
        env.disruption.reconcile()
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=3)
        assert len(env.store.list(Node)) == 4

    def test_full_budget_allows_all_empty(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(pool)
        make_empty_nodes(env, 4)
        disrupt(env)
        assert env.store.list(Node) == []

    def test_zero_budget_blocks_everything(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        env.store.create(pool)
        make_empty_nodes(env, 3)
        disrupt(env, rounds=3)
        assert len(env.store.list(Node)) == 3

    def test_per_nodepool_budgets_independent(self, env):
        """consolidation_test.go:414-480: each pool's budget is its own."""
        for name, budget in (("pool-a", "1"), ("pool-b", "100%")):
            pool = make_nodepool(name=name)
            pool.spec.disruption.budgets = [Budget(nodes=budget)]
            env.store.create(pool)
        make_empty_nodes(env, 2, pool="pool-a", prefix="a")
        make_empty_nodes(env, 2, pool="pool-b", prefix="b")
        # one pass: pool-a loses at most 1, pool-b may lose both
        env.disruption.reconcile()
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=3)
        by_pool = {}
        for n in env.store.list(Node):
            key = n.metadata.labels[api_labels.NODEPOOL_LABEL_KEY]
            by_pool[key] = by_pool.get(key, 0) + 1
        assert by_pool.get("pool-a", 0) >= 1

    def test_budget_block_does_not_mark_consolidated(self, env):
        """consolidation_test.go:608-694: a budget-blocked pass must NOT
        memoize the cluster as consolidated — lifting the budget later must
        disrupt without waiting for unrelated cluster changes."""
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        env.store.create(pool)
        make_empty_nodes(env, 2)
        disrupt(env, rounds=2)
        assert len(env.store.list(Node)) == 2
        # lift the budget; nothing else changes in the cluster
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.update(pool)
        disrupt(env)
        assert env.store.list(Node) == []


class TestReplaceAndDelete:
    """consolidation_test.go:870-3071."""

    def test_wont_replace_with_more_expensive(self, env):
        """A node already on the cheapest fitting type stays put."""
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="200m", memory="128Mi", node_selector=OD)
        env.store.create(pod)
        settle(env)
        node = env.store.list(Node)[0]
        env.clock.step(21)
        disrupt(env, rounds=3)
        nodes = env.store.list(Node)
        assert len(nodes) == 1 and nodes[0].name == node.name

    def test_delete_when_other_capacity_fits(self, env):
        """consolidation_test.go:2259-2303: pods fit on a surviving node ->
        delete-only decision, no replacement launched. Sized so the merged
        load (2x1500m) only fits the candidates' own instance type, making
        a replacement same-type (blocked) — delete is the only move."""
        env.store.create(make_nodepool(name="default"))
        for i in range(2):
            env.store.create(make_pod(cpu="2000m", node_selector=OD,
                                      name=f"big-{i}"))
            env.store.create(make_pod(cpu="1500m", node_selector=OD,
                                      name=f"small-{i}"))
            settle(env, rounds=3)
        assert len(env.store.list(Node)) == 2
        for i in range(2):
            env.store.delete(env.store.get(Pod, f"big-{i}", "default"))
        settle(env)
        env.clock.step(21)
        claims_before = {c.name for c in env.store.list(NodeClaim)}
        disrupt(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        # survivor is an original node, not a fresh replacement
        claims_after = {c.name for c in env.store.list(NodeClaim)}
        assert claims_after <= claims_before

    def test_do_not_disrupt_pod_blocks_delete(self, env):
        """consolidation_test.go:2516-2564."""
        env.store.create(make_nodepool(name="default"))
        big = make_pod(cpu="3000m", node_selector=OD)
        env.store.create(big)
        settle(env)
        env.store.delete(big)
        small = make_pod(cpu="200m", node_selector=OD)
        small.metadata.annotations[api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        env.store.create(small)
        settle(env)
        env.clock.step(21)
        before = {n.name for n in env.store.list(Node)}
        disrupt(env, rounds=3)
        assert {n.name for n in env.store.list(Node)} == before

    def test_wont_delete_onto_uninitialized_node(self, env):
        """consolidation_test.go:2714-2758: a delete whose pods would land
        on a not-yet-initialized node is rejected."""
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="2500m", node_selector=OD, name="a-big"))
        env.store.create(make_pod(cpu="300m", node_selector=OD, name="a-small"))
        settle(env, rounds=3)
        env.store.create(make_pod(cpu="2500m", node_selector=OD, name="b-big"))
        env.store.create(make_pod(cpu="300m", node_selector=OD, name="b-small"))
        settle(env, rounds=3)
        assert len(env.store.list(Node)) == 2
        env.store.delete(env.store.get(Pod, "a-big", "default"))
        env.store.delete(env.store.get(Pod, "b-big", "default"))
        settle(env)
        # strip initialization from node B: its claim loses the condition
        # and the node loses the label (cluster sees it uninitialized)
        node_b = env.store.get(Pod, "b-small", "default").spec.node_name
        for nc in env.store.list(NodeClaim):
            if nc.status.node_name == node_b:
                nc.conditions.set_false(COND_INITIALIZED, "Testing", "forced")
                env.store.update(nc)
        nb = env.store.get(Node, node_b)
        nb.metadata.labels.pop(api_labels.NODE_INITIALIZED_LABEL_KEY, None)
        env.store.update(nb)
        env.clock.step(21)
        na = env.store.get(Pod, "a-small", "default").spec.node_name
        env.disruption.reconcile()
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        # node A survived: consolidating it would schedule onto B (uninit)
        assert env.store.get(Node, na) is not None

    def test_permanently_pending_pod_does_not_block(self, env):
        """consolidation_test.go:2907-2962: an unschedulable pod can't hold
        the whole cluster hostage."""
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="100000", name="impossible"))  # 100 cpu
        make_empty_nodes(env, 2)
        disrupt(env)
        assert env.store.list(Node) == []
        assert env.store.get(Pod, "impossible", "default").spec.node_name == ""

    def test_wont_make_scheduled_pod_pending(self, env):
        """consolidation_test.go:2963-3005: deletion must resimulate ALL
        pods; if capacity disappears, keep the node."""
        env.store.create(make_nodepool(name="default"))
        # two nodes each nearly full: no node can absorb the other's pods
        for i in range(2):
            env.store.create(make_pod(cpu="3400m", node_selector=OD,
                                      name=f"full-{i}"))
            settle(env, rounds=3)
        env.clock.step(21)
        before = {n.name for n in env.store.list(Node)}
        disrupt(env, rounds=3)
        assert {n.name for n in env.store.list(Node)} == before
        for p in env.store.list(Pod):
            assert p.spec.node_name


class TestValidationRaces:
    """consolidation_test.go:3072-3499."""

    def test_catalog_shrink_during_ttl_aborts_replace(self, env):
        """consolidation_test.go:3183-3266: if the re-simulation after the
        15 s TTL picks instance types that aren't a subset of the original
        decision, the command is abandoned."""
        env.store.create(make_nodepool(name="default"))
        big = make_pod(cpu="3000m", memory="2Gi", node_selector=OD)
        env.store.create(big)
        settle(env)
        env.store.delete(big)
        small = make_pod(cpu="200m", memory="128Mi", node_selector=OD)
        env.store.create(small)
        settle(env)
        env.clock.step(21)
        env.disruption.reconcile()
        pending = env.disruption.pending
        if pending is None:
            pytest.skip("no graceful replace computed in this catalog")
        cmd, _ = pending
        if not cmd.replacements:
            pytest.skip("decision was delete-only; nothing to invalidate")
        # the chosen replacement options vanish from the provider
        replacement_names = {
            it.name for nc in cmd.replacements
            for it in nc.instance_type_options}
        env.provider._instance_types = [
            it for it in env.provider._instance_types
            if it.name not in replacement_names]
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        # original node survives; no replacement with a vanished type exists
        for n in env.store.list(Node):
            assert n.metadata.labels[api_labels.LABEL_INSTANCE_TYPE] \
                not in replacement_names

    def test_late_blocking_pdb_aborts(self, env):
        """consolidation_test.go:3449-3498: a blocking PDB created during
        the TTL invalidates the command."""
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m", labels={"app": "guard"})
        env.store.create(pod)
        settle(env)
        node = env.store.list(Node)[0]
        env.store.delete(pod)
        settle(env)
        env.clock.step(21)
        env.disruption.reconcile()
        assert env.disruption.pending is not None
        # a pod (guarded by a hot PDB) lands on the candidate mid-TTL
        guarded = make_pod(cpu="100m", labels={"app": "guard"})
        guarded.spec.node_name = node.name
        env.store.create(guarded)
        env.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "guard"}),
                         max_unavailable="0")))
        env.clock.step(16)
        env.disruption.reconcile()
        env.queue.reconcile()
        settle(env, rounds=2)
        assert env.store.get(Node, node.name) is not None


class TestMultiNodeClaim:
    """consolidation_test.go:3499-3700."""

    def test_merge_mixed_capacity_types(self, env):
        """consolidation_test.go:3597-3657: spot + on-demand candidates can
        merge into one node (spot-to-spot gate applies to all-spot only)."""
        env.store.create(make_nodepool(name="default"))
        selectors = [
            {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_SPOT},
            OD, OD]
        bigs = []
        for i, sel in enumerate(selectors):
            big = make_pod(cpu="2500m", node_selector=sel, name=f"m-big-{i}")
            env.store.create(big)
            env.store.create(make_pod(cpu="700m", node_selector=sel,
                                      name=f"m-small-{i}"))
            settle(env, rounds=3)
            bigs.append(big)
        assert len(env.store.list(Node)) == 3
        for big in bigs:
            env.store.delete(big)
        settle(env)
        env.clock.step(21)
        disrupt(env)
        assert len(env.store.list(Node)) <= 2  # merged (1 ideal, ≤2 allowed)
        for p in env.store.list(Pod):
            assert p.spec.node_name

    def test_wont_merge_two_same_type_into_same_type(self, env):
        """multinodeconsolidation.go filterOutSameType end-to-end: two
        half-full nodes of type X must not 'merge' by buying another X."""
        env.store.create(make_nodepool(name="default"))
        for i in range(2):
            env.store.create(make_pod(cpu="2000m", node_selector=OD,
                                      name=f"s-big-{i}"))
            env.store.create(make_pod(cpu="1500m", node_selector=OD,
                                      name=f"s-small-{i}"))
            settle(env, rounds=3)
        types_before = {n.metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
                        for n in env.store.list(Node)}
        assert len(types_before) == 1  # both candidates same type
        claims_before = {c.metadata.name for c in env.store.list(NodeClaim)}
        for i in range(2):
            env.store.delete(env.store.get(Pod, f"s-big-{i}", "default"))
        settle(env)
        env.clock.step(21)
        disrupt(env)
        # the merged load (3000m) only fits the candidates' own type, so a
        # replacement would be same-type at the same price — forbidden
        # (delete disguised as replace, multinodeconsolidation.go:180-217).
        # The only legal consolidation is delete-only onto the survivor.
        assert len(env.store.list(Node)) == 1
        claims_after = {c.metadata.name for c in env.store.list(NodeClaim)}
        assert claims_after <= claims_before
        if env.disruption.last_command is not None:
            assert not env.disruption.last_command.replacements
        for p in env.store.list(Pod):
            assert p.spec.node_name


class TestReasonScopedBudgets:
    """nodepool.go:305-318 + Budget schedule windows (:353-367)."""

    def _pool(self, *budgets):
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = list(budgets)
        return pool

    def test_reason_scoped_budget_only_binds_its_reason(self):
        pool = self._pool(Budget(nodes="0", reasons=["Underutilized"]))
        now = 1_000_000.0
        assert pool.allowed_disruptions(now, 10, "Underutilized") == 0
        assert pool.allowed_disruptions(now, 10, "Empty") > 10
        assert pool.allowed_disruptions(now, 10, "Drifted") > 10

    def test_min_across_matching_budgets(self):
        pool = self._pool(Budget(nodes="50%"),
                          Budget(nodes="2", reasons=["Empty"]))
        now = 1_000_000.0
        assert pool.allowed_disruptions(now, 10, "Empty") == 2
        assert pool.allowed_disruptions(now, 10, "Underutilized") == 5

    def test_schedule_window_activates_budget(self):
        from datetime import datetime, timezone
        pool = self._pool(Budget(nodes="0", schedule="0 9 * * *",
                                 duration=2 * 3600.0))
        inside = datetime(2026, 7, 1, 9, 30,
                          tzinfo=timezone.utc).timestamp()
        outside = datetime(2026, 7, 1, 13, 0,
                           tzinfo=timezone.utc).timestamp()
        assert pool.allowed_disruptions(inside, 10, "Empty") == 0
        assert pool.allowed_disruptions(outside, 10, "Empty") > 10

    def test_underutilized_scoped_zero_budget_lets_emptiness_run(self, env):
        """e2e: a zero budget scoped to Underutilized must not block
        EMPTINESS deletion."""
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [
            Budget(nodes="0", reasons=["Underutilized"])]
        env.store.create(pool)
        make_empty_nodes(env, 2)
        disrupt(env)
        assert env.store.list(Node) == []
