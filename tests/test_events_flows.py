"""Events published from every controller flow.

The reference emits deduped events from provisioning
(scheduling/scheduler.go:117-151, scheduling/events.go:34-62), disruption
(disruption/events/events.go, published from types.go:74-101,
helpers.go:240-242, consolidation.go:85-258, orchestration/queue.go:243-264),
termination (terminator/events/events.go, published from
termination/controller.go:115-119,272-280, terminator.go:140-157,
eviction.go:208), node repair (health/controller.go:102,209) and lifecycle
(lifecycle/launch.go:78-86). Each scenario here drives one flow end-to-end
through the operator's shared Recorder and asserts the event lands.
"""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.nodepool import Budget
from karpenter_tpu.api.objects import Node, ObjectMeta, Pod
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods
from test_operator import settle


@pytest.fixture
def op():
    return Operator(clock=FakeClock())


def reasons(op, name):
    return set(op.recorder.reasons_for(name))


class TestProvisioningEvents:
    """scheduling/scheduler.go:117-151 Results.Record +
    provisioner.go:388."""

    def test_failed_scheduling_event_on_unschedulable_pod(self, op):
        op.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100000")  # no instance type fits 100k cores
        op.store.create(pod)
        settle(op)
        evs = [e for e in op.recorder.for_object(pod.metadata.name)
               if e.reason == "FailedScheduling"]
        assert evs, "unschedulable pod published no FailedScheduling"
        assert evs[0].type == "Warning"
        assert evs[0].message.startswith("Failed to schedule pod, ")

    def test_nominated_event_for_new_nodeclaim(self, op):
        op.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        op.store.create(pod)
        settle(op)
        evs = [e for e in op.recorder.for_object(pod.metadata.name)
               if e.reason == "Nominated"]
        assert evs
        assert "nodeclaim/" in evs[0].message

    def test_nominated_event_for_existing_node(self, op):
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        [node] = op.store.list(Node)
        # second pod packs onto the already-initialized node
        p2 = make_pod(cpu="100m")
        op.store.create(p2)
        settle(op)
        evs = [e for e in op.recorder.for_object(p2.metadata.name)
               if e.reason == "Nominated"]
        assert evs
        assert f"node/{node.name}" in evs[0].message


def _make_node_with_pod(op, pool="default", annotations=None, cpu="2500m"):
    """Provision one node carrying one pod; returns (node, pod)."""
    pod = make_pod(cpu=cpu)
    if annotations:
        pod.metadata.annotations.update(annotations)
    op.store.create(pod)
    settle(op)
    [node] = op.store.list(Node)
    return node, pod


class TestDisruptionEvents:
    def test_blocked_event_for_do_not_disrupt_pod(self, op):
        """types.go:74-82: a candidate rejected by ValidatePodsDisruptable
        publishes DisruptionBlocked on Node and NodeClaim."""
        op.store.create(make_nodepool(name="default"))
        node, _ = _make_node_with_pod(
            op, annotations={api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY: "true"})
        op.clock.step(21)  # past consolidateAfter
        settle(op)
        op.disruption.reconcile()
        assert "DisruptionBlocked" in reasons(op, node.name)
        [nc] = op.store.list(NodeClaim)
        assert "DisruptionBlocked" in reasons(op, nc.name)
        msg = [e for e in op.recorder.for_object(node.name)
               if e.reason == "DisruptionBlocked"][0].message
        assert msg.startswith("Cannot disrupt Node: ")

    def test_nodepool_budget_blocked_event(self, op):
        """helpers.go:240-242: a populated pool with a zero budget."""
        pool = make_nodepool(name="default")
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        op.store.create(pool)
        _make_node_with_pod(op)
        op.clock.step(21)
        settle(op)
        op.disruption.reconcile()
        evs = [e for e in op.recorder.for_object("default")
               if e.object_kind == "NodePool"]
        assert evs and evs[0].reason == "DisruptionBlocked"
        assert "blocking budget" in evs[0].message

    def test_unconsolidatable_when_consolidation_disabled(self, op):
        """consolidation.go:104-108: consolidateAfter: Never."""
        pool = make_nodepool(name="default")
        pool.spec.disruption.consolidate_after = None  # Never
        op.store.create(pool)
        node, _ = _make_node_with_pod(op)
        op.clock.step(21)
        settle(op)
        op.disruption.reconcile()
        evs = [e for e in op.recorder.for_object(node.name)
               if e.reason == "Unconsolidatable"]
        assert evs
        assert 'NodePool "default" has consolidation disabled' == evs[0].message

    def test_terminating_events_on_emptiness_execution(self, op):
        """orchestration/queue.go:258-264: the command's candidates get
        DisruptionTerminating on Node and NodeClaim when executed."""
        op.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="2500m")
        op.store.create(pod)
        settle(op)
        [node] = op.store.list(Node)
        [nc] = op.store.list(NodeClaim)
        op.store.delete(pod)  # node is now empty -> emptiness candidate
        settle(op)
        op.clock.step(21)
        settle(op)
        for _ in range(6):
            op.disruption.reconcile()
            op.queue.reconcile()
            settle(op, rounds=2)
            op.clock.step(8)
        assert "DisruptionTerminating" in reasons(op, node.name)
        assert "DisruptionTerminating" in reasons(op, nc.name)
        term = [e for e in op.recorder.for_object(node.name)
                if e.reason == "DisruptionTerminating"][0]
        assert term.message == "Disrupting Node: Empty"


class TestTerminationEvents:
    def test_evicted_event_per_drained_pod(self, op):
        op.store.create(make_nodepool(name="default"))
        node, pod = _make_node_with_pod(op)
        op.store.delete(node)
        settle(op)
        assert "Evicted" in reasons(op, pod.metadata.name)

    def test_failed_draining_when_pdb_blocks(self, op):
        op.store.create(make_nodepool(name="default"))
        node, pod = _make_node_with_pod(op)
        pod.metadata.labels["app"] = "guarded"
        op.store.update(pod)
        from karpenter_tpu.api.objects import LabelSelector
        op.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(
                match_labels={"app": "guarded"}), max_unavailable="0")))
        settle(op)
        op.store.delete(node)
        op.step()
        evs = [e for e in op.recorder.for_object(node.name)
               if e.reason == "FailedDraining"]
        assert evs and evs[0].type == "Warning"
        assert evs[0].message.startswith("Failed to drain node, ")

    def test_tgp_expiring_and_disrupted_pod_events(self, op):
        """controller.go:272-280 + terminator.go:140-157: a claim with
        terminationGracePeriod stamps the deadline, pods whose grace can't
        fit are proactively Disrupted."""
        pool = make_nodepool(name="default")
        pool.spec.template.spec.termination_grace_period = 60.0
        op.store.create(pool)
        pod = make_pod(cpu="2500m")
        pod.spec.termination_grace_period_seconds = 3600  # can't fit in 60s
        op.store.create(pod)
        settle(op)
        [node] = op.store.list(Node)
        [nc] = op.store.list(NodeClaim)
        op.store.delete(node)
        op.step()
        assert "TerminationGracePeriodExpiring" in reasons(op, node.name)
        assert "TerminationGracePeriodExpiring" in reasons(op, nc.name)
        dis = [e for e in op.recorder.for_object(pod.metadata.name)
               if e.reason == "Disrupted"]
        assert dis
        assert "bypasses the PDB" in dis[0].message


class TestLifecycleEvents:
    def test_insufficient_capacity_event(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.cloudprovider.types import InsufficientCapacityError
        op = Operator(clock=FakeClock(), cloud_provider=FakeCloudProvider())
        op.store.create(make_nodepool(name="default"))
        op.cloud_provider.next_create_err = InsufficientCapacityError("no c-1x")
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        evs = [e for e in op.recorder.events
               if e.reason == "InsufficientCapacityError"]
        assert evs and evs[0].type == "Warning"
        assert "no c-1x" in evs[0].message


class TestRecorderSink:
    def test_sink_receives_published_events(self):
        from karpenter_tpu.events.catalog import evict_pod
        from karpenter_tpu.events.recorder import Recorder
        clock = FakeClock()
        seen = []
        rec = Recorder(clock, sink=seen.append)
        rec.publish(evict_pod(make_pod()))
        assert len(seen) == 1 and seen[0].reason == "Evicted"
        # deduped events must not reach the sink twice
        rec.publish(evict_pod(make_pod(name=seen[0].object_name)))
        assert len(seen) == 1

    def test_sink_errors_do_not_break_publish(self):
        from karpenter_tpu.events.catalog import evict_pod
        from karpenter_tpu.events.recorder import Recorder

        def boom(ev):
            raise RuntimeError("apiserver down")

        rec = Recorder(FakeClock(), sink=boom)
        rec.publish(evict_pod(make_pod()))
        assert len(rec.events) == 1

    def test_kube_post_event_body_shape(self, monkeypatch):
        """post_event speaks core/v1 Events: involvedObject carries the right
        apiVersion per kind, cluster-scoped kinds omit namespace."""
        from karpenter_tpu.events.recorder import Event
        from karpenter_tpu.kube.apiserver import KubeApiStore
        store = KubeApiStore.__new__(KubeApiStore)
        store.base_url = "http://api.test"
        store.clock = FakeClock()
        calls = []
        monkeypatch.setattr(
            store, "_request",
            lambda method, url, body=None: calls.append((method, url, body)))
        store.post_event(Event(
            object_kind="NodeClaim", object_name="default-1", type="Normal",
            reason="DisruptionLaunching", message="Launching NodeClaim: Empty"))
        store.post_event(Event(
            object_kind="Pod", object_name="web-1", namespace="apps",
            type="Warning", reason="FailedScheduling", message="no capacity"))
        (m1, u1, b1), (m2, u2, b2) = calls
        assert m1 == m2 == "POST"
        assert u1.endswith("/api/v1/namespaces/default/events")
        assert b1["involvedObject"] == {
            "kind": "NodeClaim", "name": "default-1",
            "apiVersion": "karpenter.sh/v1"}
        assert u2.endswith("/api/v1/namespaces/apps/events")
        assert b2["involvedObject"]["namespace"] == "apps"
        assert b2["involvedObject"]["apiVersion"] == "v1"
        assert b2["source"] == {"component": "karpenter"}


class TestDedupeFidelity:
    def test_message_churn_still_dedupes(self):
        """recorder.go:74: the dedupe key is type+reason+DedupeValues, not
        the message — FailedDraining with a shrinking pod count must stay
        one event per window."""
        from karpenter_tpu.events.catalog import node_failed_to_drain
        from karpenter_tpu.events.recorder import Recorder
        rec = Recorder(FakeClock())
        for n in (5, 4, 3, 2, 1):
            rec.publish(node_failed_to_drain(
                "node-a", f"{n} pods are waiting to be evicted"))
        assert len(rec.events) == 1

    def test_same_name_different_namespace_not_deduped(self):
        """Pod dedupe rides the UID (scheduling/events.go:60), so
        identically-named pods in different namespaces each publish."""
        from karpenter_tpu.events.catalog import pod_failed_to_schedule
        from karpenter_tpu.events.recorder import Recorder
        rec = Recorder(FakeClock())
        rec.publish(pod_failed_to_schedule(
            make_pod(name="web-0", namespace="team-a"), "no capacity"))
        rec.publish(pod_failed_to_schedule(
            make_pod(name="web-0", namespace="team-b"), "no capacity"))
        assert len(rec.events) == 2

    def test_repair_blocked_skips_bare_node_claim_event(self):
        from karpenter_tpu.events.catalog import node_repair_blocked
        evs = node_repair_blocked("node-a", "", "too many unhealthy")
        assert [e.object_kind for e in evs] == ["Node"]


class TestAsyncSink:
    def test_delivers_off_thread_and_flushes(self):
        import threading
        from karpenter_tpu.events.catalog import evict_pod
        from karpenter_tpu.events.recorder import AsyncSink, Recorder
        seen = []
        threads = set()

        def deliver(ev):
            threads.add(threading.current_thread().name)
            seen.append(ev)

        sink = AsyncSink(deliver)
        rec = Recorder(FakeClock(), sink=sink)
        for i in range(5):
            rec.publish(evict_pod(make_pod(name=f"p-{i}")))
        sink.flush()
        assert len(seen) == 5
        assert threads == {"karpenter-event-sink"}
        sink.close()

    def test_slow_delivery_does_not_block_publish(self):
        import time
        from karpenter_tpu.events.catalog import evict_pod
        from karpenter_tpu.events.recorder import AsyncSink, Recorder

        def slow(ev):
            time.sleep(0.2)

        sink = AsyncSink(slow)
        rec = Recorder(FakeClock(), sink=sink)
        t0 = time.monotonic()
        for i in range(10):
            rec.publish(evict_pod(make_pod(name=f"s-{i}")))
        assert time.monotonic() - t0 < 0.1  # publish never blocked
        sink.close()

    def test_delivery_errors_swallowed(self):
        from karpenter_tpu.events.catalog import evict_pod
        from karpenter_tpu.events.recorder import AsyncSink, Recorder

        def boom(ev):
            raise RuntimeError("apiserver down")

        sink = AsyncSink(boom)
        rec = Recorder(FakeClock(), sink=sink)
        rec.publish(evict_pod(make_pod(name="x-1")))
        sink.flush()  # must not raise or hang
        assert len(rec.events) == 1
        sink.close()


class TestQueueReadinessEvents:
    def test_launching_and_waiting_events_for_replacements(self, op):
        """orchestration/queue.go:243-249: a consolidation with a replacement
        narrates Launching then WaitingReadiness until initialization."""
        from karpenter_tpu.disruption.controller import QueuedCommand
        from karpenter_tpu.disruption.types import Command
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="500m"))
        settle(op)
        [nc] = op.store.list(NodeClaim)
        # a synthetic queued command waiting on a fresh (uninitialized) claim
        repl = NodeClaim(metadata=ObjectMeta(
            name="repl-1",
            labels={api_labels.NODEPOOL_LABEL_KEY: "default"}))
        op.store.create(repl)
        op.queue.add(QueuedCommand(
            command=Command(candidates=[], reason="underutilized"),
            replacement_names=["repl-1"], enqueued_at=op.clock.now()))
        op.queue.reconcile()
        assert "DisruptionLaunching" in reasons(op, "repl-1")
        assert "DisruptionWaitingReadiness" in reasons(op, "repl-1")
        msg = [e for e in op.recorder.for_object("repl-1")
               if e.reason == "DisruptionLaunching"][0].message
        assert msg == "Launching NodeClaim: Underutilized"
