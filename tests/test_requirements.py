"""Constraint-algebra semantics tests.

Scenario behaviors match /root/reference/pkg/scheduling/{requirement,requirements}.go,
including the complement/NotIn corner cases at requirements.go:283-304.
"""

import pytest

try:
    from hypothesis import given, strategies as st
except ImportError:  # pragma: no cover - environment-dependent
    # hypothesis is optional: only the property-based tests skip, the rest
    # of this module must stay collectible (`pytest tests/` collects clean)
    class _MissingStrategies:
        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _MissingStrategies()

    def given(*_args, **_kwargs):
        return pytest.mark.skip(reason="hypothesis not installed")

from karpenter_tpu.scheduling.requirement import (
    DOES_NOT_EXIST, EXISTS, GT, IN, INF, LT, NOT_IN, Requirement)
from karpenter_tpu.scheduling.requirements import (
    ALLOW_UNDEFINED_WELL_KNOWN, Requirements, label_requirements, pod_requirements,
    strict_pod_requirements)
from karpenter_tpu.api.objects import (
    Affinity, NodeAffinity, NodeSelectorRequirement, NodeSelectorTerm, Pod, PodSpec,
    PreferredSchedulingTerm)


def R(key, op, *values, **kw):
    return Requirement(key, op, values, **kw)


class TestRequirement:
    def test_operators(self):
        assert R("k", IN, "a").operator() == IN
        assert R("k", IN).operator() == DOES_NOT_EXIST
        assert R("k", NOT_IN, "a").operator() == NOT_IN
        assert R("k", EXISTS).operator() == EXISTS
        assert R("k", GT, "5").operator() == EXISTS
        assert R("k", LT, "5").operator() == EXISTS

    def test_has(self):
        assert R("k", IN, "a", "b").has("a")
        assert not R("k", IN, "a").has("c")
        assert R("k", NOT_IN, "a").has("b")
        assert not R("k", NOT_IN, "a").has("a")
        assert R("k", EXISTS).has("anything")
        assert not R("k", DOES_NOT_EXIST).has("anything")
        assert R("k", GT, "5").has("6")
        assert not R("k", GT, "5").has("5")
        assert not R("k", GT, "5").has("abc")  # non-integer invalid under bounds
        assert R("k", LT, "5").has("4")
        assert not R("k", LT, "5").has("5")

    def test_length(self):
        assert R("k", IN, "a", "b").length() == 2
        assert R("k", DOES_NOT_EXIST).length() == 0
        assert R("k", EXISTS).length() == INF
        assert R("k", NOT_IN, "a").length() == INF - 1

    def test_intersection_in_in(self):
        r = R("k", IN, "a", "b").intersection(R("k", IN, "b", "c"))
        assert r.operator() == IN and r.values == {"b"}

    def test_intersection_in_notin(self):
        r = R("k", IN, "a", "b").intersection(R("k", NOT_IN, "a"))
        assert r.values == {"b"} and not r.complement

    def test_intersection_notin_notin(self):
        r = R("k", NOT_IN, "a").intersection(R("k", NOT_IN, "b"))
        assert r.complement and r.values == {"a", "b"}
        assert r.operator() == NOT_IN

    def test_intersection_exists_in(self):
        r = R("k", EXISTS).intersection(R("k", IN, "a"))
        assert r.operator() == IN and r.values == {"a"}

    def test_intersection_gt_lt_crossed(self):
        r = R("k", GT, "5").intersection(R("k", LT, "3"))
        assert r.operator() == DOES_NOT_EXIST
        assert r.length() == 0

    def test_intersection_gt_lt_window(self):
        r = R("k", GT, "1").intersection(R("k", LT, "5"))
        assert r.has("2") and r.has("4")
        assert not r.has("1") and not r.has("5")
        assert r.length() == INF  # complement set remains "infinite"

    def test_intersection_bounds_filter_values(self):
        r = R("k", IN, "1", "7").intersection(R("k", GT, "5"))
        assert r.values == {"7"} and not r.complement
        # concrete results drop bounds (requirement.go:183-186)
        assert r.greater_than is None

    def test_intersection_equal_bound_crossed(self):
        r = R("k", GT, "5").intersection(R("k", LT, "5"))
        assert r.operator() == DOES_NOT_EXIST
        r2 = R("k", GT, "4").intersection(R("k", LT, "6"))
        assert r2.has("5")

    def test_min_values_propagates(self):
        a = Requirement("k", IN, ["a", "b"], min_values=2)
        b = R("k", EXISTS)
        assert a.intersection(b).min_values == 2
        assert b.intersection(a).min_values == 2

    def test_normalized_label_alias(self):
        r = R("beta.kubernetes.io/arch", IN, "amd64")
        assert r.key == "kubernetes.io/arch"

    @given(
        st.sets(st.sampled_from("abcdef"), max_size=4),
        st.sets(st.sampled_from("abcdef"), max_size=4),
        st.booleans(), st.booleans(),
    )
    def test_intersection_membership_property(self, va, vb, ca, cb):
        """intersection(a,b).has(v) == a.has(v) and b.has(v) for all probe values."""
        a = Requirement._raw("k", ca, set(va))
        b = Requirement._raw("k", cb, set(vb))
        inter = a.intersection(b)
        for v in "abcdefgh":
            assert inter.has(v) == (a.has(v) and b.has(v))


class TestRequirements:
    def test_add_intersects_per_key(self):
        reqs = Requirements([R("k", IN, "a", "b")])
        reqs.add(R("k", IN, "b", "c"))
        assert reqs.get("k").values == {"b"}

    def test_get_undefined_is_exists(self):
        assert Requirements().get("missing").operator() == EXISTS

    def test_intersects_ok(self):
        a = Requirements([R("zone", IN, "z1", "z2")])
        b = Requirements([R("zone", IN, "z2", "z3")])
        assert a.intersects(b) == []

    def test_intersects_disjoint_fails(self):
        a = Requirements([R("zone", IN, "z1")])
        b = Requirements([R("zone", IN, "z2")])
        assert a.intersects(b)

    def test_intersects_both_notin_exempt(self):
        # NotIn vs NotIn with empty intersection of their concrete views is allowed
        a = Requirements([R("k", DOES_NOT_EXIST)])
        b = Requirements([R("k", NOT_IN, "x")])
        assert a.intersects(b) == []

    def test_intersects_dne_vs_in_fails(self):
        a = Requirements([R("k", DOES_NOT_EXIST)])
        b = Requirements([R("k", IN, "x")])
        assert a.intersects(b)

    def test_intersects_exists_vs_dne_fails(self):
        # existing Exists is NOT exempt even though intersection is empty
        a = Requirements([R("k", EXISTS)])
        b = Requirements([R("k", DOES_NOT_EXIST)])
        assert a.intersects(b)

    def test_intersects_undefined_keys_allowed(self):
        a = Requirements([R("zone", IN, "z1")])
        b = Requirements([R("other", IN, "v")])
        assert a.intersects(b) == []

    def test_compatible_custom_label_undefined_denied(self):
        node = Requirements([R("zone", IN, "z1")])
        pod = Requirements([R("team", IN, "infra")])
        assert node.compatible(pod)  # custom label undefined on node side -> error

    def test_compatible_well_known_undefined_allowed(self):
        node = Requirements()
        pod = Requirements([R("topology.kubernetes.io/zone", IN, "z1")])
        assert node.compatible(pod, ALLOW_UNDEFINED_WELL_KNOWN) == []
        assert node.compatible(pod)  # without the allowance it is denied

    def test_compatible_notin_undefined_allowed(self):
        node = Requirements()
        pod = Requirements([R("team", NOT_IN, "infra")])
        assert node.compatible(pod) == []

    def test_labels_representative(self):
        reqs = Requirements([R("zone", IN, "z1"), R("kubernetes.io/hostname", IN, "h1")])
        labels = reqs.labels()
        assert labels["zone"] == "z1"
        assert "kubernetes.io/hostname" not in labels  # restricted


class TestPodRequirements:
    def _pod(self, selector=None, required=None, preferred=None):
        na = None
        if required or preferred:
            na = NodeAffinity(
                required_terms=[NodeSelectorTerm(match_expressions=tuple(required))] if required else [],
                preferred=preferred or [],
            )
        return Pod(spec=PodSpec(
            node_selector=selector or {},
            affinity=Affinity(node_affinity=na) if na else None,
        ))

    def test_node_selector(self):
        pod = self._pod(selector={"zone": "z1"})
        reqs = pod_requirements(pod)
        assert reqs.get("zone").values == {"z1"}

    def test_first_required_term_only(self):
        pod = self._pod(required=[NodeSelectorRequirement("zone", IN, ("z1",))])
        assert pod_requirements(pod).get("zone").values == {"z1"}

    def test_heaviest_preference_treated_required(self):
        pod = self._pod(preferred=[
            PreferredSchedulingTerm(1, NodeSelectorTerm(
                match_expressions=(NodeSelectorRequirement("zone", IN, ("z1",)),))),
            PreferredSchedulingTerm(10, NodeSelectorTerm(
                match_expressions=(NodeSelectorRequirement("zone", IN, ("z2",)),))),
        ])
        assert pod_requirements(pod).get("zone").values == {"z2"}
        # strict requirements exclude preferences entirely
        assert "zone" not in strict_pod_requirements(pod)


# ---------------------------------------------------------------------------
# Machine-extracted operator tables (requirement_test.go:104-893): 466
# intersection triples over 28 fixtures, 70 Has() cases, 12 length cases.
# ---------------------------------------------------------------------------

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.scheduling.requirement import INF, Requirement
from requirement_intersection_table import (ENTRIES, FIXTURES, HAS_ENTRIES,
                                            LEN_ENTRIES)


def _mk(name):
    op, values, mv = FIXTURES[name]
    return Requirement("key", op, values, min_values=mv)


def _shape(r):
    return (r.complement, frozenset(r.values), r.greater_than, r.less_than,
            r.min_values)


class TestReferenceIntersectionTable:
    def test_all_466_intersections(self):
        failures = []
        for a_name, b_name, want_name in ENTRIES:
            got = _mk(a_name).intersection(_mk(b_name))
            want = _mk(want_name)
            if _shape(got) != _shape(want):
                failures.append(
                    f"{a_name} ∩ {b_name}: got {_shape(got)}, "
                    f"want {want_name}={_shape(want)}")
        assert not failures, "\n".join(failures[:20]) + \
            f"\n... {len(failures)} total"

    def test_intersection_is_commutative_on_shape(self):
        names = list(FIXTURES)
        for a in names:
            for b in names:
                ab = _shape(_mk(a).intersection(_mk(b)))
                ba = _shape(_mk(b).intersection(_mk(a)))
                assert ab == ba, (a, b)

    def test_has_table(self):
        for name, value, want in HAS_ENTRIES:
            assert _mk(name).has(value) == want, (name, value)

    def test_length_table(self):
        for name, want in LEN_ENTRIES:
            want = INF if want == "INF" else int(want)
            assert _mk(name).length() == want, name


class TestReferenceCompatibilityMatrices:
    """requirements_test.go:57-543 — 225 lenient (well-known labels may be
    undefined) + 225 strict Compatible() verdicts over single-requirement
    sets on the zone key."""

    ZONE = api_labels.LABEL_TOPOLOGY_ZONE

    def _reqs(self, name):
        from karpenter_tpu.scheduling.requirements import Requirements
        if name == "unconstrained":
            return Requirements()
        op, values, _ = FIXTURES[name]
        return Requirements([Requirement(self.ZONE, op, values)])

    def test_lenient_matrix(self):
        from requirement_intersection_table import COMPAT_LENIENT
        from karpenter_tpu.scheduling.requirements import \
            ALLOW_UNDEFINED_WELL_KNOWN
        failures = []
        for a, b, want_ok in COMPAT_LENIENT:
            got_ok = not self._reqs(a).compatible(
                self._reqs(b), ALLOW_UNDEFINED_WELL_KNOWN)
            if got_ok != want_ok:
                failures.append(f"{a}.Compatible({b}, lenient): got "
                                f"{got_ok}, want {want_ok}")
        assert not failures, "\n".join(failures[:15]) + \
            f"\n... {len(failures)} total"

    def test_strict_matrix(self):
        from requirement_intersection_table import COMPAT_STRICT
        failures = []
        for a, b, want_ok in COMPAT_STRICT:
            got_ok = not self._reqs(a).compatible(self._reqs(b))
            if got_ok != want_ok:
                failures.append(f"{a}.Compatible({b}, strict): got "
                                f"{got_ok}, want {want_ok}")
        assert not failures, "\n".join(failures[:15]) + \
            f"\n... {len(failures)} total"


class TestTypoHints:
    """requirements.go:189-251 + requirements_test.go:544-576: unknown keys
    suggest the well-known label the user probably meant."""

    def _compat_err(self, bad_label):
        from karpenter_tpu.scheduling.requirement import EXISTS, Requirement
        from karpenter_tpu.scheduling.requirements import (
            ALLOW_UNDEFINED_WELL_KNOWN, Requirements)
        unconstrained = Requirements()
        req = Requirements([Requirement(bad_label, EXISTS, [])])
        errs = unconstrained.compatible(req, ALLOW_UNDEFINED_WELL_KNOWN)
        assert len(errs) == 1
        return errs[0]

    @pytest.mark.parametrize("bad,expected", [
        # truncations (requirements_test.go:545-556)
        ("zone", 'label "zone" does not have known values '
                 '(typo of "topology.kubernetes.io/zone"?)'),
        ("region", 'label "region" does not have known values '
                   '(typo of "topology.kubernetes.io/region"?)'),
        ("nodepool", 'label "nodepool" does not have known values '
                     '(typo of "karpenter.sh/nodepool"?)'),
        ("instance-type", 'label "instance-type" does not have known values '
                          '(typo of "node.kubernetes.io/instance-type"?)'),
        ("arch", 'label "arch" does not have known values '
                 '(typo of "kubernetes.io/arch"?)'),
        ("capacity-type", 'label "capacity-type" does not have known values '
                          '(typo of "karpenter.sh/capacity-type"?)'),
        # typos (requirements_test.go:557-570)
        ("topology.kubernetesio/zone",
         'label "topology.kubernetesio/zone" does not have known values '
         '(typo of "topology.kubernetes.io/zone"?)'),
        ("node.io/zone",
         'label "node.io/zone" does not have known values '
         '(typo of "topology.kubernetes.io/zone"?)'),
        ("topology.kubernetes.io/regio",
         'label "topology.kubernetes.io/regio" does not have known values '
         '(typo of "topology.kubernetes.io/region"?)'),
        ("karpenter.shnodepool",
         'label "karpenter.shnodepool" does not have known values '
         '(typo of "karpenter.sh/nodepool"?)'),
        ("karpenter/nodepool",
         'label "karpenter/nodepool" does not have known values '
         '(typo of "karpenter.sh/nodepool"?)'),
    ])
    def test_near_miss_hints(self, bad, expected):
        assert self._compat_err(bad) == expected

    def test_unknown_label_without_hint(self):
        """requirements_test.go:571-575: nothing close -> plain error."""
        from karpenter_tpu.scheduling.requirement import EXISTS, Requirement
        from karpenter_tpu.scheduling.requirements import Requirements
        unconstrained = Requirements()
        req = Requirements([Requirement("deployment", EXISTS, [])])
        [err] = unconstrained.compatible(req)
        assert err == 'label "deployment" does not have known values'

    def test_hint_from_existing_requirement_keys(self):
        """requirements.go:243-249: the already-required key pool is the
        second hint source."""
        from karpenter_tpu.scheduling.requirement import EXISTS, IN, Requirement
        from karpenter_tpu.scheduling.requirements import Requirements
        existing = Requirements([Requirement("example.com/team", IN, ["a"])])
        req = Requirements([Requirement("example.com/tean", EXISTS, [])])
        [err] = existing.compatible(req)
        assert '(typo of "example.com/team"?)' in err

    def test_hint_rides_the_tensor_solve(self):
        """End-to-end (VERDICT r4 #9): a typo'd nodeSelector key failing the
        TENSOR path still produces the host oracle's per-nodepool
        incompatibility message with the near-miss hint."""
        from karpenter_tpu.cloudprovider.kwok import construct_instance_types
        from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
        from factories import make_nodepool, make_pod
        ts = TensorScheduler([make_nodepool()],
                             {"default": construct_instance_types()[:24]},
                             force_tensor=True)
        r = ts.solve([make_pod(cpu="100m",
                               node_selector={"zone": "test-zone-a"})])
        [msg] = r.pod_errors.values()
        assert msg == ('incompatible with nodepool "default", incompatible '
                       'requirements, label "zone" does not have known '
                       'values (typo of "topology.kubernetes.io/zone"?)')
        # byte-identical to the host oracle's verdict for the same pod
        from factories import make_scheduler
        h = make_scheduler(
            [make_nodepool()], construct_instance_types()[:24],
            [make_pod(cpu="100m", node_selector={"zone": "test-zone-a"})])
        r2 = h.solve([make_pod(cpu="100m",
                               node_selector={"zone": "test-zone-a"})])
        [hmsg] = r2.pod_errors.values()
        assert hmsg == msg
