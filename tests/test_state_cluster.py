"""Store, Cluster, and Manager behavior (reference: state/suite_test.go shapes)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import (Node, NodeSpec, NodeStatus, ObjectMeta, Pod,
                                       PodSpec)
from karpenter_tpu.controllers.manager import Controller, Manager, Result
from karpenter_tpu.kube.store import (ADDED, DELETED, MODIFIED, ConflictError,
                                      NotFoundError, Store)
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock

from factories import affinity_term, make_pod


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def store(clock):
    return Store(clock)


@pytest.fixture
def cluster(store, clock):
    c = Cluster(store, clock)
    wire_informers(store, c)
    return c


def make_node(name, provider_id=None, cpu="16", memory="32Gi", labels=None,
              initialized=True):
    lbl = {api_labels.LABEL_HOSTNAME: name}
    if initialized:
        lbl[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
    lbl.update(labels or {})
    alloc = res.parse_list({"cpu": cpu, "memory": memory, "pods": "110"})
    return Node(metadata=ObjectMeta(name=name, namespace="", labels=lbl),
                spec=NodeSpec(provider_id=provider_id or f"test://{name}"),
                status=NodeStatus(capacity=dict(alloc), allocatable=alloc))


class TestStore:
    def test_create_get_update_delete(self, store):
        n = make_node("n1")
        store.create(n)
        assert store.get(Node, "n1") is n
        rv1 = n.metadata.resource_version
        store.update(n)
        assert n.metadata.resource_version > rv1
        store.delete(n)
        assert store.get(Node, "n1") is None

    def test_create_conflict(self, store):
        store.create(make_node("n1"))
        with pytest.raises(ConflictError):
            store.create(make_node("n1"))

    def test_update_missing(self, store):
        with pytest.raises(NotFoundError):
            store.update(make_node("ghost"))

    def test_finalizer_two_phase_delete(self, store, clock):
        n = make_node("n1")
        n.metadata.finalizers.append("karpenter.sh/termination")
        store.create(n)
        store.delete(n)
        # still present, deletion stamped
        assert store.get(Node, "n1") is n
        assert n.metadata.deletion_timestamp == clock.now()
        store.delete(n)  # idempotent
        store.remove_finalizer(n, "karpenter.sh/termination")
        assert store.get(Node, "n1") is None

    def test_watch_events(self, store):
        seen = []
        store.watch(lambda ev: seen.append((ev.type, ev.obj.metadata.name)))
        n = make_node("n1")
        store.create(n)
        store.update(n)
        store.delete(n)
        assert seen == [("ADDED", "n1"), ("MODIFIED", "n1"), ("DELETED", "n1")]


class TestCluster:
    def test_node_tracking_via_informers(self, store, cluster):
        store.create(make_node("n1"))
        assert len(cluster.nodes) == 1
        assert cluster.synced()
        sn = cluster.state_nodes()[0]
        assert sn.name() == "n1"
        assert sn.initialized()

    def test_pod_binding_updates_available(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        pod = make_pod(cpu="1000m")
        pod.spec.node_name = "n1"
        store.create(pod)
        sn = cluster.state_nodes()[0]
        assert sn.available()["cpu"] == 3000
        store.delete(pod)
        sn = cluster.state_nodes()[0]
        assert sn.available()["cpu"] == 4000

    def test_nodeclaim_then_node_unify_by_provider_id(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.status.provider_id = "test://n1"
        store.create(nc)
        assert len(cluster.nodes) == 1
        store.create(make_node("n1", provider_id="test://n1"))
        assert len(cluster.nodes) == 1
        sn = cluster.nodes["test://n1"]
        assert sn.node is not None and sn.nodeclaim is not None

    def test_nodeclaim_placeholder_migrates(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        store.create(nc)  # no providerID yet
        assert "nodeclaim://nc1" in cluster.nodes
        nc.status.provider_id = "test://real"
        store.update(nc)
        assert "nodeclaim://nc1" not in cluster.nodes
        assert "test://real" in cluster.nodes
        assert cluster.synced()

    def test_mark_for_deletion_and_consolidation_state(self, store, cluster, clock):
        store.create(make_node("n1"))
        t = cluster.consolidation_state()
        clock.step(1)
        cluster.mark_for_deletion("test://n1")
        assert cluster.consolidation_state() != t  # change bumped the token
        assert cluster.nodes["test://n1"].deleting()
        cluster.unmark_for_deletion("test://n1")
        assert not cluster.nodes["test://n1"].deleting()

    def test_consolidation_state_forced_revalidation(self, cluster, clock):
        t = cluster.consolidation_state()
        clock.step(100)
        assert cluster.consolidation_state() == t  # quiet cluster: stable
        clock.step(301)
        assert cluster.consolidation_state() != t  # 5-min forced bump

    def test_nomination_window(self, store, cluster, clock):
        store.create(make_node("n1"))
        pod = make_pod()
        store.create(pod)
        cluster.nominate_node_for_pod("n1", pod)
        sn = cluster.nodes["test://n1"]
        assert sn.nominated(clock.now())
        clock.step(21)
        assert not sn.nominated(clock.now())

    def test_deep_copy_isolation(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        snapshot = cluster.state_nodes()
        pod = make_pod(cpu="1000m")
        pod.spec.node_name = "n1"
        store.create(pod)
        # snapshot taken before the pod landed is unaffected
        assert snapshot[0].available()["cpu"] == 4000

    def test_daemonset_cache(self, store, cluster):
        pod = make_pod(cpu="100m")
        pod.is_daemonset_pod = True
        pod.spec.node_name = ""
        store.create(pod)
        assert len(cluster.daemonset_pod_list()) == 1


class TestManager:
    def test_watch_controller_dispatch_and_requeue(self, store, clock):
        mgr = Manager(store, clock)
        seen = []

        class C(Controller):
            name = "test"
            kinds = (Node,)

            def reconcile(self, obj):
                seen.append(obj.metadata.name)
                if len(seen) == 1:
                    return Result(requeue_after=10.0)
                return None

        mgr.register(C())
        store.create(make_node("n1"))
        assert mgr.drain() == 1
        assert seen == ["n1"]
        # requeue fires only after the clock advances
        assert mgr.drain() == 0
        mgr.advance(10.0)
        assert seen == ["n1", "n1"]

    def test_queue_dedup(self, store, clock):
        mgr = Manager(store, clock)
        count = []

        class C(Controller):
            name = "test"
            kinds = (Node,)

            def reconcile(self, obj):
                count.append(1)

        mgr.register(C())
        n = make_node("n1")
        store.create(n)
        store.update(n)
        store.update(n)
        assert mgr.drain() == 1  # deduped to one work item


# ---------------------------------------------------------------------------
# Widened port of /root/reference/pkg/controllers/state/suite_test.go
# ---------------------------------------------------------------------------

from karpenter_tpu.api.objects import HostPort, OwnerReference, PVCRef, Taint
from karpenter_tpu.api.storage import (CSINode, CSINodeDriver,
                                       PersistentVolumeClaim, PVCSpec,
                                       StorageClass)
from karpenter_tpu.provisioning.provisioner import StateClusterView
from karpenter_tpu.scheduling.hostports import get_host_ports
from karpenter_tpu.scheduling.taints import NO_EXECUTE, NO_SCHEDULE
from karpenter_tpu.scheduling.volumeusage import Volumes, node_volume_limits
from karpenter_tpu.state.statenode import StateNode


def bind(store, pod, node_name):
    pod.spec.node_name = node_name
    store.update(pod)


class TestPodAck:
    """suite_test.go:102-118."""

    def test_scheduling_decision_marked_once(self, store, cluster, clock):
        pod = make_pod()
        store.create(pod)
        key = f"{pod.namespace}/{pod.name}"
        assert key not in cluster.pod_scheduling_decisions
        cluster.mark_pod_scheduling_decisions({}, {key: "n1"})
        t0 = cluster.pod_scheduling_decisions[key]
        clock.step(5)
        cluster.mark_pod_scheduling_decisions({}, {key: "n2"})
        assert cluster.pod_scheduling_decisions[key] == t0  # first write wins

    def test_ack_only_once(self, store, cluster, clock):
        pod = make_pod()
        store.create(pod)
        cluster.ack_pods([pod])
        t0 = cluster.pod_acks[f"{pod.namespace}/{pod.name}"]
        clock.step(3)
        cluster.ack_pods([pod])
        assert cluster.pod_acks[f"{pod.namespace}/{pod.name}"] == t0


class TestNodeResourceLevel:
    """suite_test.go:365-843 (Node Resource Level)."""

    def test_does_not_count_unbound_pods(self, store, cluster):
        store.create(make_pod(cpu="1500m"))
        store.create(make_node("n1", cpu="4"))
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total() == {}
        assert sn.available()["cpu"] == 4000

    def test_counts_new_pods_bound_to_node(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        p1, p2 = make_pod(cpu="1500m"), make_pod(cpu="1")
        store.create(p1)
        store.create(p2)
        bind(store, p1, "n1")
        bind(store, p2, "n1")
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total()["cpu"] == 2500
        assert sn.available()["cpu"] == 1500

    def test_counts_existing_pods_bound_before_node_tracked(self, store, cluster):
        """Hydration: pods bound before the node appears must be counted
        (populateResourceRequests, suite_test.go:439-471)."""
        p1 = make_pod(cpu="1500m")
        p1.spec.node_name = "n1"
        store.create(p1)
        store.create(make_node("n1", cpu="4"))
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total()["cpu"] == 1500
        assert sn.available()["cpu"] == 2500

    def test_subtracts_requests_when_pod_deleted(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        pod = make_pod(cpu="1500m")
        store.create(pod)
        bind(store, pod, "n1")
        store.delete(pod)
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total() == {}
        assert sn.available()["cpu"] == 4000

    def test_terminal_pods_not_counted(self, store, cluster):
        """suite_test.go:519-557: Failed/Succeeded pods consume nothing."""
        store.create(make_node("n1", cpu="4"))
        p1, p2 = make_pod(cpu="1500m"), make_pod(cpu="2")
        p1.status.phase = "Failed"
        p2.status.phase = "Succeeded"
        store.create(p1)
        store.create(p2)
        bind(store, p1, "n1")
        bind(store, p2, "n1")
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total() == {}

    def test_pod_turning_terminal_releases_usage(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        pod = make_pod(cpu="1500m")
        store.create(pod)
        bind(store, pod, "n1")
        assert cluster.nodes["test://n1"].pod_request_total()["cpu"] == 1500
        pod.status.phase = "Succeeded"
        store.update(pod)
        assert cluster.nodes["test://n1"].pod_request_total() == {}

    def test_stops_tracking_deleted_nodes(self, store, cluster):
        node = make_node("n1", cpu="4")
        store.create(node)
        pod = make_pod(cpu="1500m")
        store.create(pod)
        bind(store, pod, "n1")
        store.delete(node)
        assert cluster.nodes == {}
        assert cluster.state_nodes() == []

    def test_missed_delete_event_reused_pod_name(self, store, cluster):
        """suite_test.go:598-673: a pod deleted+recreated under the same name
        on another node (DELETE event missed) must free the old node."""
        store.create(make_node("n1", cpu="4"))
        store.create(make_node("n2", cpu="8"))
        p1 = make_pod(cpu="1500m", name="stateful-set-pod")
        store.create(p1)
        bind(store, p1, "n1")
        assert cluster.nodes["test://n1"].available()["cpu"] == 2500
        # simulate: p1 deleted and re-created bound to n2, we only see the
        # new pod's event (delivered directly, not through the store)
        p2 = make_pod(cpu="5", name="stateful-set-pod")
        p2.spec.node_name = "n2"
        cluster.update_pod(p2)
        assert cluster.nodes["test://n1"].available()["cpu"] == 4000
        assert cluster.nodes["test://n1"].pod_request_total() == {}
        assert cluster.nodes["test://n2"].pod_request_total()["cpu"] == 5000
        assert cluster.nodes["test://n2"].available()["cpu"] == 3000

    def test_usage_count_through_add_delete_churn(self, store, cluster):
        """suite_test.go:674-740."""
        store.create(make_node("n1", cpu="200000m"))
        pods = [make_pod(cpu=f"{(i % 20) * 100 + 100}m") for i in range(100)]
        total = 0
        for p in pods:
            store.create(p)
            bind(store, p, "n1")
            total += p.requests()["cpu"]
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total()["cpu"] == total
        for p in pods[::2]:
            store.delete(p)
            total -= p.requests()["cpu"]
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total()["cpu"] == total
        for p in pods[1::2]:
            store.delete(p)
        assert cluster.nodes["test://n1"].pod_request_total() == {}

    def test_daemonset_requests_tracked_separately(self, store, cluster):
        """suite_test.go:741-817."""
        store.create(make_node("n1", cpu="4"))
        ds_pod = make_pod(cpu="500m")
        ds_pod.is_daemonset_pod = True
        ds_pod.metadata.owner_refs.append(
            OwnerReference(kind="DaemonSet", name="fluentd"))
        reg = make_pod(cpu="1")
        store.create(ds_pod)
        store.create(reg)
        bind(store, ds_pod, "n1")
        bind(store, reg, "n1")
        sn = cluster.nodes["test://n1"]
        assert sn.daemonset_requests()["cpu"] == 500
        assert sn.pod_request_total()["cpu"] == 1500
        store.delete(ds_pod)
        sn = cluster.nodes["test://n1"]
        assert sn.daemonset_requests() == {}

    def test_mark_node_for_deletion_on_node_delete_timestamp(self, store, cluster, clock):
        node = make_node("n1")
        node.metadata.finalizers.append("karpenter.sh/termination")
        store.create(node)
        store.delete(node)  # finalizer holds it: deletionTimestamp stamped
        assert cluster.nodes["test://n1"].deleting()

    def test_mark_node_for_deletion_on_nodeclaim_delete_timestamp(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.metadata.finalizers.append("karpenter.sh/termination")
        nc.status.provider_id = "test://n1"
        store.create(nc)
        store.create(make_node("n1"))
        store.delete(nc)
        assert cluster.nodes["test://n1"].deleting()

    def test_provider_id_registration_migrates_state(self, store, cluster):
        """suite_test.go:928-945: a node gaining a providerID later must not
        duplicate or lose its state."""
        node = make_node("n1")
        node.spec.provider_id = ""
        store.create(node)
        assert "node://n1" in cluster.nodes
        pod = make_pod(cpu="1")
        store.create(pod)
        bind(store, pod, "n1")
        assert cluster.nodes["node://n1"].pod_request_total()["cpu"] == 1000
        node.spec.provider_id = "real://n1"
        store.update(node)
        assert "node://n1" not in cluster.nodes
        assert len(cluster.nodes) == 1
        assert cluster.nodes["real://n1"].pod_request_total()["cpu"] == 1000


class TestVolumeUsageState:
    """suite_test.go:120-234 (Volume Usage/Limits)."""

    def _make_csi_world(self, store, n_pods=10):
        store.create(StorageClass(metadata=ObjectMeta(name="my-sc", namespace=""),
                                  provisioner="csi.test.com"))
        for i in range(n_pods):
            pvc = PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"pvc-{i}"),
                spec=PVCSpec(storage_class_name="my-sc"))
            store.create(pvc)
            pod = make_pod()
            pod.spec.volumes.append(PVCRef(claim_name=f"pvc-{i}"))
            pod.spec.node_name = "n1"
            store.create(pod)
        store.create(CSINode(metadata=ObjectMeta(name="n1", namespace=""),
                             drivers=[CSINodeDriver(name="csi.test.com",
                                                    allocatable_count=10)]))

    def test_hydrates_volume_usage_on_node_update(self, store, cluster):
        self._make_csi_world(store)
        store.create(make_node("n1"))  # node arrives after the pods
        sn = cluster.nodes["test://n1"]
        limits = node_volume_limits(store, "n1")
        assert sn.volume_usage().exceeds_limits(
            Volumes({"csi.test.com": {"default/one-more"}}), limits) is not None

    def test_maintains_volume_usage_across_nodeclaim_updates(self, store, cluster):
        self._make_csi_world(store)
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.status.provider_id = "test://n1"
        store.create(nc)
        store.create(make_node("n1"))
        store.update(nc)  # nodeclaim reconcile must not wipe usage
        sn = cluster.nodes["test://n1"]
        limits = node_volume_limits(store, "n1")
        assert sn.volume_usage().exceeds_limits(
            Volumes({"csi.test.com": {"default/one-more"}}), limits) is not None

    def test_already_tracked_volume_is_not_a_breach(self, store, cluster):
        self._make_csi_world(store)
        store.create(make_node("n1"))
        sn = cluster.nodes["test://n1"]
        limits = node_volume_limits(store, "n1")
        assert sn.volume_usage().exceeds_limits(
            Volumes({"csi.test.com": {"default/pvc-5"}}), limits) is None


class TestHostPortUsageState:
    """suite_test.go:235-336 (HostPort Usage)."""

    def _bind_port_pods(self, store, n=10):
        pods = []
        for i in range(n):
            pod = make_pod(host_ports=[HostPort(port=i)])
            pod.spec.node_name = "n1"
            store.create(pod)
            pods.append(pod)
        return pods

    def test_hydrates_host_port_usage_on_node_update(self, store, cluster):
        self._bind_port_pods(store)
        store.create(make_node("n1"))
        sn = cluster.nodes["test://n1"]
        probe = make_pod(host_ports=[HostPort(port=5)])
        assert sn.host_port_usage().conflicts(probe, get_host_ports(probe))

    def test_maintains_host_port_usage_across_nodeclaim_updates(self, store, cluster):
        self._bind_port_pods(store)
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.status.provider_id = "test://n1"
        store.create(nc)
        store.create(make_node("n1"))
        store.update(nc)
        sn = cluster.nodes["test://n1"]
        probe = make_pod(host_ports=[HostPort(port=5)])
        assert sn.host_port_usage().conflicts(probe, get_host_ports(probe))

    def test_own_tracked_port_is_not_a_conflict(self, store, cluster):
        pods = self._bind_port_pods(store)
        store.create(make_node("n1"))
        sn = cluster.nodes["test://n1"]
        assert sn.host_port_usage().conflicts(
            pods[5], get_host_ports(pods[5])) == []

    def test_disjoint_ips_no_conflict(self, store, cluster):
        store.create(make_node("n1"))
        p1 = make_pod(host_ports=[HostPort(port=80, host_ip="10.0.0.1")])
        store.create(p1)
        bind(store, p1, "n1")
        sn = cluster.nodes["test://n1"]
        probe = make_pod(host_ports=[HostPort(port=80, host_ip="10.0.0.2")])
        assert sn.host_port_usage().conflicts(probe, get_host_ports(probe)) == []
        wildcard = make_pod(host_ports=[HostPort(port=80)])
        assert sn.host_port_usage().conflicts(wildcard, get_host_ports(wildcard))


class TestNodeDeletionNoLeak:
    """suite_test.go:337-364: NodeClaim and Node sharing a name must not
    leak a state node."""

    def test_same_name_nodeclaim_and_node(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="shared", namespace=""))
        nc.status.provider_id = "test://shared"
        node = make_node("shared", provider_id="test://shared")
        store.create(nc)
        store.create(node)
        assert len(cluster.nodes) == 1
        store.delete(nc)
        assert len(cluster.nodes) == 1  # node still alive
        store.delete(node)
        assert len(cluster.nodes) == 0


class TestAntiAffinityTracking:
    """suite_test.go:946-1129 (Pod Anti-Affinity)."""

    def _anti_pod(self, **kw):
        return make_pod(pod_anti_affinity=[affinity_term(
            api_labels.LABEL_TOPOLOGY_ZONE)], **kw)

    def test_tracks_required_anti_affinity(self, store, cluster):
        pod = self._anti_pod()
        store.create(pod)
        assert [p.name for p in cluster.anti_affinity_pods()] == [pod.name]

    def test_does_not_track_preferred_anti_affinity(self, store, cluster):
        pod = make_pod(preferred_pod_anti_affinity=[
            (1, affinity_term(api_labels.LABEL_TOPOLOGY_ZONE))])
        store.create(pod)
        assert cluster.anti_affinity_pods() == []

    def test_stops_tracking_on_delete(self, store, cluster):
        pod = self._anti_pod()
        store.create(pod)
        store.delete(pod)
        assert cluster.anti_affinity_pods() == []

    def test_out_of_order_node_deletion(self, store, cluster):
        """suite_test.go:1083-1129: node deleted before the pod — the
        anti-affinity join must yield nothing rather than a dangling node."""
        node = make_node("n1")
        store.create(node)
        pod = self._anti_pod()
        store.create(pod)
        bind(store, pod, "n1")
        store.delete(node)
        view = StateClusterView(store, cluster)
        assert list(view.for_pods_with_anti_affinity()) == []


class TestClusterStateSync:
    """suite_test.go:1130-1341 (Cluster State Sync)."""

    def test_synced_when_all_nodes_tracked(self, store, cluster):
        for i in range(3):
            store.create(make_node(f"n{i}"))
        assert cluster.synced()

    def test_synced_when_node_has_no_provider_id(self, store, cluster):
        node = make_node("n1")
        node.spec.provider_id = ""
        store.create(node)
        assert cluster.synced()

    def test_synced_when_nodeclaims_tracked(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.status.provider_id = "test://n1"
        store.create(nc)
        assert cluster.synced()

    def test_unsynced_when_nodeclaim_added_manually(self, store, cluster):
        """A nodeclaim in the store the informers never delivered."""
        nc = NodeClaim(metadata=ObjectMeta(name="ghost", namespace=""))
        store._objs.setdefault(NodeClaim, {})[("", "ghost")] = nc
        assert not cluster.synced()

    def test_unsynced_when_node_added_manually(self, store, cluster):
        node = make_node("ghost")
        store._objs.setdefault(Node, {})[("", "ghost")] = node
        assert not cluster.synced()

    def test_synced_again_after_unresolved_nodeclaim_deleted(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        store.create(nc)  # no providerID: tracked under a placeholder
        assert cluster.synced()
        store.delete(nc)
        assert cluster.synced()
        assert cluster.nodes == {}


class TestDaemonSetCache:
    """suite_test.go:1342-1465 (DaemonSet Controller)."""

    def _ds_pod(self, ds="fluentd", **kw):
        pod = make_pod(**kw)
        pod.is_daemonset_pod = True
        pod.metadata.owner_refs.append(OwnerReference(kind="DaemonSet", name=ds))
        return pod

    def test_non_daemonset_pod_not_cached(self, store, cluster):
        store.create(make_pod())
        assert cluster.daemonset_pod_list() == []

    def test_daemonset_pod_cached(self, store, cluster):
        store.create(self._ds_pod())
        assert len(cluster.daemonset_pod_list()) == 1

    def test_newest_pod_wins(self, store, cluster, clock):
        old = self._ds_pod(cpu="100m")
        store.create(old)
        clock.step(10)
        new = self._ds_pod(cpu="200m")
        store.create(new)
        [cached] = cluster.daemonset_pod_list()
        assert cached.uid == new.uid
        # an out-of-order event for the older pod must not displace it
        cluster.update_pod(old)
        [cached] = cluster.daemonset_pod_list()
        assert cached.uid == new.uid

    def test_cache_entry_dropped_when_daemonset_gone(self, store, cluster, clock):
        p1 = self._ds_pod()
        store.create(p1)
        clock.step(1)
        p2 = self._ds_pod()
        store.create(p2)
        store.delete(p2)  # exemplar dies, sibling survives
        [cached] = cluster.daemonset_pod_list()
        assert cached.uid == p1.uid
        store.delete(p1)  # daemonset fully gone
        assert cluster.daemonset_pod_list() == []

    def test_two_daemonsets_cached_independently(self, store, cluster):
        store.create(self._ds_pod(ds="fluentd"))
        store.create(self._ds_pod(ds="node-exporter"))
        assert len(cluster.daemonset_pod_list()) == 2


class TestConsolidatedState:
    """suite_test.go:1466-1498 (Consolidated State)."""

    def test_mark_unconsolidated_bumps_token(self, cluster, clock):
        t = cluster.consolidation_state()
        clock.step(1)
        cluster.mark_unconsolidated()
        assert cluster.consolidation_state() != t

    def test_five_minute_forced_bump(self, cluster, clock):
        t = cluster.consolidation_state()
        clock.step(60)
        assert cluster.consolidation_state() == t
        clock.step(180)
        assert cluster.consolidation_state() == t
        clock.step(120)
        assert cluster.consolidation_state() != t

    def test_nodepool_update_bumps_token(self, store, cluster, clock):
        from factories import make_nodepool
        np = make_nodepool()
        store.create(np)
        clock.step(1)
        t = cluster.consolidation_state()
        clock.step(1)
        store.update(np)
        assert cluster.consolidation_state() != t


class TestStateNodeTaints:
    """suite_test.go:1554-1700 (Taints, managed vs unmanaged)."""

    EPHEMERAL = [
        Taint(key="node.kubernetes.io/not-ready", effect=NO_SCHEDULE),
        Taint(key="node.kubernetes.io/unreachable", effect=NO_SCHEDULE),
        Taint(key="node.cloudprovider.kubernetes.io/uninitialized",
              effect=NO_SCHEDULE, value="true"),
    ]
    STARTUP = [
        Taint(key="taint-key", value="taint-value", effect=NO_SCHEDULE),
        Taint(key="taint-key2", value="taint-value2", effect=NO_EXECUTE),
    ]

    def _managed(self, store, cluster, taints, startup_taints=(), initialized=False):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.status.provider_id = "test://n1"
        nc.spec.startup_taints = list(startup_taints)
        store.create(nc)
        node = make_node("n1", initialized=initialized)
        node.spec.taints = list(taints)
        store.create(node)
        return cluster.nodes["test://n1"]

    def test_managed_uninitialized_hides_ephemeral(self, store, cluster):
        sn = self._managed(store, cluster, self.EPHEMERAL)
        assert sn.taints() == []

    def test_managed_initialized_shows_ephemeral(self, store, cluster):
        sn = self._managed(store, cluster, self.EPHEMERAL, initialized=True)
        assert len(sn.taints()) == 3

    def test_managed_uninitialized_hides_startup_taints(self, store, cluster):
        sn = self._managed(store, cluster, self.STARTUP,
                           startup_taints=self.STARTUP)
        assert sn.taints() == []

    def test_managed_initialized_shows_startup_taints(self, store, cluster):
        sn = self._managed(store, cluster, self.STARTUP,
                           startup_taints=self.STARTUP, initialized=True)
        assert len(sn.taints()) == 2

    def test_unmanaged_uninitialized_shows_ephemeral(self, store, cluster):
        node = make_node("n1", initialized=False)
        node.spec.taints = list(self.EPHEMERAL)
        store.create(node)
        sn = cluster.nodes["test://n1"]
        assert not sn.managed()
        assert len(sn.taints()) == 3

    def test_unmanaged_initialized_shows_ephemeral(self, store, cluster):
        node = make_node("n1", initialized=True)
        node.spec.taints = list(self.EPHEMERAL)
        store.create(node)
        assert len(cluster.nodes["test://n1"].taints()) == 3


class TestSameNodeUidReuse:
    def test_missed_delete_same_node_does_not_double_count(self, store, cluster):
        """A pod deleted+recreated under the same name on the SAME node
        (missed DELETE) must not leak the old uid's usage."""
        store.create(make_node("n1", cpu="4"))
        p1 = make_pod(cpu="1500m", name="stateful-set-pod")
        store.create(p1)
        bind(store, p1, "n1")
        p2 = make_pod(cpu="1", name="stateful-set-pod")
        p2.spec.node_name = "n1"
        cluster.update_pod(p2)  # direct event; DELETE for p1 never seen
        sn = cluster.nodes["test://n1"]
        assert sn.pod_request_total()["cpu"] == 1000
        assert set(sn.pod_requests) == {p2.uid}
