"""Store, Cluster, and Manager behavior (reference: state/suite_test.go shapes)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import (Node, NodeSpec, NodeStatus, ObjectMeta, Pod,
                                       PodSpec)
from karpenter_tpu.controllers.manager import Controller, Manager, Result
from karpenter_tpu.kube.store import (ADDED, DELETED, MODIFIED, ConflictError,
                                      NotFoundError, Store)
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock

from factories import make_pod


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def store(clock):
    return Store(clock)


@pytest.fixture
def cluster(store, clock):
    c = Cluster(store, clock)
    wire_informers(store, c)
    return c


def make_node(name, provider_id=None, cpu="16", memory="32Gi", labels=None,
              initialized=True):
    lbl = {api_labels.LABEL_HOSTNAME: name}
    if initialized:
        lbl[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
    lbl.update(labels or {})
    alloc = res.parse_list({"cpu": cpu, "memory": memory, "pods": "110"})
    return Node(metadata=ObjectMeta(name=name, namespace="", labels=lbl),
                spec=NodeSpec(provider_id=provider_id or f"test://{name}"),
                status=NodeStatus(capacity=dict(alloc), allocatable=alloc))


class TestStore:
    def test_create_get_update_delete(self, store):
        n = make_node("n1")
        store.create(n)
        assert store.get(Node, "n1") is n
        rv1 = n.metadata.resource_version
        store.update(n)
        assert n.metadata.resource_version > rv1
        store.delete(n)
        assert store.get(Node, "n1") is None

    def test_create_conflict(self, store):
        store.create(make_node("n1"))
        with pytest.raises(ConflictError):
            store.create(make_node("n1"))

    def test_update_missing(self, store):
        with pytest.raises(NotFoundError):
            store.update(make_node("ghost"))

    def test_finalizer_two_phase_delete(self, store, clock):
        n = make_node("n1")
        n.metadata.finalizers.append("karpenter.sh/termination")
        store.create(n)
        store.delete(n)
        # still present, deletion stamped
        assert store.get(Node, "n1") is n
        assert n.metadata.deletion_timestamp == clock.now()
        store.delete(n)  # idempotent
        store.remove_finalizer(n, "karpenter.sh/termination")
        assert store.get(Node, "n1") is None

    def test_watch_events(self, store):
        seen = []
        store.watch(lambda ev: seen.append((ev.type, ev.obj.metadata.name)))
        n = make_node("n1")
        store.create(n)
        store.update(n)
        store.delete(n)
        assert seen == [("ADDED", "n1"), ("MODIFIED", "n1"), ("DELETED", "n1")]


class TestCluster:
    def test_node_tracking_via_informers(self, store, cluster):
        store.create(make_node("n1"))
        assert len(cluster.nodes) == 1
        assert cluster.synced()
        sn = cluster.state_nodes()[0]
        assert sn.name() == "n1"
        assert sn.initialized()

    def test_pod_binding_updates_available(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        pod = make_pod(cpu="1000m")
        pod.spec.node_name = "n1"
        store.create(pod)
        sn = cluster.state_nodes()[0]
        assert sn.available()["cpu"] == 3000
        store.delete(pod)
        sn = cluster.state_nodes()[0]
        assert sn.available()["cpu"] == 4000

    def test_nodeclaim_then_node_unify_by_provider_id(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        nc.status.provider_id = "test://n1"
        store.create(nc)
        assert len(cluster.nodes) == 1
        store.create(make_node("n1", provider_id="test://n1"))
        assert len(cluster.nodes) == 1
        sn = cluster.nodes["test://n1"]
        assert sn.node is not None and sn.nodeclaim is not None

    def test_nodeclaim_placeholder_migrates(self, store, cluster):
        nc = NodeClaim(metadata=ObjectMeta(name="nc1", namespace=""))
        store.create(nc)  # no providerID yet
        assert "nodeclaim://nc1" in cluster.nodes
        nc.status.provider_id = "test://real"
        store.update(nc)
        assert "nodeclaim://nc1" not in cluster.nodes
        assert "test://real" in cluster.nodes
        assert cluster.synced()

    def test_mark_for_deletion_and_consolidation_state(self, store, cluster, clock):
        store.create(make_node("n1"))
        t = cluster.consolidation_state()
        clock.step(1)
        cluster.mark_for_deletion("test://n1")
        assert cluster.consolidation_state() != t  # change bumped the token
        assert cluster.nodes["test://n1"].deleting()
        cluster.unmark_for_deletion("test://n1")
        assert not cluster.nodes["test://n1"].deleting()

    def test_consolidation_state_forced_revalidation(self, cluster, clock):
        t = cluster.consolidation_state()
        clock.step(100)
        assert cluster.consolidation_state() == t  # quiet cluster: stable
        clock.step(301)
        assert cluster.consolidation_state() != t  # 5-min forced bump

    def test_nomination_window(self, store, cluster, clock):
        store.create(make_node("n1"))
        pod = make_pod()
        store.create(pod)
        cluster.nominate_node_for_pod("n1", pod)
        sn = cluster.nodes["test://n1"]
        assert sn.nominated(clock.now())
        clock.step(21)
        assert not sn.nominated(clock.now())

    def test_deep_copy_isolation(self, store, cluster):
        store.create(make_node("n1", cpu="4"))
        snapshot = cluster.state_nodes()
        pod = make_pod(cpu="1000m")
        pod.spec.node_name = "n1"
        store.create(pod)
        # snapshot taken before the pod landed is unaffected
        assert snapshot[0].available()["cpu"] == 4000

    def test_daemonset_cache(self, store, cluster):
        pod = make_pod(cpu="100m")
        pod.is_daemonset_pod = True
        pod.spec.node_name = ""
        store.create(pod)
        assert len(cluster.daemonset_pod_list()) == 1


class TestManager:
    def test_watch_controller_dispatch_and_requeue(self, store, clock):
        mgr = Manager(store, clock)
        seen = []

        class C(Controller):
            name = "test"
            kinds = (Node,)

            def reconcile(self, obj):
                seen.append(obj.metadata.name)
                if len(seen) == 1:
                    return Result(requeue_after=10.0)
                return None

        mgr.register(C())
        store.create(make_node("n1"))
        assert mgr.drain() == 1
        assert seen == ["n1"]
        # requeue fires only after the clock advances
        assert mgr.drain() == 0
        mgr.advance(10.0)
        assert seen == ["n1", "n1"]

    def test_queue_dedup(self, store, clock):
        mgr = Manager(store, clock)
        count = []

        class C(Controller):
            name = "test"
            kinds = (Node,)

            def reconcile(self, obj):
                count.append(1)

        mgr.register(C())
        n = make_node("n1")
        store.create(n)
        store.update(n)
        store.update(n)
        assert mgr.drain() == 1  # deduped to one work item
