"""ProblemState: incremental delta solver tests (ISSUE 6 tentpole).

Every test here enforces ONE contract: a solve through a persistent
ProblemState (delta path) makes decisions bit-identical to a cold solve of
the same inputs — across every row of the invalidation matrix
(provisioning/problem_state.py module docstring) and under a seeded churn
stream interleaving pod arrivals/deletions, node churn, and drought marks.
"""

import pytest

import numpy as np

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import (COND_INITIALIZED, COND_LAUNCHED,
                                         COND_REGISTERED, NodeClaim,
                                         NodeClaimSpec)
from karpenter_tpu.api.objects import (LabelSelector, Node, NodeSpec,
                                       NodeStatus, ObjectMeta, Pod, PodSpec,
                                       TopologySpreadConstraint)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.grouping import group_signature, partition_pods
from karpenter_tpu.provisioning.problem_state import ProblemState
from karpenter_tpu.provisioning.provisioner import StateClusterView
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.state.unavailable import UnavailableOfferings
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods, spread_zone

pytestmark = pytest.mark.churn


def digest(r):
    """Full decision digest: launch claims, existing-node fills, errors."""
    return (sorted(
        (nc.template.nodepool_name,
         tuple(sorted(nc.requirements.get(
             api_labels.LABEL_TOPOLOGY_ZONE).values)),
         tuple(it.name for it in nc.instance_type_options),
         len(nc.pods),
         tuple(sorted(p.metadata.name for p in nc.pods)))
        for nc in r.new_nodeclaims),
        sorted((en.name, tuple(sorted(p.metadata.name for p in en.pods)))
               for en in r.existing_nodes if en.pods),
        {uid: msg for uid, msg in r.pod_errors.items()})


class ChurnEnv:
    """A live cluster (store + informers + state) plus a persistent
    ProblemState; solve_pair() runs the delta path and a cold control on
    identical inputs and asserts bit-identical decisions."""

    def __init__(self, n_nodes=4, pods_per_node=2, catalog=None):
        self.clock = FakeClock()
        self.store = Store(self.clock)
        self.cluster = Cluster(self.store, self.clock)
        wire_informers(self.store, self.cluster)
        self.catalog = catalog if catalog is not None \
            else construct_instance_types()
        self.pool = make_nodepool(name="default")
        self.ps = ProblemState()
        self.registry = UnavailableOfferings(clock=self.clock)
        self.bound = {}
        self._seq = 0
        big = next(it for it in self.catalog
                   if it.capacity.get("cpu") == 4000)
        self.node_type = big
        for i in range(n_nodes):
            self.add_node(i, pods_per_node)

    def add_node(self, i, pods_per_node=0):
        name = f"churn-node-{i:03d}"
        labels = {
            api_labels.LABEL_HOSTNAME: name,
            api_labels.NODEPOOL_LABEL_KEY: "default",
            api_labels.NODE_INITIALIZED_LABEL_KEY: "true",
            api_labels.NODE_REGISTERED_LABEL_KEY: "true",
            api_labels.LABEL_INSTANCE_TYPE: self.node_type.name,
            api_labels.LABEL_TOPOLOGY_ZONE: f"test-zone-{'abc'[i % 3]}",
            api_labels.CAPACITY_TYPE_LABEL_KEY:
                api_labels.CAPACITY_TYPE_ON_DEMAND,
        }
        nc = NodeClaim(metadata=ObjectMeta(name=f"churn-nc-{i:03d}",
                                           namespace="",
                                           labels=dict(labels)),
                       spec=NodeClaimSpec())
        nc.status.provider_id = f"churn://{i}"
        nc.status.node_name = name
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED):
            nc.conditions.set_true(cond, now=self.clock.now())
        self.store.create(nc)
        self.store.create(Node(
            metadata=ObjectMeta(name=name, namespace="", labels=labels),
            spec=NodeSpec(provider_id=f"churn://{i}"),
            status=NodeStatus(capacity=dict(self.node_type.capacity),
                              allocatable=self.node_type.allocatable())))
        self.bound.setdefault(name, [])
        for _ in range(pods_per_node):
            self.bind_pod(name)
        return name

    def bind_pod(self, node_name, labels=None):
        self._seq += 1
        p = Pod(metadata=ObjectMeta(name=f"bound-{self._seq}",
                                    namespace="default",
                                    labels=dict(labels or {"warm": "w"})),
                spec=PodSpec(node_name=node_name),
                container_requests=[res.parse_list(
                    {"cpu": "200m", "memory": "128Mi"})])
        self.store.create(p)
        self.bound[node_name].append(p)
        return p

    def complete_bound(self, node_name):
        if self.bound.get(node_name):
            self.store.delete(self.bound[node_name].pop())

    def delete_node(self, name):
        node = self.store.get(Node, name)
        if node is not None:
            self.store.delete(node)
        nc = self.store.get(NodeClaim, name.replace("node", "nc"))
        if nc is not None:
            self.store.delete(nc)
        self.bound.pop(name, None)

    def scheduler(self, ps, unavailable=True):
        state_nodes = [sn for sn in self.cluster.state_nodes()
                       if not sn.deleting()]
        return TensorScheduler(
            [self.pool], {"default": self.catalog},
            state_nodes=state_nodes,
            cluster=StateClusterView(self.store, self.cluster),
            unavailable=self.registry if unavailable else None,
            problem_state=ps)

    def solve_pair(self, batch):
        """(delta results, delta scheduler): decisions asserted identical
        to a ProblemState-free cold solve of the same inputs."""
        ts = self.scheduler(self.ps)
        r = ts.solve(batch)
        cold = self.scheduler(None)
        r_cold = cold.solve(batch)
        assert digest(r) == digest(r_cold), \
            "delta solve diverged from cold solve"
        assert ts.fallback_reason == cold.fallback_reason
        return r, ts


def deployment(name, n, cpu="250m", spread_key=None, host_spread=False):
    labels = {"app": name}
    sel = LabelSelector(match_labels=dict(labels))
    spread = []
    if spread_key == "zone":
        spread = [TopologySpreadConstraint(
            topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=1,
            label_selector=sel)]
    elif host_spread:
        spread = [TopologySpreadConstraint(
            topology_key=api_labels.LABEL_HOSTNAME, max_skew=1,
            label_selector=sel)]
    return [Pod(metadata=ObjectMeta(name=f"{name}-{i}", namespace="default",
                                    labels=dict(labels)),
                spec=PodSpec(topology_spread_constraints=list(spread)),
                container_requests=[res.parse_list(
                    {"cpu": cpu, "memory": "128Mi"})])
            for i in range(n)]


# -- signatures --------------------------------------------------------------


def test_group_signature_stable_across_passes():
    """Equal-content deployments stamped in different passes (fresh pod
    objects) share a signature; a changed request does not."""
    g1, _, _ = partition_pods(deployment("sig", 3))
    g2, _, _ = partition_pods(deployment("sig", 5))
    g3, _, _ = partition_pods(deployment("sig", 3, cpu="300m"))
    assert group_signature(g1[0]) == group_signature(g2[0])
    assert group_signature(g1[0]) != group_signature(g3[0])


# -- node rows ---------------------------------------------------------------


class TestNodeRows:
    def test_dirty_rows_only_reencode(self):
        env = ChurnEnv(n_nodes=4, pods_per_node=1)
        env.solve_pair(deployment("a", 4))
        n0 = env.ps.last["node_rows_reencoded"]
        assert n0 == 4  # first pass encodes everything
        env.solve_pair(deployment("a", 5))
        assert env.ps.last["node_rows_reencoded"] == 0
        env.complete_bound("churn-node-001")  # dirties exactly one node
        env.solve_pair(deployment("a", 5))
        assert env.ps.last["node_rows_reencoded"] == 1

    def test_node_add_and_remove_invalidate_their_rows_only(self):
        env = ChurnEnv(n_nodes=3, pods_per_node=1)
        env.solve_pair(deployment("a", 3))
        env.add_node(7, pods_per_node=0)
        env.solve_pair(deployment("a", 3))
        assert env.ps.last["node_rows_reencoded"] == 1  # the new node only
        env.delete_node("churn-node-000")
        env.solve_pair(deployment("a", 3))
        assert env.ps.last["node_rows_reencoded"] == 0  # removal: no encode

    def test_daemonset_change_reencodes_all_rows(self):
        env = ChurnEnv(n_nodes=3, pods_per_node=1)
        ds = make_pod(name="ds-0", cpu="50m")
        ds.metadata.owner_refs = []
        env.solve_pair(deployment("a", 3))
        # daemonset overhead rides in the node avail vectors: a changed
        # daemonset set clears the whole row cache (invalidation row)
        ts = env.scheduler(env.ps)
        ts.daemonset_pods = [ds]
        ts.solve(deployment("a", 3))
        assert env.ps.last["node_rows_reencoded"] == 3


# -- topology memo -----------------------------------------------------------


class TestTopologyMemo:
    def test_counts_memoized_until_revision_bump(self):
        env = ChurnEnv(n_nodes=3, pods_per_node=1)
        batch = deployment("t", 4, spread_key="zone")
        env.solve_pair(batch)
        assert env.ps.last["topo_groups_counted"] == 1
        env.solve_pair(deployment("t", 6, spread_key="zone"))
        assert env.ps.last["topo_groups_counted"] == 0  # memo hit
        # binding a selector-matching pod bumps topo_revision -> recount,
        # and the recount must see the new occupancy (parity pins it)
        env.bind_pod("churn-node-000", labels={"app": "t"})
        env.solve_pair(deployment("t", 6, spread_key="zone"))
        assert env.ps.last["topo_groups_counted"] == 1


# -- warm-started packing ----------------------------------------------------


class TestWarmPack:
    def test_identical_batch_full_replay(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        batch = deployment("w", 4) + deployment("x", 3, cpu="500m")
        env.solve_pair(batch)
        # same shape, fresh pod objects: the whole pack replays from seed
        batch2 = deployment("w", 4) + deployment("x", 3, cpu="500m")
        _, ts = env.solve_pair(batch2)
        assert ts.encode_kind == "delta"
        assert env.ps.last["warm_matched"] == 2
        assert env.ps.last["warm_restored"] == 2

    def test_dirty_group_cuts_prefix(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        # FFD order: big (500m) first, small (100m) last
        env.solve_pair(deployment("big", 3, cpu="500m")
                       + deployment("small", 3, cpu="100m"))
        _, ts = env.solve_pair(deployment("big", 3, cpu="500m")
                               + deployment("small", 5, cpu="100m"))
        assert env.ps.last["warm_matched"] == 1  # big unchanged
        assert env.ps.last["warm_restored"] == 1

    def test_error_groups_replay_onto_fresh_pods(self):
        """An unschedulable backlog group's errors re-bind to the NEW pod
        objects on replay (uids change across passes; counts don't)."""
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        backlog = deployment("impossible", 3, cpu="900")
        r1, _ = env.solve_pair(backlog + deployment("ok", 2))
        assert len(r1.pod_errors) == 3
        backlog2 = deployment("impossible", 3, cpu="900")
        r2, _ = env.solve_pair(backlog2 + deployment("ok", 2))
        assert set(r2.pod_errors) == {p.uid for p in backlog2}
        assert env.ps.last["warm_restored"] >= 1

    def test_node_churn_disables_warm_pack_for_the_pass(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=2)
        batch = deployment("w", 4)
        env.solve_pair(batch)
        env.complete_bound("churn-node-000")
        _, ts = env.solve_pair(deployment("w", 4))
        # exist state changed: global token mismatch, no restore — but the
        # pass still encodes delta and re-seeds for the next one
        assert env.ps.last["warm_restored"] == 0
        assert ts.encode_kind == "delta"
        _, ts = env.solve_pair(deployment("w", 4))
        assert env.ps.last["warm_restored"] > 0


# -- invalidation matrix: directed vectors -----------------------------------


class TestInvalidationMatrix:
    def test_vocab_overflow_falls_back_to_cold_encode(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        _, ts = env.solve_pair(deployment("v", 3))
        _, ts = env.solve_pair(deployment("v", 3))
        assert ts.encode_kind == "delta"
        # a pod with a never-seen label value: inexpressible as a delta
        # (complement masks enumerate the value universe) -> cold
        novel = [make_pod(name="novel-1", labels={"app": "v"},
                          node_selector={"brand-new-key": "brand-new-val"})]
        _, ts = env.solve_pair(deployment("v", 3) + novel)
        assert ts.encode_kind == "cold"
        # and the state re-warms on the next unchanged pass
        _, ts = env.solve_pair(deployment("v", 3) + [
            make_pod(name="novel-2", labels={"app": "v"},
                     node_selector={"brand-new-key": "brand-new-val"})])
        assert ts.encode_kind == "delta"

    def test_catalog_change_falls_back_to_cold_encode(self):
        its = construct_instance_types()
        env = ChurnEnv(n_nodes=2, pods_per_node=1, catalog=its[:40])
        _, ts = env.solve_pair(deployment("c", 3))
        env.catalog = its[:44]  # provider refreshed the catalog
        _, ts = env.solve_pair(deployment("c", 3))
        assert ts.encode_kind == "cold"

    def test_drought_mark_and_expiry_stay_bit_identical(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        batch = deployment("d", 4)
        env.solve_pair(batch)
        env.registry.mark(zone="test-zone-a")
        r, ts = env.solve_pair(deployment("d", 4))
        assert ts.encode_kind == "delta"  # mask rebuild, not a re-encode
        for nc in r.new_nodeclaims:
            zr = nc.requirements.raw(api_labels.LABEL_TOPOLOGY_ZONE)
            if zr is not None and not zr.complement:
                assert "test-zone-a" not in zr.values
        # expiry bumps the registry version; the delta path must follow
        env.clock.step(10_000)
        env.registry.expire()
        env.solve_pair(deployment("d", 4))

    def test_minvalues_disables_warm_pack_not_delta_encode(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=1)

        class MinValuesReq:
            key = api_labels.LABEL_INSTANCE_TYPE
            values = ()
            min_values = 5

            def operator(self):
                return "Exists"
        env.pool = make_nodepool(name="default", requirements=[
            type("R", (), {"key": api_labels.LABEL_INSTANCE_TYPE,
                           "operator": "Exists", "values": (),
                           "min_values": 5})()])
        env.solve_pair(deployment("m", 3))
        _, ts = env.solve_pair(deployment("m", 3))
        assert ts.encode_kind == "delta"
        assert env.ps.last["warm"] == "disabled:inexpressible"
        assert env.ps.last["warm_restored"] == 0

    def test_conflicting_host_ports_disable_warm_pack(self):
        from karpenter_tpu.api.objects import HostPort
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        ported = [make_pod(name=f"hp-{i}", labels={"app": "hp"},
                           host_ports=[HostPort(port=8080)])
                  for i in range(3)]
        env.solve_pair(ported)
        _, ts = env.solve_pair([
            make_pod(name=f"hp2-{i}", labels={"app": "hp"},
                     host_ports=[HostPort(port=8080)]) for i in range(3)])
        assert env.ps.last["warm_restored"] == 0
        assert env.ps.last["warm"] == "disabled:inexpressible"

    def test_coupled_topology_demotes_to_host_on_both_paths(self):
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        # group B's spread selector matches group A's labels: cross-group
        # coupling demotes both to the host oracle — on the delta path
        # exactly as on a cold one (partition runs per pass)
        a = deployment("couple-a", 2)
        sel = LabelSelector(match_labels={"app": "couple-a"})
        b = [Pod(metadata=ObjectMeta(name=f"couple-b-{i}",
                                     namespace="default",
                                     labels={"app": "couple-b"}),
                 spec=PodSpec(topology_spread_constraints=[
                     TopologySpreadConstraint(
                         topology_key=api_labels.LABEL_TOPOLOGY_ZONE,
                         max_skew=1, label_selector=sel)]),
                 container_requests=[res.parse_list(
                     {"cpu": "100m", "memory": "64Mi"})])
             for i in range(2)]
        _, ts = env.solve_pair(a + b)
        assert ts.fallback_reason  # host path, same on both sides

    def test_registry_version_in_warm_token(self):
        """A drought mark between identical batches must invalidate the
        warm seed (offering masks changed) — pinned by parity, and by the
        restore count dropping to zero on the marked pass."""
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        env.solve_pair(deployment("rv", 4))
        env.solve_pair(deployment("rv", 4))
        assert env.ps.last["warm_restored"] > 0
        env.registry.mark(instance_type=env.catalog[0].name)
        env.solve_pair(deployment("rv", 4))
        assert env.ps.last["warm_restored"] == 0


# -- review-hardening regressions --------------------------------------------


class TestReviewRegressions:
    def test_topo_memo_overflow_recomputes_all_groups(self, monkeypatch):
        """Overflow wipes the memo; the pass must recompute EVERY group,
        not only the misses — a dangling hit sig was a KeyError that the
        solve's blanket except turned into circuit-breaker failures."""
        from karpenter_tpu.provisioning import problem_state as ps_mod
        monkeypatch.setattr(ps_mod, "MAX_SIG_ENTRIES", 3)
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        env.solve_pair(deployment("ov-a", 2) + deployment("ov-b", 2))
        # 2 cached + 2 new = 4 > 3: overflow path with live hit entries
        _, ts = env.solve_pair(deployment("ov-a", 2) + deployment("ov-b", 2)
                               + deployment("ov-c", 2)
                               + deployment("ov-d", 2))
        assert ts.fallback_reason == ""  # no KeyError -> no host fallback
        assert env.ps.last["topo_groups_counted"] == 4

    def test_recreated_node_same_name_never_reuses_stale_row(self):
        """A node deleted and re-created under the same name replays the
        same revision sequence; the identity component of the cache key
        must still force a fresh encode (here: the replacement sits in a
        DIFFERENT zone, so a stale row would mis-zone placements)."""
        env = ChurnEnv(n_nodes=3, pods_per_node=0)
        batch = deployment("rz", 6, spread_key="zone")
        env.solve_pair(batch)
        sn0 = {sn.name(): (sn.identity, sn.revision)
               for sn in env.cluster.state_nodes()}
        env.delete_node("churn-node-001")
        # re-create the same name through the same event sequence but in
        # another zone (i=4 -> zone-b; original i=1 -> zone-b... use i=3
        # -> zone-a to guarantee the zone actually changes)
        name = env.add_node(1 + 3 * 1000, pods_per_node=0)
        node = env.store.get(Node, name)
        renamed = Node(
            metadata=ObjectMeta(name="churn-node-001", namespace="",
                                labels=dict(node.metadata.labels)),
            spec=NodeSpec(provider_id=node.spec.provider_id),
            status=NodeStatus(capacity=dict(node.status.capacity),
                              allocatable=dict(node.status.allocatable)))
        env.store.delete(node)
        env.store.create(renamed)
        _, ts = env.solve_pair(deployment("rz", 6, spread_key="zone"))
        sn1 = {sn.name(): (sn.identity, sn.revision)
               for sn in env.cluster.state_nodes()}
        # same name present both times, but a different identity
        assert "churn-node-001" in sn0 and "churn-node-001" in sn1
        assert sn0["churn-node-001"][0] != sn1["churn-node-001"][0]

    def test_daemonset_change_on_empty_cluster_invalidates_warm_seed(self):
        """Zero state nodes: exist_token is None, so the daemonset token
        must ride the warm global token on its own — daemon overhead
        shapes every fresh-node fill even with no existing nodes."""
        its = construct_instance_types()
        pool = make_nodepool(name="default")
        ps = ProblemState()
        batch = deployment("ds", 6)

        def solve(ds_pods):
            ts = TensorScheduler([pool], {"default": its},
                                 daemonset_pods=ds_pods, problem_state=ps)
            r = ts.solve(deployment("ds", 6))
            cold = TensorScheduler([pool], {"default": its},
                                   daemonset_pods=ds_pods)
            assert digest(r) == digest(cold.solve(deployment("ds", 6)))
            return ts

        solve([])
        solve([])
        assert ps.last["warm_restored"] > 0
        ds = make_pod(name="ds-pod", cpu="2")
        solve([ds])
        assert ps.last["warm_restored"] == 0  # seed invalidated


    def test_seed_checkpoints_stay_bounded_across_passes(self):
        """Carried + fresh checkpoints must not accumulate: a long-lived
        provisioner restoring the full prefix every pass would otherwise
        grow the seed (full cohort-array copies) without bound."""
        from karpenter_tpu.ops.binpack import MAX_SEED_CHECKPOINTS
        env = ChurnEnv(n_nodes=2, pods_per_node=1)
        for w in range(30):
            # stable core + one fresh small deployment appended per pass:
            # the previous prefix always matches fully, so every old
            # checkpoint is carried and new ones are recorded
            batch = deployment("core", 4, cpu="800m") \
                + [p for d in range(w + 1)
                   for p in deployment(f"tail-{d}", 1, cpu="50m")]
            env.solve_pair(batch)
            assert len(env.ps.seed.checkpoints) <= MAX_SEED_CHECKPOINTS
        assert env.ps.last["warm_restored"] > 0  # still warm at pass 30


# -- seeded churn fuzzer -----------------------------------------------------


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_churn_fuzzer_delta_matches_cold_every_step(seed):
    """Interleaved arrivals/completions/node churn/drought marks over a
    persistent ProblemState: the delta solve must match a cold solve of
    the same state BIT-IDENTICALLY at every step."""
    import random
    rng = random.Random(seed)
    env = ChurnEnv(n_nodes=5, pods_per_node=2,
                   catalog=construct_instance_types())
    shapes = [dict(cpu="100m"), dict(cpu="250m", spread_key="zone"),
              dict(cpu="500m", host_spread=True), dict(cpu="750m")]
    pending = {}
    step_seq = 0
    for step in range(24):
        op = rng.choice(["arrive", "arrive", "arrive", "complete",
                         "node-churn", "drought", "expire", "node-add"])
        if op == "arrive":
            d = rng.randrange(6)
            step_seq += 1
            kw = dict(shapes[d % len(shapes)])
            pending.setdefault(d, []).extend(
                deployment(f"fz-{d}-{step_seq}", rng.randrange(1, 5), **kw))
        elif op == "complete" and pending:
            d = rng.choice(list(pending))
            drop = rng.randrange(0, len(pending[d]) + 1)
            pending[d] = pending[d][drop:]
            if not pending[d]:
                del pending[d]
        elif op == "node-churn":
            env.complete_bound(
                f"churn-node-{rng.randrange(5):03d}")
        elif op == "drought":
            it = rng.choice(env.catalog)
            env.registry.mark(instance_type=it.name,
                              zone=rng.choice(["test-zone-a",
                                               "test-zone-b"]))
        elif op == "expire":
            env.clock.step(rng.choice([30, 400, 2000]))
            env.registry.expire()
        elif op == "node-add":
            env.add_node(10 + step, pods_per_node=1)
        batch = [p for pods in pending.values() for p in pods]
        if not batch:
            continue
        env.solve_pair(batch)  # asserts delta == cold

    st = env.ps.stats
    assert st["delta_encodes"] > 0, st  # the stream actually rode deltas


# -- sharded-state churn fuzzer (ISSUE 18) -----------------------------------


class MeshChurnEnv(ChurnEnv):
    """ChurnEnv whose schedulers (delta AND cold control) run on the
    8-device (pods_groups x catalog) mesh: the persistent ProblemState is
    sharded along the pods_groups axis, so the fuzzer's invalidation matrix
    runs against per-shard exist tokens + the per-shard upload cache."""

    def __init__(self, *args, **kwargs):
        from karpenter_tpu.parallel.mesh import make_solver_mesh
        self.mesh = make_solver_mesh(8)
        super().__init__(*args, **kwargs)

    def scheduler(self, ps, unavailable=True):
        state_nodes = [sn for sn in self.cluster.state_nodes()
                       if not sn.deleting()]
        return TensorScheduler(
            [self.pool], {"default": self.catalog},
            state_nodes=state_nodes,
            cluster=StateClusterView(self.store, self.cluster),
            unavailable=self.registry if unavailable else None,
            mesh=self.mesh, problem_state=ps)


@pytest.mark.parametrize("seed", [7, 31, 61])
def test_sharded_churn_fuzzer_delta_matches_cold_mesh_every_step(seed):
    """The DEVIATIONS 19 invalidation matrix against the SHARDED state:
    node churn (one shard's rows dirty), group moves (an app's shape
    changes, shifting its FFD slot), vocab growth (a new node's hostname
    enters the requirement vocabulary -> cold everywhere), drought-pattern
    bumps and expiries — every step's delta solve on the mesh must match a
    cold mesh solve of the same state BIT-IDENTICALLY."""
    import random

    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest 8-device virtual CPU platform")
    rng = random.Random(seed)
    env = MeshChurnEnv(n_nodes=6, pods_per_node=2,
                       catalog=construct_instance_types())
    shapes = [dict(cpu="100m"), dict(cpu="250m", spread_key="zone"),
              dict(cpu="500m", host_spread=True), dict(cpu="750m")]
    pending = {}
    step_seq = 0
    saw_shard_dirty = False
    for step in range(24):
        op = rng.choice(["arrive", "arrive", "complete", "node-churn",
                         "group-move", "drought", "expire", "vocab-grow"])
        if op == "arrive":
            d = rng.randrange(6)
            step_seq += 1
            kw = dict(shapes[d % len(shapes)])
            pending.setdefault(d, []).extend(
                deployment(f"fzm-{d}-{step_seq}", rng.randrange(1, 5), **kw))
        elif op == "complete" and pending:
            d = rng.choice(list(pending))
            drop = rng.randrange(0, len(pending[d]) + 1)
            pending[d] = pending[d][drop:]
            if not pending[d]:
                del pending[d]
        elif op == "node-churn":
            env.complete_bound(f"churn-node-{rng.randrange(6):03d}")
        elif op == "group-move" and pending:
            # the group keeps its app identity but changes shape: a new
            # signature lands in a different FFD slot
            d = rng.choice(list(pending))
            step_seq += 1
            pending[d] = deployment(f"fzm-{d}-{step_seq}",
                                    max(1, len(pending[d])),
                                    cpu=f"{rng.choice([150, 350, 650])}m")
        elif op == "drought":
            it = rng.choice(env.catalog)
            env.registry.mark(instance_type=it.name,
                              zone=rng.choice(["test-zone-a",
                                               "test-zone-b"]))
        elif op == "expire":
            env.clock.step(rng.choice([30, 400, 2000]))
            env.registry.expire()
        elif op == "vocab-grow":
            # a brand-new hostname enters the requirement vocabulary:
            # every shard's rows go cold at once
            env.add_node(10 + step, pods_per_node=1)
        batch = [p for pods in pending.values() for p in pods]
        if not batch:
            continue
        env.solve_pair(batch)  # asserts delta == cold (both on the mesh)
        sd = env.ps.last.get("shard_dirty")
        if sd and sum(sd.values()) > 0:
            saw_shard_dirty = True

    st = env.ps.stats
    assert st["delta_encodes"] > 0, st  # the stream actually rode deltas
    assert saw_shard_dirty, \
        "no step ever dirtied a shard's rows — the sharded state never engaged"
