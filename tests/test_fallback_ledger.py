"""Fallback cost ledger (ISSUE 12 tentpole c): every host-oracle escape
classified by shape class, with pod counts and host-vs-tensor wall cost —
directed vectors per shape class, the process-wide LEDGER aggregation,
the karpenter_fallback_* metric families, and /debug/fallbacks."""

import json
import urllib.request

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (Affinity, HostPort, LabelSelector,
                                       ObjectMeta, Pod, PodAffinity,
                                       PodAffinityTerm, PodSpec, PVCRef,
                                       TopologySpreadConstraint)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.obs import fallbacks as fb
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.utils import resources as res

from factories import make_nodepool, make_pods

REQ = res.parse_list({"cpu": "100m", "memory": "128Mi"})


def _pod(name, labels=None, **spec_kw):
    return Pod(metadata=ObjectMeta(name=name, namespace="default",
                                   labels=dict(labels or {})),
               spec=PodSpec(**spec_kw), container_requests=[REQ])


def _scheduler(**kw):
    return TensorScheduler([make_nodepool(name="default")],
                           {"default": construct_instance_types()[:12]},
                           **kw)


class TestClassifyReason:
    """One directed vector per shape class, over the EXACT reason strings
    the partitioner / scheduler / LOO engine emit today — a reworded
    reason that falls out of its class lands in 'other', which this test
    catches."""

    CASES = {
        # grouping._demotion_reason
        "host ports require per-pod conflict tracking": "ports",
        "persistent volume claims shared across pods require host-side "
        "limit tracking": "volumes",                      # NOT limits
        "unsupported topology constraint shape": "topo",  # NOT ports
        "host ports with hostname pod-affinity need per-pod host "
        "tracking": "ports",                              # NOT topo
        "node-affinity preferences with zonal topology need the host "
        "relaxation ladder": "topo",
        # grouping._finish_partition coupling
        "topology selector couples to host-path pods": "topo",
        "topology selector couples multiple pod groups": "multi_group",
        # tensor_scheduler fallbacks
        "daemonset host ports need per-pod conflict tracking": "ports",
        "minValues on example.com/foo needs host-side enforcement":
            "minvalues",
        "pack errors under nodepool limit pressure": "limits",
        "unscheduled pods with relaxable preferences": "topo",
        "circuit_open": "circuit_open",
        "tensor solve failed: RuntimeError('device gone')": "device_error",
        # a device OOM's exception text mentions 'limit' — still a device
        # error, never the nodepool-limits shape class
        "tensor solve failed: XlaRuntimeError('RESOURCE_EXHAUSTED: "
        "memory limit exceeded')": "device_error",
        # disruption LOO globals
        "base pods re-pack the shared pending set": "base_pods",
        # unknown strings stay visible, not silently dropped
        "some future reason": "other",
        "": "other",
    }

    def test_every_reason_classifies(self):
        for reason, want in self.CASES.items():
            assert fb.classify_reason(reason) == want, reason

    def test_breakdown_folds_counts(self):
        classes = fb.classify_breakdown([
            ("host ports require per-pod conflict tracking", 3),
            ("unsupported topology constraint shape", 2),
            ("topology selector couples to host-path pods", 4),
        ])
        assert classes == {"ports": 3, "topo": 6}


class TestSolveAttribution:
    """Directed integration vectors: a mixed batch's per-class pod counts
    are EXACT on TensorScheduler.fallback_attribution."""

    def _mixed(self):
        pods = make_pods(6, cpu="100m")
        # ports: conflicting host port + self-selecting hostname affinity
        plab = {"app": "t-ports"}
        sel = LabelSelector(match_labels=dict(plab))
        aff = Affinity(pod_affinity=PodAffinity(required=[
            PodAffinityTerm(topology_key=api_labels.LABEL_HOSTNAME,
                            label_selector=sel)]))
        pods += [_pod(f"t-ports-{i}", plab,
                      host_ports=[HostPort(port=2222)], affinity=aff)
                 for i in range(2)]
        # volumes: shared non-ephemeral PVC
        pods += [_pod(f"t-vol-{i}", {"app": "t-vol"},
                      volumes=[PVCRef(claim_name="d", ephemeral=False)])
                 for i in range(3)]
        # topo: unsupported topology key
        rack = [TopologySpreadConstraint(
            topology_key="example.com/rack", max_skew=1,
            label_selector=LabelSelector(match_labels={"app": "t-topo"}))]
        pods += [_pod(f"t-topo-{i}", {"app": "t-topo"},
                      topology_spread_constraints=list(rack))
                 for i in range(4)]
        # multi_group: A's selector counts B's pods; B rides along as topo
        selb = LabelSelector(match_labels={"app": "t-mg-b"})
        mg = [TopologySpreadConstraint(
            topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=1,
            label_selector=selb)]
        pods += [_pod(f"t-mg-a-{i}", {"app": "t-mg-a"},
                      topology_spread_constraints=list(mg))
                 for i in range(2)]
        pods += [_pod(f"t-mg-b-{i}", {"app": "t-mg-b"}) for i in range(2)]
        expected = {"ports": 2, "volumes": 3, "topo": 6, "multi_group": 2}
        return pods, expected

    def test_mixed_batch_classes_exact(self):
        pods, expected = self._mixed()
        ts = _scheduler()
        ts.solve(pods)
        attr = ts.fallback_attribution
        assert attr["classes"] == expected
        assert attr["host_pods"] == sum(expected.values())
        assert attr["tensor_pods"] == len(pods) - sum(expected.values())
        assert attr["host_seconds"] > 0.0
        assert attr["tensor_seconds"] > 0.0

    def test_clean_tensor_solve_has_no_classes(self):
        ts = _scheduler()
        ts.solve(make_pods(5, cpu="100m"))
        attr = ts.fallback_attribution
        assert attr["classes"] == {}
        assert attr["host_pods"] == 0
        assert attr["host_seconds"] == 0.0

    def test_circuit_open_charges_whole_batch(self):
        class _Open:
            def allow(self):
                return False

            def record_failure(self):
                pass

            def record_success(self):
                pass

        ts = _scheduler(circuit=_Open())
        pods = make_pods(7, cpu="100m")
        ts.solve(pods)
        assert ts.fallback_reason == "circuit_open"
        attr = ts.fallback_attribution
        assert attr["classes"] == {"circuit_open": 7}
        assert attr["tensor_pods"] == 0 and attr["host_pods"] == 7

    def test_minvalues_fallback_charges_batch(self):
        class _MinValuesReq:
            def __init__(self):
                self.key = "example.com/custom"
                self.operator = "Exists"
                self.values = ()
                self.min_values = 2

        np_ = make_nodepool(name="default",
                            requirements=[_MinValuesReq()])
        ts = TensorScheduler([np_],
                             {"default": construct_instance_types()[:12]})
        pods = make_pods(4, cpu="100m")
        ts.solve(pods)
        assert "minValues" in ts.fallback_reason
        assert ts.fallback_attribution["classes"] == {"minvalues": 4}


class TestLedger:
    def test_record_and_snapshot_shapes(self):
        led = fb.FallbackLedger()
        led.record_solve({"ports": 3, "topo": 1}, tensor_pods=96,
                         host_pods=4, tensor_seconds=0.4, host_seconds=0.2,
                         trace_id="t000042", encode_kind="delta")
        led.record_solve({}, tensor_pods=100, host_pods=0,
                         tensor_seconds=0.3, host_seconds=0.0)
        snap = led.snapshot()
        assert snap["solves"] == 2
        assert snap["tensor_pods"] == 196 and snap["host_pods"] == 4
        assert snap["fallback_fraction"] == round(4 / 200, 6)
        ports = snap["classes"]["provisioning/ports"]
        assert ports["pods"] == 3 and ports["solves"] == 1
        # host seconds split pro-rata by pod count: 3/4 of 0.2s to ports
        assert ports["host_seconds"] == pytest.approx(0.15)
        assert snap["classes"]["provisioning/topo"]["host_seconds"] == \
            pytest.approx(0.05)
        assert snap["recent"][-1]["trace_id"] == "t000042"

    def test_disruption_records_do_not_move_headline_totals(self):
        led = fb.FallbackLedger()
        led.record_disruption({"base_pods": 10, "volumes": 2})
        led.record_solve({"topo": 1}, 9, 1, 0.1, 0.05,
                         subsystem="disruption")
        snap = led.snapshot()
        assert snap["solves"] == 0 and snap["host_pods"] == 0
        assert snap["classes"]["disruption/base_pods"]["pods"] == 10
        assert snap["classes"]["disruption/topo"]["pods"] == 1
        assert snap["recent"] == []

    def test_process_ledger_aggregates_solves(self):
        fb.LEDGER.reset()
        ts = _scheduler()
        pods = make_pods(3, cpu="100m") + [
            _pod("lp-0", {"app": "lp"},
                 volumes=[PVCRef(claim_name="x", ephemeral=False)])]
        ts.solve(pods)
        snap = fb.LEDGER.snapshot()
        assert snap["solves"] == 1
        assert snap["classes"]["provisioning/volumes"]["pods"] == 1
        assert snap["recent"][0]["classes"] == {"volumes": 1}

    def test_metrics_families_move(self):
        from karpenter_tpu.metrics.registry import (FALLBACK_HOST_SECONDS,
                                                    FALLBACK_PODS,
                                                    FALLBACK_SOLVES)
        labels = {"shape": "volumes", "subsystem": "provisioning"}
        before = FALLBACK_PODS.value(labels)
        ts = _scheduler()
        ts.solve([_pod("mp-0", {"app": "mp"},
                       volumes=[PVCRef(claim_name="y", ephemeral=False)])])
        assert FALLBACK_PODS.value(labels) == before + 1
        assert FALLBACK_SOLVES.value(labels) >= 1
        assert FALLBACK_HOST_SECONDS.value(labels) > 0


class TestDebugEndpoint:
    def test_debug_fallbacks_serves_ledger(self):
        from karpenter_tpu.operator.server import ServingGroup
        fb.LEDGER.reset()
        ts = _scheduler()
        ts.solve([_pod("ep-0", {"app": "ep"},
                       volumes=[PVCRef(claim_name="z", ephemeral=False)])])
        group = ServingGroup(0, 0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{group.metrics_port}"
                    "/debug/fallbacks?n=5", timeout=10) as resp:
                doc = json.loads(resp.read().decode())
        finally:
            group.stop()
        assert doc["solves"] >= 1
        assert doc["classes"]["provisioning/volumes"]["pods"] >= 1
        assert doc["fallback_fraction"] > 0
        assert isinstance(doc["recent"], list) and doc["recent"]

    def test_debug_fallbacks_rejects_bad_n(self):
        from karpenter_tpu.operator.server import ServingGroup
        group = ServingGroup(0, 0).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{group.metrics_port}"
                "/debug/fallbacks?n=bogus")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400
        finally:
            group.stop()


class TestSubsystemFlag:
    def test_disruption_flag_honored_with_tracing_off(self):
        """A candidate-build probe (ledger_subsystem='disruption', the
        schedule_with(record=False)/DisruptionSnapshot flag) must not move
        the headline provisioning totals even when --trace-ring 0 disabled
        the root-span backstop."""
        from karpenter_tpu.obs.tracer import TRACER
        fb.LEDGER.reset()
        saved = TRACER.enabled
        try:
            TRACER.enabled = False
            ts = _scheduler()
            ts.ledger_subsystem = "disruption"
            ts.solve([_pod("sf-0", {"app": "sf"},
                           volumes=[PVCRef(claim_name="q",
                                           ephemeral=False)])])
        finally:
            TRACER.enabled = saved
        snap = fb.LEDGER.snapshot()
        assert snap["solves"] == 0 and snap["host_pods"] == 0
        assert snap["classes"]["disruption/volumes"]["pods"] == 1

    def test_simulation_probes_flagged_disruption(self):
        """Provisioner.schedule_with(record=False) — the disruption sim
        entry point — flags its scheduler; record=True (live) does not."""
        import inspect

        from karpenter_tpu.provisioning.provisioner import Provisioner
        src = inspect.getsource(Provisioner.schedule_with)
        assert 'ts.ledger_subsystem = "disruption"' in src

    def test_snapshot_recent_zero_returns_none(self):
        led = fb.FallbackLedger()
        led.record_solve({"topo": 1}, 1, 1, 0.1, 0.1)
        assert led.snapshot(recent=0)["recent"] == []
        assert len(led.snapshot(recent=5)["recent"]) == 1
