"""One state plane (ISSUE 19 tentpole): the shared EncodePlane.

The contract under test: provisioning, disruption, and sidecar-session
solvers consuming ONE refcounted EncodePlane through subscriber handles
make decisions bit-identical to the pre-ISSUE-19 layout (three private
ProblemStates), while node/group rows encode once per revision bump and
are served shared to every other subscriber. Covers the combined-loop
fuzzer, the subscriber lifecycle (refcounts + gauge), the two-generation
node-row cache that absorbs the provisioning/disruption node-subset
alternation, and the /debug/stateplane surface.
"""

import random

import pytest

from karpenter_tpu.metrics.registry import (STATE_PLANE_ROWS,
                                            STATE_PLANE_SUBSCRIBERS)
from karpenter_tpu.provisioning.problem_state import ProblemState
from karpenter_tpu.provisioning.provisioner import StateClusterView
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.state.plane import (EncodePlane, live_planes,
                                       refresh_subscriber_gauge)

from test_problem_state import ChurnEnv, deployment, digest

pytestmark = pytest.mark.churn


def _solve(env, ps, batch, state_nodes=None):
    """One pass through a fresh scheduler bound to `ps` (the provisioner
    constructs a scheduler per pass the same way)."""
    if state_nodes is None:
        state_nodes = [sn for sn in env.cluster.state_nodes()
                       if not sn.deleting()]
    ts = TensorScheduler(
        [env.pool], {"default": env.catalog}, state_nodes=state_nodes,
        cluster=StateClusterView(env.store, env.cluster),
        unavailable=env.registry, problem_state=ps)
    return ts.solve(batch)


# -- combined-loop fuzzer ----------------------------------------------------


class TestCombinedLoopFuzzer:
    """Interleave provisioning, disruption, and sidecar-session passes
    over ONE plane while the cluster churns; every pass is shadowed by
    the same pass over a private ProblemState (the pre-ISSUE-19 layout)
    and the decisions must be bit-identical."""

    @pytest.mark.parametrize("seed", [0, 7])
    def test_three_subscribers_one_plane_bit_identical(self, seed):
        rng = random.Random(seed)
        env = ChurnEnv(n_nodes=6, pods_per_node=2)
        plane = EncodePlane(name=f"fuzz-{seed}")
        shared = {
            "provisioning": plane.subscribe("provisioning"),
            "disruption": plane.subscribe("disruption"),
            "sidecar": plane.subscribe("sidecar"),
        }
        private = {name: ProblemState() for name in shared}
        assert plane.subscribers == {"provisioning": 1, "disruption": 1,
                                     "sidecar": 1}

        def batch(step):
            shapes = [deployment(f"std-{k}", rng.randint(1, 3))
                      for k in rng.sample(range(4), 2)]
            if step % 3 == 0:
                # a genuinely new deployment shape: unique request combo
                shapes.append(deployment(f"roll-{step}", 2,
                                         cpu=f"{201 + step}m"))
            return [p for shape in shapes for p in shape]

        next_node = 100
        for step in range(12):
            op = rng.choice(["arrive", "complete", "node-add",
                             "node-remove", "arrive"])
            if op == "complete":
                names = [n for n, pods in env.bound.items() if pods]
                if names:
                    env.complete_bound(rng.choice(names))
            elif op == "node-add":
                env.add_node(next_node, pods_per_node=1)
                next_node += 1
            elif op == "node-remove":
                names = sorted(env.bound)
                if len(names) > 3:
                    env.delete_node(rng.choice(names))
            pods = batch(step)
            all_nodes = [sn for sn in env.cluster.state_nodes()
                         if not sn.deleting()]
            # the disruption view excludes one candidate node (the
            # non-deleting-subset alternation the two-generation cache
            # exists for); the sidecar session sees the full set
            victim = rng.randrange(len(all_nodes))
            views = {
                "provisioning": all_nodes,
                "disruption": all_nodes[:victim] + all_nodes[victim + 1:],
                "sidecar": all_nodes,
            }
            for name in ("provisioning", "disruption", "sidecar"):
                r_sh = _solve(env, shared[name], pods, views[name])
                r_pr = _solve(env, private[name], pods, views[name])
                assert digest(r_sh) == digest(r_pr), (
                    f"seed {seed} step {step}: {name} pass over the "
                    "shared plane diverged from its private state")

        # the reuse ledger: rows landed once on the plane and were served
        # shared to the other subscribers, while each private state paid
        # its own encodes
        assert plane.stats["node_rows_shared"] > 0
        assert plane.stats["group_rows_shared"] > 0
        assert plane.stats["stack_hits"] > 0
        private_encoded = sum(ps.plane.stats["node_rows_encoded"]
                              for ps in private.values())
        assert plane.stats["node_rows_encoded"] < private_encoded, (
            "the shared plane re-encoded as much as three private states "
            "- rows are not being shared across subscribers")
        for name in shared:
            assert STATE_PLANE_ROWS.value(
                {"subscriber": name, "outcome": "shared"}) > 0


# -- subscriber lifecycle ----------------------------------------------------


class TestSubscriberLifecycle:
    def test_refcounts_and_gauge(self):
        plane = EncodePlane(name="lifecycle")
        h1 = plane.subscribe("provisioning")
        h2 = plane.subscribe("provisioning")
        h3 = plane.subscribe("disruption")
        assert plane.subscribers == {"provisioning": 2, "disruption": 1}
        assert STATE_PLANE_SUBSCRIBERS.value({"plane": "lifecycle"}) == 3.0
        h2.close()
        assert plane.subscribers == {"provisioning": 1, "disruption": 1}
        h1.close()
        h3.close()
        assert plane.subscribers == {}
        refresh_subscriber_gauge()
        assert STATE_PLANE_SUBSCRIBERS.value({"plane": "lifecycle"}) == 0.0

    def test_bare_problem_state_gets_private_plane(self):
        ps1 = ProblemState()
        ps2 = ProblemState()
        assert ps1.plane is not ps2.plane
        assert ps1.plane.subscribers == {"private": 1}
        assert ps1.plane.name.startswith("private:")

    def test_live_planes_registry(self):
        plane = EncodePlane(name="registry-probe")
        assert plane in live_planes()

    def test_topo_revision_bump(self):
        plane = EncodePlane(name="rev")
        assert plane.topo_revision == 0
        assert plane.bump_topo_revision() == 1
        assert plane.topo_revision == 1


# -- two-generation node rows ------------------------------------------------


class TestTwoGenerationRows:
    def test_full_subset_full_alternation_reencodes_nothing(self):
        """Provisioning (all nodes) and disruption (subset) alternate:
        the single-generation private cache would drop the complement on
        every subset pass; the plane's prev generation serves it back."""
        env = ChurnEnv(n_nodes=5, pods_per_node=1)
        plane = EncodePlane(name="twogen")
        prov = plane.subscribe("provisioning")
        dis = plane.subscribe("disruption")
        pods = deployment("a", 3)
        all_nodes = [sn for sn in env.cluster.state_nodes()
                     if not sn.deleting()]
        _solve(env, prov, pods, all_nodes)
        assert prov.last["node_rows_reencoded"] == 5
        _solve(env, dis, pods, all_nodes[:3])
        assert dis.last["node_rows_reencoded"] == 0
        # back to the full set: the two dropped-from-cur rows must come
        # from the prev generation, not a re-encode
        _solve(env, prov, pods, all_nodes)
        assert prov.last["node_rows_reencoded"] == 0
        assert plane.stats["node_rows_encoded"] == 5

    def test_stack_slots_keep_both_views_resident(self):
        """The alternating exist_tokens (full set vs subset) each keep a
        stack slot: the second full-set pass is a stack hit, not a
        rebuild."""
        env = ChurnEnv(n_nodes=4, pods_per_node=1)
        plane = EncodePlane(name="stacks")
        prov = plane.subscribe("provisioning")
        dis = plane.subscribe("disruption")
        pods = deployment("a", 2)
        all_nodes = [sn for sn in env.cluster.state_nodes()
                     if not sn.deleting()]
        _solve(env, prov, pods, all_nodes)
        _solve(env, dis, pods, all_nodes[:2])
        builds = plane.stats["stack_builds"]
        _solve(env, prov, pods, all_nodes)
        _solve(env, dis, pods, all_nodes[:2])
        assert plane.stats["stack_builds"] == builds
        assert plane.stats["stack_hits"] >= 2


# -- debug surface -----------------------------------------------------------


class TestDebugSurface:
    def test_debug_view_reports_caches_and_stats(self):
        env = ChurnEnv(n_nodes=3, pods_per_node=1)
        plane = EncodePlane(name="view")
        ps = plane.subscribe("provisioning")
        _solve(env, ps, deployment("a", 2))
        view = plane.debug_view()
        assert view["name"] == "view"
        assert view["subscribers"] == {"provisioning": 1}
        assert view["node_caches"] and \
            view["node_caches"][0]["rows_cur"] == 3
        assert view["stats"]["node_rows_encoded"] == 3

    def test_debug_stateplane_endpoint(self):
        import json
        from karpenter_tpu.operator.server import _debug_stateplane
        plane = EncodePlane(name="endpoint-probe")
        plane.subscribe("provisioning")
        code, ctype, body = _debug_stateplane({})
        assert code == 200 and ctype == "application/json"
        names = [p["name"] for p in json.loads(body)]
        assert "endpoint-probe" in names
        # the endpoint refreshes the gauge as a side effect
        assert STATE_PLANE_SUBSCRIBERS.value(
            {"plane": "endpoint-probe"}) == 1.0
