"""Regression guard for the flagship Solve() tentpole (round 6).

Round 5 proved the failure mode this file exists for: a correctness fix
moved the cohort scan into per-cohort host Python and the headline
benchmark regressed 0.499 s -> 1.197 s, discovered only at the NEXT
benchmark capture. This guard runs a scaled-down headline mix (the bench
deployment kinds at 2,000 pods x the kwok 144-type catalog) inside the
normal test suite and pins everything that regression would have tripped:

- the whole batch stays ON the vectorized tensor path (no host fallback,
  no partition) — a "fix" that silently demotes mix shapes to the host
  oracle fails here instead of a benchmark round later;
- a generous wall-clock budget per solve — pure-Python cohort scans at
  O(groups x cohorts) blow it even at this scale;
- byte-identical placements across independent solves of the same batch
  (the packer is deterministic; vectorization must keep it so);
- pod-error identity with the host oracle, and exact node-count parity
  per constraint kind everywhere it structurally holds (hostname pod
  affinity is a documented deviation: the tensor path keeps those groups
  alone, DEVIATIONS.md).
"""

import time

import pytest

from karpenter_tpu.api import labels as api_labels

import bench

N_PODS = 2000
N_DEPLOYS = 36
# generous: the solve runs ~0.2 s on CPU jax; a return of the round-5
# per-cohort Python scan costs >5x at 50k pods and measurably here too
BUDGET_SECONDS = 10.0


def _mix():
    saved = (bench.N_PODS, bench.N_DEPLOYS)
    bench.N_PODS, bench.N_DEPLOYS = N_PODS, N_DEPLOYS
    try:
        return bench._pods()
    finally:
        bench.N_PODS, bench.N_DEPLOYS = saved


def _claim_key(nc):
    return (nc.template.nodepool_name,
            tuple(sorted(nc.requirements.get(
                api_labels.LABEL_TOPOLOGY_ZONE).values)),
            tuple(it.name for it in nc.instance_type_options),
            len(nc.pods))


@pytest.fixture(scope="module")
def solved():
    pods = _mix()
    ts = bench._scheduler(0)
    ts.solve(pods)  # warm the jit cache: the budget times the solve, not XLA
    ts = bench._scheduler(0)
    t0 = time.perf_counter()
    results = ts.solve(pods)
    elapsed = time.perf_counter() - t0
    return pods, ts, results, elapsed


def test_headline_mix_stays_on_tensor_path(solved):
    pods, ts, results, _ = solved
    assert ts.fallback_reason == "", \
        f"headline mix fell off the tensor path: {ts.fallback_reason}"
    assert ts.partition == (len(pods), 0), ts.partition
    assert not results.pod_errors


def test_headline_mix_within_wall_clock_budget(solved):
    _, _, _, elapsed = solved
    assert elapsed < BUDGET_SECONDS, \
        (f"scaled headline solve took {elapsed:.2f}s (budget "
         f"{BUDGET_SECONDS}s) — the cohort scan likely fell off the "
         "vectorized path")


def test_solve_is_byte_identical_across_runs(solved):
    pods, _, results, _ = solved
    ts2 = bench._scheduler(0)
    r2 = ts2.solve(pods)
    assert ts2.fallback_reason == ""
    assert sorted(map(_claim_key, r2.new_nodeclaims)) == \
        sorted(map(_claim_key, results.new_nodeclaims))
    assert r2.pod_errors == results.pod_errors


def test_error_identity_vs_host_oracle(solved):
    pods, _, results, _ = solved
    host = bench._scheduler(0)
    rh = host._host_solve(pods, "forced oracle comparison")
    assert set(results.pod_errors) == set(rh.pod_errors)


# hostname pod affinity (kind 3) is excluded: the tensor path packs each
# affinity group on its own node while the oracle may co-locate distinct
# groups (documented deviation) — count parity doesn't apply there
class TestSingleNodeConsolidationBudget:
    """ISSUE 3 guard: the BENCH_MODE=single line at test scale. Runs the
    bench's own worst-case shape (every candidate but the last provably
    unconsolidatable) at 120 nodes and pins what the 5,000-node acceptance
    line demands: tensor-path residency (the bench function asserts zero
    needs_sim rows and exactly one probe internally), decision determinism
    across repeats (also asserted internally), a wall-clock budget a return
    of per-candidate serial sims would blow, and warm compile-cache reuse
    across successive passes (padded shape buckets must be stable)."""

    N_NODES = 120
    # the batched pass runs ~50 ms here; the serial shape costs ~3 s at
    # this scale (28 ms/sim x 120) and the budget catches that regression
    BUDGET_SECONDS = 10.0

    def test_single_bench_shape_within_budget(self, capsys):
        import json

        from karpenter_tpu.metrics.registry import (
            SOLVER_COMPILE_CACHE_HITS, SOLVER_COMPILE_CACHE_MISSES)

        saved = (bench.N_NODES, bench.REPEATS)
        bench.N_NODES, bench.REPEATS = self.N_NODES, 3
        try:
            bench.bench_single_consolidation()  # warm pass inside
            hits0 = SOLVER_COMPILE_CACHE_HITS.value()
            misses0 = SOLVER_COMPILE_CACHE_MISSES.value()
            t0 = time.perf_counter()
            bench.bench_single_consolidation()
            elapsed = time.perf_counter() - t0
        finally:
            bench.N_NODES, bench.REPEATS = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"single-node consolidation bench took {elapsed:.2f}s at "
            f"{self.N_NODES} nodes — the leave-one-out path likely fell "
            "back to per-candidate sims")
        # the second bench run re-encodes the same padded shape buckets:
        # the compiled-executable cache must serve it without recompiling
        assert SOLVER_COMPILE_CACHE_HITS.value() > hits0
        assert SOLVER_COMPILE_CACHE_MISSES.value() == misses0
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "seconds"
        assert line["value"] < self.BUDGET_SECONDS


class TestFlightRecorderBudget:
    """ISSUE 4 guard: the BENCH_MODE=replay budget at test scale. The 5%
    recorder-on bound is asserted at 50k in bench_replay; at 2,000 pods the
    absolute overhead budget is what a regression would trip — so this
    pins the capture mechanism directly: the hot-path capture must stay
    deferred (no payload/digest encode inside the solve) and cost
    milliseconds, and the deferred materialization must still replay to a
    byte-identical decision."""

    # same-process ratio (ISSUE 12 satellite): the capture rides inside
    # the solve, so its cost is bounded as a fraction of THIS process's
    # own measured solve — the old 20ms absolute budget flaked whenever
    # the 2-core box stalled the timer (an eager encode costs >100ms at
    # this scale, far past 5% of any plausible solve time + grace)
    CAPTURE_SOLVE_FRACTION = 0.05
    CAPTURE_GRACE_SECONDS = 0.010

    def test_capture_is_deferred_and_cheap(self, solved):
        from karpenter_tpu.flightrec import FlightRecorder
        pods, ts, results, solve_elapsed = solved
        rec = FlightRecorder(capacity=4)
        t0 = time.perf_counter()
        rec.capture_provisioning(ts, pods, results, 0.0)
        elapsed = time.perf_counter() - t0
        budget = (solve_elapsed * self.CAPTURE_SOLVE_FRACTION
                  + self.CAPTURE_GRACE_SECONDS)
        assert elapsed < budget, (
            f"hot-path capture took {elapsed * 1000:.1f}ms vs the "
            f"same-process solve's {solve_elapsed * 1000:.0f}ms at "
            f"{len(pods)} pods — the deferred encode likely went eager")
        r = rec.records()[-1]
        assert r._refs is not None and r._digest_refs is not None, \
            "capture materialized inside the solve path"
        assert r.decision is None

    def test_recorded_solve_replays_byte_identical(self, solved):
        from karpenter_tpu.flightrec import (FlightRecorder, loads_record,
                                             replay_record)
        pods, ts, results, _ = solved
        rec = FlightRecorder(capacity=4)
        rec.capture_provisioning(ts, pods, results, 0.0)
        report = replay_record(loads_record(rec.lines()[-1]))
        assert report.deterministic is True, report.render()

    def test_bench_mode_replay_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "replay" in m.group(0), \
            "BENCH_MODE=replay missing from the unknown-mode error list"


class TestTracingBudget:
    """ISSUE 7 guard: the BENCH_MODE=trace budget at test scale. The 5%
    tracing-on bound is asserted at 50k in bench_trace; at 2,000 pods the
    absolute span cost is what a regression would trip — so this pins the
    mechanism directly: spans stay per-STAGE (a per-pod/per-group span
    regression multiplies the count by 1000x and fails the hard count
    check), the tracing-disabled path stays a no-op, and the dumped trace
    stays valid Chrome JSON covering the measured wall clock."""

    MAX_SPANS_PER_SOLVE = 40
    RELATIVE_FACTOR = 1.25
    RELATIVE_GRACE_SECONDS = 0.10

    def test_span_count_and_overhead(self, solved):
        from karpenter_tpu.obs.tracer import TRACER
        pods, _, _, _ = solved

        def best_of(n=3):
            best = float("inf")
            for _ in range(n):
                ts = bench._scheduler(0)
                t0 = time.perf_counter()
                ts.solve(pods)
                best = min(best, time.perf_counter() - t0)
            return best

        saved = TRACER.enabled
        try:
            TRACER.enabled = False
            best_off = best_of()
            TRACER.enabled = True
            best_on = best_of()
            trace = TRACER.last()
        finally:
            TRACER.enabled = saved
        assert trace is not None and trace.name == "solve"
        assert len(trace.spans) <= self.MAX_SPANS_PER_SOLVE, (
            f"{len(trace.spans)} spans in one solve — a per-pod/per-group "
            "span slipped into the hot path")
        assert best_on <= best_off * self.RELATIVE_FACTOR \
            + self.RELATIVE_GRACE_SECONDS, (
            f"tracing-on solve {best_on:.3f}s vs off {best_off:.3f}s — "
            "span overhead regressed")

    def test_trace_covers_wall_clock_and_is_valid_chrome(self, solved):
        import json

        from karpenter_tpu.obs.tracer import TRACER, dumps_chrome
        pods, _, _, _ = solved
        ts = bench._scheduler(0)
        t0 = time.perf_counter()
        ts.solve(pods)
        wall = time.perf_counter() - t0
        trace = TRACER.last()
        assert trace.name == "solve"
        assert trace.duration >= 0.95 * wall or wall - trace.duration < 0.010
        doc = json.loads(dumps_chrome([trace]))
        assert all(e["ph"] == "X" and "dur" in e and "ts" in e
                   and e["args"]["trace_id"] == trace.trace_id
                   for e in doc["traceEvents"])

    def test_disabled_tracer_records_nothing(self, solved):
        from karpenter_tpu.obs.tracer import TRACER
        pods, _, _, _ = solved
        saved = TRACER.enabled
        try:
            TRACER.enabled = False
            TRACER.clear()
            ts = bench._scheduler(0)
            ts.solve(pods)
            assert TRACER.traces() == []
            assert ts.last_trace_id == ""
        finally:
            TRACER.enabled = saved

    def test_headline_bench_emits_phase_breakdown(self, capsys):
        saved = (bench.N_PODS, bench.N_DEPLOYS)
        bench.N_PODS, bench.N_DEPLOYS = 500, 12
        try:
            line = bench.bench_provisioning(bench._pods(), 0, repeats=1)
        finally:
            bench.N_PODS, bench.N_DEPLOYS = saved
        assert "phases" in line
        assert line["phases"].get("pack", 0) > 0
        assert "build_problem" in line["phases"]

    def test_bench_mode_trace_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "trace" in m.group(0), \
            "BENCH_MODE=trace missing from the unknown-mode error list"


class TestDroughtBudget:
    """ISSUE 5 guard: the BENCH_MODE=drought line at test scale. The 5%
    masked-vs-unmasked bound is asserted at 50k in bench_drought (10 ms
    grace); at 2,000 pods timer noise dwarfs the mask cost, so this guard
    widens the absolute grace and pins what a regression would actually
    trip: the bench's internal assertions (tensor-path residency under the
    mask, no claim on a masked offering) plus an absolute wall-clock
    budget a host-Python mask rewrite would blow."""

    BUDGET_SECONDS = 30.0

    def test_drought_bench_shape_within_budget(self, capsys, monkeypatch):
        import json
        import os as _os

        monkeypatch.setenv("BENCH_DROUGHT_GRACE", "0.25")
        saved = (bench.N_PODS, bench.N_DEPLOYS, bench.REPEATS)
        bench.N_PODS, bench.N_DEPLOYS, bench.REPEATS = N_PODS, N_DEPLOYS, 3
        try:
            t0 = time.perf_counter()
            bench.bench_drought()
            elapsed = time.perf_counter() - t0
        finally:
            bench.N_PODS, bench.N_DEPLOYS, bench.REPEATS = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"drought bench took {elapsed:.2f}s at {N_PODS} pods — the "
            "registry mask likely left the vectorized path")
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "pods/sec"
        assert "unavailable-offerings registry" in line["metric"]

    def test_bench_mode_drought_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "drought" in m.group(0), \
            "BENCH_MODE=drought missing from the unknown-mode error list"


class TestMeshBudget:
    """8-device mesh regression gate (ISSUE 6 satellite, thresholds
    re-derived in ISSUE 10): BENCH_r05 showed the mesh line regress
    0.412s -> 0.918s with NO tier-1 gate — it was discovered at re-anchor
    time. This runs the ACTUAL headline shape (50k pods x 2k instance
    types) on the conftest-provided virtual 8-device CPU mesh and pins
    (1) exact decision equality vs the single-device solve and (2) the
    recovered wall-clock line as a RATIO against a same-process
    single-device run measured at test time — no absolute r05-capture
    constants, which flake on the 2-core driver box (it runs cross-process
    benches 30-50% slower than the captures).

    The bound: mesh <= single x RATIO_BOUND + GRACE. On-box the unified
    kernel lineage measures ~1.0x (0.385s vs 0.378s); the r05 dual-lineage
    regression was 2.2x, so 1.35x catches it with margin while absorbing
    2-core scheduler noise."""

    N_PODS_MESH = 50000
    N_ITS_MESH = 2000
    RATIO_BOUND = 1.35
    RATIO_GRACE_SECONDS = 0.15

    def test_mesh_solve_budget_and_parity(self):
        import jax

        from karpenter_tpu.parallel.mesh import make_solver_mesh

        if len(jax.devices()) < 8:
            pytest.skip("needs the conftest 8-device virtual CPU platform")
        saved = (bench.N_PODS, bench.N_DEPLOYS)
        bench.N_PODS, bench.N_DEPLOYS = self.N_PODS_MESH, N_DEPLOYS
        try:
            pods = bench._pods()
        finally:
            bench.N_PODS, bench.N_DEPLOYS = saved
        mesh = make_solver_mesh(8)

        def best_of(mesh_or_none, n=2):
            best, results = float("inf"), None
            for _ in range(n + 1):  # first pass warms the jit cache
                s = bench._scheduler(self.N_ITS_MESH)
                s.mesh = mesh_or_none
                t0 = time.perf_counter()
                results = s.solve(pods)
                best = min(best, time.perf_counter() - t0)
                assert s.fallback_reason == "", s.fallback_reason
            return best, results

        t_single, r_single = best_of(None)
        t_mesh, r_mesh = best_of(mesh)
        assert sorted(map(_claim_key, r_mesh.new_nodeclaims)) == \
            sorted(map(_claim_key, r_single.new_nodeclaims))
        assert r_mesh.pod_errors == r_single.pod_errors
        assert t_mesh <= t_single * self.RATIO_BOUND \
            + self.RATIO_GRACE_SECONDS, (
            f"8-device mesh line regressed: {t_mesh:.3f}s vs single-device "
            f"{t_single:.3f}s same-process (bound {self.RATIO_BOUND}x + "
            f"{self.RATIO_GRACE_SECONDS}s) — the r05 dual-kernel-lineage "
            "failure mode measured 2.2x")


class TestMeshScaleBudget:
    """BENCH_MODE=meshscale at tier-1 scale: the million-pod frontier bench
    clipped to 20k pods x 200 ITs x 200 groups x 2 pack shards runs the
    SAME bench function in-process (the conftest virtual 8-device platform
    stands in for the re-exec) and must hold every in-bench contract: exact
    mesh-vs-single-device decision parity, exact sharded-pack pod errors,
    the reconcile node envelope, and a reported per-device peak-bytes
    advantage over the single-device program."""

    BUDGET_SECONDS = 120.0

    def test_meshscale_bench_shape_within_budget(self, capsys):
        import json as _json

        import jax

        if len(jax.devices()) < bench.MESH_DEVICES:
            pytest.skip("needs the conftest 8-device virtual CPU platform")
        saved = (bench.MESHSCALE_PODS, bench.MESHSCALE_DEPLOYS,
                 bench.MESHSCALE_ITS, bench.MESHSCALE_SHARDS)
        bench.MESHSCALE_PODS, bench.MESHSCALE_DEPLOYS, \
            bench.MESHSCALE_ITS, bench.MESHSCALE_SHARDS = 20000, 200, 200, 2
        try:
            t0 = time.perf_counter()
            bench.bench_meshscale_local()
            elapsed = time.perf_counter() - t0
        finally:
            (bench.MESHSCALE_PODS, bench.MESHSCALE_DEPLOYS,
             bench.MESHSCALE_ITS, bench.MESHSCALE_SHARDS) = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"clipped meshscale bench took {elapsed:.1f}s — the sharded "
            "dispatch likely fell off the compiled path")
        line = _json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "pods/sec"
        assert "mesh scale" in line["metric"]
        assert line["value"] > 0
        assert line["exact_match_vs_single_device"] is True
        assert line["sharded_pack_errors_exact"] is True
        assert line["per_device_peak_bytes_sharded"] > 0
        assert line["per_device_peak_bytes_sharded"] < \
            line["single_device_peak_bytes"], (
            "sharding stopped lowering the per-device memory ceiling")

    def test_bench_mode_meshscale_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "meshscale" in m.group(0), \
            "BENCH_MODE=meshscale missing from the unknown-mode error list"


class TestChurnBudget:
    """ISSUE 6 guard: the BENCH_MODE=churn line at test scale. The 1k+
    arrivals/sec floor is asserted at 50k scale inside bench_churn; here
    the bench's own shape runs small (300 nodes) so tier-1 pins what a
    regression would trip: the internal delta-residency asserts
    (encode_kind == delta every window, dirty-row counts on node-churn
    windows, warm prefix restores on steady ones), the sampled
    delta-vs-cold bit-identity, and a p99 time-to-decision bound a
    return of cold encodes would blow — expressed as a SAME-PROCESS RATIO
    against the bench's own timed cold parity solve (the TestMeshBudget
    pattern, ISSUE 12 satellite: the old 1500ms absolute budget flaked on
    slow boxes and couldn't flag a cold regression on a fast one; on-box
    the delta p99 is ~22ms vs ~294ms cold, so 0.5x cold catches a return
    to cold encodes with >2x margin on both sides)."""

    N_NODES = 300
    P99_COLD_RATIO = 0.5
    RATE_FLOOR = 200.0

    def test_churn_bench_shape_within_budget(self, capsys):
        import json

        saved = (bench.N_NODES, bench.CHURN_PODS_PER_NODE,
                 bench.CHURN_WINDOWS, bench.CHURN_ARRIVALS,
                 bench.CHURN_MIN_RATE, bench.N_ITS)
        (bench.N_NODES, bench.CHURN_PODS_PER_NODE, bench.CHURN_WINDOWS,
         bench.CHURN_ARRIVALS, bench.CHURN_MIN_RATE, bench.N_ITS) = \
            (self.N_NODES, 4, 8, 120, self.RATE_FLOOR, 144)
        try:
            bench.bench_churn()
        finally:
            (bench.N_NODES, bench.CHURN_PODS_PER_NODE, bench.CHURN_WINDOWS,
             bench.CHURN_ARRIVALS, bench.CHURN_MIN_RATE, bench.N_ITS) = saved
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "pods/sec"
        assert "steady-state churn" in line["metric"]
        assert line["cold_ms"] > 0, "bench reported no cold reference"
        assert line["p99_ms"] < line["cold_ms"] * self.P99_COLD_RATIO, (
            f"churn p99 {line['p99_ms']}ms vs same-process cold "
            f"{line['cold_ms']}ms at {self.N_NODES} nodes — the delta "
            "path likely fell back to cold encodes")
        assert line["value"] >= self.RATE_FLOOR
        assert line["delta_encodes"] == 8  # every timed window rode deltas
        assert line["warm_restored_groups"] > 0

    def test_bench_mode_churn_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "churn" in m.group(0), \
            "BENCH_MODE=churn missing from the unknown-mode error list"

    def test_unknown_bench_mode_errors_loudly(self, monkeypatch):
        monkeypatch.setattr(bench, "MODE", "definitely-not-a-mode")
        with pytest.raises(SystemExit) as exc:
            bench.main()
        msg = str(exc.value)
        assert "definitely-not-a-mode" in msg
        assert "churn" in msg and "drought" in msg and "replay" in msg


class TestMeshChurnBudget:
    """ISSUE 18 guard: BENCH_MODE=meshchurn at tier-1 scale. The bench's
    own in-line asserts are the real matrix (per-shard dirty-row residency
    every window, per-shard upload/skip metric deltas on rollout windows,
    warm-vs-cold decision parity, the per-flavor ratio gates) — this
    guard runs the SAME bench function on a clipped shape under the
    conftest virtual 8-device platform with RATIO knobs opened (at 128
    nodes the fixed jit-dispatch overhead of a churn window rivals the
    tiny cold solve, so the full-scale 0.10 ceiling is meaningless here)
    and pins the structural fields a regression would flip. Ratios stay
    ratio-only: no absolute milliseconds that flake across boxes."""

    BUDGET_SECONDS = 240.0
    RATIO = 50.0

    def test_meshchurn_bench_shape_within_budget(self, capsys):
        import json as _json

        import jax

        if len(jax.devices()) < bench.MESH_DEVICES:
            pytest.skip("needs the conftest 8-device virtual CPU platform")
        saved = (bench.MESHCHURN_NODES, bench.MESHCHURN_PODS_PER_NODE,
                 bench.MESHCHURN_DEPLOYS, bench.MESHCHURN_WINDOWS,
                 bench.MESHCHURN_WOBBLE, bench.MESHCHURN_ITS,
                 bench.MESHCHURN_RATIO, bench.MESHCHURN_CHURN_RATIO,
                 bench.MESHCHURN_ROLLOUT_RATIO)
        (bench.MESHCHURN_NODES, bench.MESHCHURN_PODS_PER_NODE,
         bench.MESHCHURN_DEPLOYS, bench.MESHCHURN_WINDOWS,
         bench.MESHCHURN_WOBBLE, bench.MESHCHURN_ITS,
         bench.MESHCHURN_RATIO, bench.MESHCHURN_CHURN_RATIO,
         bench.MESHCHURN_ROLLOUT_RATIO) = \
            (128, 4, 40, 10, 6, 144, self.RATIO, self.RATIO, self.RATIO)
        try:
            t0 = time.perf_counter()
            bench.bench_meshchurn_local()
            elapsed = time.perf_counter() - t0
        finally:
            (bench.MESHCHURN_NODES, bench.MESHCHURN_PODS_PER_NODE,
             bench.MESHCHURN_DEPLOYS, bench.MESHCHURN_WINDOWS,
             bench.MESHCHURN_WOBBLE, bench.MESHCHURN_ITS,
             bench.MESHCHURN_RATIO, bench.MESHCHURN_CHURN_RATIO,
             bench.MESHCHURN_ROLLOUT_RATIO) = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"clipped meshchurn bench took {elapsed:.1f}s — the delta "
            "path likely fell back to cold work every window")
        line = _json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert "mesh churn" in line["metric"]
        assert line["parity_vs_cold"] is True
        assert line["exist_shards"] > 1
        # per-shard delta residency was asserted inside EVERY window
        assert line["shard_residency_windows"] == line["windows"] == 10
        assert line["steady_windows"] == 5
        assert line["churn_windows"] == 2
        assert line["rollout_windows"] == 3
        assert line["cold_s"] > 0, "bench reported no cold reference"
        # ratio-only: the gates the bench itself enforced, re-checked from
        # the reported record so a silently-skipped assert can't pass
        assert line["ratio_p99"] <= self.RATIO
        assert line["churn_ratio"] <= self.RATIO
        assert line["rollout_ratio"] <= self.RATIO
        assert line["warm_p50_s"] <= line["warm_p99_s"]

    def test_bench_mode_meshchurn_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "meshchurn" in m.group(0), \
            "BENCH_MODE=meshchurn missing from the unknown-mode error list"


class TestStatePlaneBudget:
    """ISSUE 19 guard: BENCH_MODE=stateplane at tier-1 scale. The bench's
    own in-line asserts are the real matrix (rows encode ONCE per revision
    bump with the second subscriber reporting zero reencodes, object-
    identity proof that ONE exist-side upload served both passes, the
    encode wall-time ratio gate) — this guard runs the SAME bench function
    on a clipped shape with the in-bench ratio knob opened, then re-checks
    the structural fields and a modest ratio floor from the reported
    record so a silently-skipped assert can't pass. Ratio-only: no
    absolute milliseconds that flake across boxes."""

    BUDGET_SECONDS = 120.0
    NODES = 512
    WINDOWS = 4
    CHURN = 32
    # headline floor is 1.5 at 2048 nodes; the clipped shape measures
    # ~1.6x but sums only ~25ms of encode, so hold a no-win-collapse
    # floor with jitter headroom instead of the full gate
    RATIO_FLOOR = 1.15

    def test_stateplane_bench_shape_within_budget(self, capsys):
        import json as _json

        saved = (bench.STATEPLANE_NODES, bench.STATEPLANE_PODS_PER_NODE,
                 bench.STATEPLANE_WINDOWS, bench.STATEPLANE_CHURN,
                 bench.STATEPLANE_ITS, bench.STATEPLANE_RATIO)
        (bench.STATEPLANE_NODES, bench.STATEPLANE_PODS_PER_NODE,
         bench.STATEPLANE_WINDOWS, bench.STATEPLANE_CHURN,
         bench.STATEPLANE_ITS, bench.STATEPLANE_RATIO) = \
            (self.NODES, 2, self.WINDOWS, self.CHURN, 144, 1.0)
        try:
            t0 = time.perf_counter()
            bench.bench_stateplane()
            elapsed = time.perf_counter() - t0
        finally:
            (bench.STATEPLANE_NODES, bench.STATEPLANE_PODS_PER_NODE,
             bench.STATEPLANE_WINDOWS, bench.STATEPLANE_CHURN,
             bench.STATEPLANE_ITS, bench.STATEPLANE_RATIO) = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"clipped stateplane bench took {elapsed:.1f}s — the shared "
            "plane likely stopped serving rows across subscribers")
        line = _json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert "one state plane" in line["metric"]
        assert line["windows"] == self.WINDOWS
        # rows encoded ONCE per revision bump: the shared plane's encode
        # counter is exactly the cold warmup stack plus the dirtied rows —
        # a second subscriber paying again would double the dirtied term
        assert line["node_rows_encoded"] == self.NODES + line["dirtied_rows"]
        assert line["node_rows_shared"] > 0
        assert line["group_rows_shared"] > 0
        assert line["stack_hits"] > 0
        # every window dirtied rows, so every window re-keyed the shared
        # exist-side upload exactly once (the identity assert that the
        # second pass was served the SAME slot ran inside the bench)
        assert line["exist_uploads"] == self.WINDOWS
        assert line["value"] >= self.RATIO_FLOOR, (
            f"shared-plane encode speedup collapsed to {line['value']}x "
            f"(floor {self.RATIO_FLOOR}x)")

    def test_bench_mode_stateplane_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "stateplane" in m.group(0), \
            "BENCH_MODE=stateplane missing from the unknown-mode error list"


class TestServiceBudget:
    """ISSUE 8 guard: the BENCH_MODE=service line at test scale. The 0.5s
    warm-delta round-trip budget is asserted at 50k x 2k inside
    bench_service; here the bench's own shape runs small (2k pods x the
    kwok 144-type catalog, 2 tenants) so tier-1 pins what a regression
    would trip: every timed window DELTA-resident server-side with zero
    resyncs (asserted in-bench from the response headers), the sampled
    byte-identical cold-parity probes, per-tenant admission metrics, and a
    wall-clock bound a return of full-batch re-encodes (or a resync loop)
    would blow — expressed as a SAME-PROCESS RATIO: the warm delta round
    trip vs the SAME run's full-session bootstrap, both measured in the
    same client process (ISSUE 12 satellite — the old 20s absolute warm
    budget was a recurring flake on this 2-core box, where cross-process
    captures run 30-50% slower than the r05 numbers; the in-bench
    SERVICE_WARM_BUDGET stays as a generous hang guard only)."""

    BUDGET_SECONDS = 240.0
    WARM_BUDGET_SECONDS = 60.0     # hang guard passed into the bench
    # the warm delta must BEAT the bootstrap by a margin for the ratio to
    # bind (1.0 would hold even when deltas regress to full re-encodes):
    # headline measures 0.46s vs 2.2s (0.21x); test scale ~0.1x
    WARM_VS_FULL_RATIO = 0.5
    RATIO_GRACE_SECONDS = 0.1

    def test_service_bench_shape_within_budget(self, capsys):
        import json

        saved = (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
                 bench.SERVICE_TENANTS, bench.SERVICE_WINDOWS,
                 bench.SERVICE_WARM_BUDGET)
        (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
         bench.SERVICE_TENANTS, bench.SERVICE_WINDOWS,
         bench.SERVICE_WARM_BUDGET) = (
            N_PODS, N_DEPLOYS, 144, 2, 3, self.WARM_BUDGET_SECONDS)
        try:
            t0 = time.perf_counter()
            bench.bench_service()
            elapsed = time.perf_counter() - t0
        finally:
            (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
             bench.SERVICE_TENANTS, bench.SERVICE_WINDOWS,
             bench.SERVICE_WARM_BUDGET) = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"service bench took {elapsed:.1f}s at {N_PODS} pods — the "
            "delta wire likely fell back to full-batch re-encodes")
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "pods/sec"
        assert "sidecar service" in line["metric"]
        # delta residency + handshake health, from the in-bench asserts'
        # reported evidence: every timed window rode the delta wire, no
        # session ever resynced, every parity probe came back identical
        assert line["resyncs"] == 0
        assert line["delta_solves"] == 3 + 2 * 3  # phase A + B windows
        assert line["parity_samples"] == 3        # 1 + one per tenant
        assert line["tenants"] == 2
        # same-process ratio: the p50 warm delta round trip must beat the
        # full-session bootstrap measured by the same client process in
        # the same run (a return of full-batch re-encodes makes them equal)
        assert line["full_session_seconds"] > 0
        assert line["seconds"] <= (line["full_session_seconds"]
                                   * self.WARM_VS_FULL_RATIO
                                   + self.RATIO_GRACE_SECONDS), (
            f"warm delta p50 {line['seconds']}s vs full bootstrap "
            f"{line['full_session_seconds']}s same-process — the delta "
            "wire likely fell back to full-batch re-encodes")
        assert line["resync_seconds"] > 0
        # the causal join evidence (ISSUE 12): every tenant's warm solve
        # joined client-side and at least one full server tree survived
        assert line["trace_joins_in_server_ring"] >= 1

    def test_bench_mode_service_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "service" in m.group(0), \
            "BENCH_MODE=service missing from the unknown-mode error list"


class TestServiceFaultsBudget:
    """ISSUE 11 guard: the BENCH_MODE=svc-faults line at test scale. The
    headline run asserts in-bench: zero wedged sessions, zero resyncs
    under a seeded 5% wire-fault window (with a forced drop/disconnect/
    duplicate per tenant so each recovery path provably fires), p99 round
    trip bounded, cold-parity byte-identical on the chaos-churned
    sessions, and chaos-off overhead within budget. Here the same code
    runs small (2k pods x the kwok 144-type catalog, 2 tenants) — the
    overhead and p99 budgets are loosened because this 2-core driver box
    cannot resolve a 5% delta on ~20ms windows (the memory-pinned
    cross-process noise), while every correctness assert stays exact."""

    BUDGET_SECONDS = 240.0
    # wall-noise allowances for the clipped shape; the 5%/3s defaults
    # remain asserted by the headline BENCH_MODE=svc-faults run
    OVERHEAD_ALLOWANCE = 0.5
    P99_ALLOWANCE_SECONDS = 20.0

    def test_svc_faults_bench_shape_within_budget(self, capsys):
        import json

        saved = (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
                 bench.SVCFAULTS_TENANTS, bench.SVCFAULTS_WINDOWS,
                 bench.SVCFAULTS_OVERHEAD, bench.SVCFAULTS_P99_BUDGET)
        (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
         bench.SVCFAULTS_TENANTS, bench.SVCFAULTS_WINDOWS,
         bench.SVCFAULTS_OVERHEAD, bench.SVCFAULTS_P99_BUDGET) = (
            N_PODS, N_DEPLOYS, 144, 2, 3,
            self.OVERHEAD_ALLOWANCE, self.P99_ALLOWANCE_SECONDS)
        try:
            t0 = time.perf_counter()
            bench.bench_svc_faults()
            elapsed = time.perf_counter() - t0
        finally:
            (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
             bench.SVCFAULTS_TENANTS, bench.SVCFAULTS_WINDOWS,
             bench.SVCFAULTS_OVERHEAD, bench.SVCFAULTS_P99_BUDGET) = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"svc-faults bench took {elapsed:.1f}s at {N_PODS} pods — "
            "fault recovery is likely resyncing instead of retrying")
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "pods/sec"
        assert "wire faults" in line["metric"]
        # the in-bench asserts' reported evidence: every recovery path
        # provably fired and healed without a single resync or wedge
        assert line["zero_wedged"] is True
        assert line["resyncs"] == 0
        assert line["faults"]["drop"] >= 2        # one forced per tenant
        assert line["faults"]["disconnect"] >= 2
        assert line["faults"]["duplicate"] >= 2
        assert line["retries"] >= 4               # drop+disconnect x tenants
        assert line["dedup_hits"] >= 2            # disconnect recovery
        assert line["parity_samples"] == 2
        assert line["fault_p99_ms"] > 0
        assert line["tenants"] == 2

    def test_bench_mode_svc_faults_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "svc-faults" in m.group(0), \
            "BENCH_MODE=svc-faults missing from the unknown-mode error list"


class TestSimBudget:
    """ISSUE 9 guard: the BENCH_MODE=sim line at test scale. The full 24h
    mixed-day acceptance (two same-seed runs, byte-identical digests,
    >=100x compression, exactly-one breach dump) runs in the bench; here
    the scenario is clipped to its first 2 simulated hours so tier-1 pins
    what a regression would trip: the bench's own in-bench asserts
    (digest determinism across the two runs, finite SLO numbers, the
    compression floor) plus a wall-clock budget an unpaced disruption
    loop or a per-tick O(pods^2) scan would blow.

    Budgets measured on this box — the 2-core driver runs cross-process
    benches 30-50% slower than the r05 captures, so the clipped bench
    (~5 s here) gets a generous envelope."""

    CLIP_SECONDS = 7200.0
    BUDGET_SECONDS = 120.0

    def test_sim_bench_shape_within_budget(self, capsys):
        import json as _json

        saved = (bench.SIM_CLIP_SECONDS, bench.SIM_MIN_COMPRESSION)
        bench.SIM_CLIP_SECONDS, bench.SIM_MIN_COMPRESSION = \
            self.CLIP_SECONDS, 100.0
        try:
            t0 = time.perf_counter()
            bench.bench_sim()
            elapsed = time.perf_counter() - t0
        finally:
            bench.SIM_CLIP_SECONDS, bench.SIM_MIN_COMPRESSION = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"clipped sim bench took {elapsed:.1f}s — the adaptive "
            "stepper or the paced disruption cadence likely regressed")
        line = _json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "x wall-clock compression"
        assert "fleet simulator" in line["metric"]
        assert line["value"] >= 100.0
        assert line["deterministic"] is True
        assert line["p99_tts_s"] > 0
        assert line["cost_per_pod_hour"] > 0
        assert line["claims_created"] > 0

    def test_bench_mode_sim_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "sim" in m.group(0), \
            "BENCH_MODE=sim missing from the unknown-mode error list"


@pytest.mark.parametrize("kind", [0, 1, 2, 4, 5, 6, 7, 8])
def test_node_count_parity_vs_host_oracle_per_kind(kind):
    pods = [p for p in _mix()
            if int(p.metadata.name.split("-")[1]) % 9 == kind]
    assert pods
    ts = bench._scheduler(0)
    r = ts.solve(pods)
    assert ts.fallback_reason == ""
    assert ts.partition == (len(pods), 0)
    host = bench._scheduler(0)
    rh = host._host_solve(pods, "forced oracle comparison")
    assert len(r.new_nodeclaims) == len(rh.new_nodeclaims), \
        (f"node count diverged from the host oracle for constraint kind "
         f"{kind}: tensor={len(r.new_nodeclaims)} "
         f"oracle={len(rh.new_nodeclaims)}")
    assert set(r.pod_errors) == set(rh.pod_errors)


class TestFallbacksBudget:
    """ISSUE 12 guard: the BENCH_MODE=fallbacks line at test scale. The
    bench itself asserts the hard contracts (per-class pod counts EXACT on
    the solve's attribution, the process ledger's aggregation consistent,
    circuit_open charging the whole batch); this guard runs the same code
    small and pins the reported evidence plus a generous hang-guard
    wall clock (the real cost signal is the in-line host-vs-tensor ratio,
    which is same-process by construction — no absolute capture
    constants)."""

    BUDGET_SECONDS = 120.0

    def test_fallbacks_bench_shape_within_budget(self, capsys):
        import json

        saved = (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS, bench.REPEATS)
        (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS, bench.REPEATS) = \
            (N_PODS, N_DEPLOYS, 144, 1)
        try:
            t0 = time.perf_counter()
            bench.bench_fallbacks()
            elapsed = time.perf_counter() - t0
        finally:
            (bench.N_PODS, bench.N_DEPLOYS, bench.N_ITS,
             bench.REPEATS) = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"fallbacks bench took {elapsed:.1f}s at {N_PODS} pods — the "
            "host path or the ledger likely regressed")
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
        mixed, circ = lines[-2], lines[-1]
        assert set(mixed["classes"]) == {"ports", "volumes", "topo",
                                         "multi_group"}
        assert mixed["fallback_fraction"] > 0
        assert set(mixed["class_fraction"]) == set(mixed["classes"])
        assert mixed["host_seconds"] > 0 and mixed["tensor_seconds"] > 0
        # the degradation envelope is real: the host path is measurably
        # slower per pod than the tensor path on the same solve
        assert mixed["host_vs_tensor_slowdown"] > 1.0
        assert "circuit_open" in circ["metric"]
        assert list(circ["classes"]) == ["circuit_open"]

    def test_bench_mode_fallbacks_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "fallbacks" in m.group(0), \
            "BENCH_MODE=fallbacks missing from the unknown-mode error list"


class TestDisruptionScaleBudget:
    """ISSUE 14 guard: the BENCH_MODE=disruption-scale line at test scale.
    Runs the bench's own worst-case fleet (every candidate but the last
    provably unconsolidatable) at 800 nodes through the FULL 4-method
    controller pass and pins what the 50k acceptance line demands: warm
    passes entirely delta-resident (all snapshot layers reused, zero
    candidate rows rebuilt, encodings kept — asserted inside the bench),
    decisions byte-identical to a fresh cold controller (asserted inside),
    only the winner replayed (one LOO probe, ranked multi-node midpoints
    skipped), and the warm pass landing within the provisioning-pass
    ratio. The asserts here are ratio-based against the bench's own
    same-run measurements, never absolute wall clock."""

    N_NODES = 800
    PENDING = 300

    def test_disruption_scale_bench_shape_within_budget(self, capsys):
        import json

        saved = (bench.DISRUPTION_NODES, bench.DISRUPTION_PENDING,
                 bench.REPEATS)
        bench.DISRUPTION_NODES = self.N_NODES
        bench.DISRUPTION_PENDING = self.PENDING
        bench.REPEATS = 3
        try:
            bench.bench_disruption_scale()
        finally:
            (bench.DISRUPTION_NODES, bench.DISRUPTION_PENDING,
             bench.REPEATS) = saved
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "seconds"
        assert line["nodes"] == self.N_NODES
        assert line["decision"] == "delete"
        # the acceptance bar: a warm streaming pass runs in the same order
        # as a provisioning pass over the same fleet (the bench asserts
        # the ceiling internally; the field must be present and sane)
        assert line["warm_vs_provisioning"] <= bench.DISRUPTION_WARM_RATIO
        # warm must beat cold (the streaming state actually engaged) —
        # same-process ratio, not an absolute budget
        assert line["warm_pass_s"] < line["cold_pass_s"], line
        assert line["warm_candidate_build_s"] < \
            line["cold_candidate_build_s"], line
        # residency facts the bench asserted internally, re-pinned here so
        # a silently-removed bench assert still fails the budget
        assert line["loo_probes"] == 1
        assert line["multi_probes_saved"] > 0

    def test_bench_mode_disruption_scale_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "disruption-scale" in m.group(0), \
            "BENCH_MODE=disruption-scale missing from the unknown-mode list"


class TestSvcFleetBudget:
    """ISSUE 17 guard: the BENCH_MODE=svc-fleet line's scaffolding at test
    scale. The headline run asserts in-bench: sim ledger digests
    byte-identical at 1-vs-N replicas, zero resyncs (no cold bootstrap
    after the initial connect), aggregate warm-solve scaling over one
    server, and per-tenant p99 held through a whole-fleet rolling
    restart. The full line boots subprocess replicas and replays the
    service-fleet scenario twice — too heavy for tier-1 (the end-to-end
    fleet behavior is covered by tests/test_sidecar_fleet.py's sim smoke
    and the sim-regression digest pin) — so this class pins the pieces
    that must not silently drift: the mode dispatch and the
    floor-selection plan that decides when 2.5x actually binds."""

    def test_bench_mode_svc_fleet_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "svc-fleet" in m.group(0), \
            "BENCH_MODE=svc-fleet missing from the unknown-mode error list"

    @pytest.mark.parametrize(
        "cores,mode,want_proc,want_full_floor",
        [
            # auto picks subprocess replicas iff the box has a spare core
            # per replica; only THAT shape can prove parallel scaling
            (8, "auto", True, True),
            (1, "auto", False, False),
            (3, "auto", False, False),  # cores == replicas: starved
            # forced proc on a starved box still runs the real subprocess
            # shape but is held to the no-collapse floor, not 2.5x
            (1, "proc", True, False),
            (8, "proc", True, True),
            # forced thread shares one GIL regardless of cores — the full
            # floor never binds in-process
            (8, "thread", False, False),
            (1, "thread", False, False),
        ])
    def test_scaling_floor_binds_only_when_provable(
            self, cores, mode, want_proc, want_full_floor):
        use_proc, floor = bench.svcfleet_scaling_plan(cores, 3, mode)
        assert use_proc is want_proc
        want = bench.SVCFLEET_SCALING if want_full_floor \
            else bench.SVCFLEET_SCALING_MIN
        assert floor == want


class TestAuditBudget:
    """ISSUE 20 guard: the BENCH_MODE=audit line at test scale. The 5%
    auditor-on bound is asserted at the 512-node/2k-IT headline shape in
    bench_audit; here the same function runs shrunk (96 nodes x 144 ITs,
    2 windows x best-of 2) so a regression that makes the lazy digest
    checks or the sampled shadow audits non-amortized — anything that
    puts per-row Python back on the serve path — trips in tier-1 instead
    of a benchmark round later. The detect-quarantine-heal half is
    structural, so it must hold at ANY scale: the bench asserts the
    forced corruption is caught with cold parity internally, and the
    emitted JSON line is pinned here."""

    KNOBS = {"AUDIT_NODES": 96, "AUDIT_ITS": 144, "AUDIT_WINDOWS": 2,
             "AUDIT_CHURN": 8, "AUDIT_REPEAT": 2,
             # absolute-slack dominated at this scale: per-window walls
             # are single-digit ms, where timer noise swamps any ratio
             "AUDIT_SLACK_S": 0.5}

    def test_audit_bench_shape_passes_at_test_scale(self, capsys):
        import json

        saved = {k: getattr(bench, k) for k in self.KNOBS}
        for k, v in self.KNOBS.items():
            setattr(bench, k, v)
        try:
            bench.bench_audit()
        finally:
            for k, v in saved.items():
                setattr(bench, k, v)
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "fractional overhead"
        assert line["incidents_detected"] == 1
        assert line["healed"] is True
        assert line["audited"].get("node_rows", 0) > 0
        assert line["audited"].get("warm_checkpoint", 0) > 0

    def test_bench_mode_audit_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "audit" in m.group(0), \
            "BENCH_MODE=audit missing from the unknown-mode error list"
