"""Regression guard for the flagship Solve() tentpole (round 6).

Round 5 proved the failure mode this file exists for: a correctness fix
moved the cohort scan into per-cohort host Python and the headline
benchmark regressed 0.499 s -> 1.197 s, discovered only at the NEXT
benchmark capture. This guard runs a scaled-down headline mix (the bench
deployment kinds at 2,000 pods x the kwok 144-type catalog) inside the
normal test suite and pins everything that regression would have tripped:

- the whole batch stays ON the vectorized tensor path (no host fallback,
  no partition) — a "fix" that silently demotes mix shapes to the host
  oracle fails here instead of a benchmark round later;
- a generous wall-clock budget per solve — pure-Python cohort scans at
  O(groups x cohorts) blow it even at this scale;
- byte-identical placements across independent solves of the same batch
  (the packer is deterministic; vectorization must keep it so);
- pod-error identity with the host oracle, and exact node-count parity
  per constraint kind everywhere it structurally holds (hostname pod
  affinity is a documented deviation: the tensor path keeps those groups
  alone, DEVIATIONS.md).
"""

import time

import pytest

from karpenter_tpu.api import labels as api_labels

import bench

N_PODS = 2000
N_DEPLOYS = 36
# generous: the solve runs ~0.2 s on CPU jax; a return of the round-5
# per-cohort Python scan costs >5x at 50k pods and measurably here too
BUDGET_SECONDS = 10.0


def _mix():
    saved = (bench.N_PODS, bench.N_DEPLOYS)
    bench.N_PODS, bench.N_DEPLOYS = N_PODS, N_DEPLOYS
    try:
        return bench._pods()
    finally:
        bench.N_PODS, bench.N_DEPLOYS = saved


def _claim_key(nc):
    return (nc.template.nodepool_name,
            tuple(sorted(nc.requirements.get(
                api_labels.LABEL_TOPOLOGY_ZONE).values)),
            tuple(it.name for it in nc.instance_type_options),
            len(nc.pods))


@pytest.fixture(scope="module")
def solved():
    pods = _mix()
    ts = bench._scheduler(0)
    ts.solve(pods)  # warm the jit cache: the budget times the solve, not XLA
    ts = bench._scheduler(0)
    t0 = time.perf_counter()
    results = ts.solve(pods)
    elapsed = time.perf_counter() - t0
    return pods, ts, results, elapsed


def test_headline_mix_stays_on_tensor_path(solved):
    pods, ts, results, _ = solved
    assert ts.fallback_reason == "", \
        f"headline mix fell off the tensor path: {ts.fallback_reason}"
    assert ts.partition == (len(pods), 0), ts.partition
    assert not results.pod_errors


def test_headline_mix_within_wall_clock_budget(solved):
    _, _, _, elapsed = solved
    assert elapsed < BUDGET_SECONDS, \
        (f"scaled headline solve took {elapsed:.2f}s (budget "
         f"{BUDGET_SECONDS}s) — the cohort scan likely fell off the "
         "vectorized path")


def test_solve_is_byte_identical_across_runs(solved):
    pods, _, results, _ = solved
    ts2 = bench._scheduler(0)
    r2 = ts2.solve(pods)
    assert ts2.fallback_reason == ""
    assert sorted(map(_claim_key, r2.new_nodeclaims)) == \
        sorted(map(_claim_key, results.new_nodeclaims))
    assert r2.pod_errors == results.pod_errors


def test_error_identity_vs_host_oracle(solved):
    pods, _, results, _ = solved
    host = bench._scheduler(0)
    rh = host._host_solve(pods, "forced oracle comparison")
    assert set(results.pod_errors) == set(rh.pod_errors)


# hostname pod affinity (kind 3) is excluded: the tensor path packs each
# affinity group on its own node while the oracle may co-locate distinct
# groups (documented deviation) — count parity doesn't apply there
class TestSingleNodeConsolidationBudget:
    """ISSUE 3 guard: the BENCH_MODE=single line at test scale. Runs the
    bench's own worst-case shape (every candidate but the last provably
    unconsolidatable) at 120 nodes and pins what the 5,000-node acceptance
    line demands: tensor-path residency (the bench function asserts zero
    needs_sim rows and exactly one probe internally), decision determinism
    across repeats (also asserted internally), a wall-clock budget a return
    of per-candidate serial sims would blow, and warm compile-cache reuse
    across successive passes (padded shape buckets must be stable)."""

    N_NODES = 120
    # the batched pass runs ~50 ms here; the serial shape costs ~3 s at
    # this scale (28 ms/sim x 120) and the budget catches that regression
    BUDGET_SECONDS = 10.0

    def test_single_bench_shape_within_budget(self, capsys):
        import json

        from karpenter_tpu.metrics.registry import (
            SOLVER_COMPILE_CACHE_HITS, SOLVER_COMPILE_CACHE_MISSES)

        saved = (bench.N_NODES, bench.REPEATS)
        bench.N_NODES, bench.REPEATS = self.N_NODES, 3
        try:
            bench.bench_single_consolidation()  # warm pass inside
            hits0 = SOLVER_COMPILE_CACHE_HITS.value()
            misses0 = SOLVER_COMPILE_CACHE_MISSES.value()
            t0 = time.perf_counter()
            bench.bench_single_consolidation()
            elapsed = time.perf_counter() - t0
        finally:
            bench.N_NODES, bench.REPEATS = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"single-node consolidation bench took {elapsed:.2f}s at "
            f"{self.N_NODES} nodes — the leave-one-out path likely fell "
            "back to per-candidate sims")
        # the second bench run re-encodes the same padded shape buckets:
        # the compiled-executable cache must serve it without recompiling
        assert SOLVER_COMPILE_CACHE_HITS.value() > hits0
        assert SOLVER_COMPILE_CACHE_MISSES.value() == misses0
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "seconds"
        assert line["value"] < self.BUDGET_SECONDS


class TestFlightRecorderBudget:
    """ISSUE 4 guard: the BENCH_MODE=replay budget at test scale. The 5%
    recorder-on bound is asserted at 50k in bench_replay; at 2,000 pods the
    absolute overhead budget is what a regression would trip — so this
    pins the capture mechanism directly: the hot-path capture must stay
    deferred (no payload/digest encode inside the solve) and cost
    milliseconds, and the deferred materialization must still replay to a
    byte-identical decision."""

    CAPTURE_BUDGET_SECONDS = 0.020

    def test_capture_is_deferred_and_cheap(self, solved):
        from karpenter_tpu.flightrec import FlightRecorder
        pods, ts, results, _ = solved
        rec = FlightRecorder(capacity=4)
        t0 = time.perf_counter()
        rec.capture_provisioning(ts, pods, results, 0.0)
        elapsed = time.perf_counter() - t0
        assert elapsed < self.CAPTURE_BUDGET_SECONDS, (
            f"hot-path capture took {elapsed * 1000:.1f}ms at "
            f"{len(pods)} pods — the deferred encode likely went eager")
        r = rec.records()[-1]
        assert r._refs is not None and r._digest_refs is not None, \
            "capture materialized inside the solve path"
        assert r.decision is None

    def test_recorded_solve_replays_byte_identical(self, solved):
        from karpenter_tpu.flightrec import (FlightRecorder, loads_record,
                                             replay_record)
        pods, ts, results, _ = solved
        rec = FlightRecorder(capacity=4)
        rec.capture_provisioning(ts, pods, results, 0.0)
        report = replay_record(loads_record(rec.lines()[-1]))
        assert report.deterministic is True, report.render()

    def test_bench_mode_replay_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "replay" in m.group(0), \
            "BENCH_MODE=replay missing from the unknown-mode error list"


class TestDroughtBudget:
    """ISSUE 5 guard: the BENCH_MODE=drought line at test scale. The 5%
    masked-vs-unmasked bound is asserted at 50k in bench_drought (10 ms
    grace); at 2,000 pods timer noise dwarfs the mask cost, so this guard
    widens the absolute grace and pins what a regression would actually
    trip: the bench's internal assertions (tensor-path residency under the
    mask, no claim on a masked offering) plus an absolute wall-clock
    budget a host-Python mask rewrite would blow."""

    BUDGET_SECONDS = 30.0

    def test_drought_bench_shape_within_budget(self, capsys, monkeypatch):
        import json
        import os as _os

        monkeypatch.setenv("BENCH_DROUGHT_GRACE", "0.25")
        saved = (bench.N_PODS, bench.N_DEPLOYS, bench.REPEATS)
        bench.N_PODS, bench.N_DEPLOYS, bench.REPEATS = N_PODS, N_DEPLOYS, 3
        try:
            t0 = time.perf_counter()
            bench.bench_drought()
            elapsed = time.perf_counter() - t0
        finally:
            bench.N_PODS, bench.N_DEPLOYS, bench.REPEATS = saved
        assert elapsed < self.BUDGET_SECONDS, (
            f"drought bench took {elapsed:.2f}s at {N_PODS} pods — the "
            "registry mask likely left the vectorized path")
        line = json.loads(
            [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")][-1])
        assert line["unit"] == "pods/sec"
        assert "unavailable-offerings registry" in line["metric"]

    def test_bench_mode_drought_is_a_known_mode(self):
        import re
        with open(bench.__file__) as f:
            src = f.read()
        m = re.search(r"unknown BENCH_MODE.*?\"\)", src, re.S)
        assert m and "drought" in m.group(0), \
            "BENCH_MODE=drought missing from the unknown-mode error list"


@pytest.mark.parametrize("kind", [0, 1, 2, 4, 5, 6, 7, 8])
def test_node_count_parity_vs_host_oracle_per_kind(kind):
    pods = [p for p in _mix()
            if int(p.metadata.name.split("-")[1]) % 9 == kind]
    assert pods
    ts = bench._scheduler(0)
    r = ts.solve(pods)
    assert ts.fallback_reason == ""
    assert ts.partition == (len(pods), 0)
    host = bench._scheduler(0)
    rh = host._host_solve(pods, "forced oracle comparison")
    assert len(r.new_nodeclaims) == len(rh.new_nodeclaims), \
        (f"node count diverged from the host oracle for constraint kind "
         f"{kind}: tensor={len(r.new_nodeclaims)} "
         f"oracle={len(rh.new_nodeclaims)}")
    assert set(r.pod_errors) == set(rh.pod_errors)
