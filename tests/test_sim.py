"""Fleet simulator (ISSUE 9): scenario schema rejects, the thread-safe
condition-variable FakeClock, engine replays through the full operator
loop, determinism, the breach -> flight-dump path, and the CLI.

Everything here runs on tiny scenarios (a few pods, minutes of simulated
time) so tier-1 stays inside its timeout; the multi-minute soak at the
bottom carries `slow` and only runs in the full suite.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time

import pytest

from karpenter_tpu.sim import (FleetSimulator, ScenarioError, load_scenario,
                               parse_scenario)
from karpenter_tpu.utils.clock import FakeClock

pytestmark = pytest.mark.sim

SCENARIOS_DIR = os.path.join(os.path.dirname(__file__), "..",
                             "karpenter_tpu", "sim", "scenarios")


def _doc(**over):
    doc = {
        "name": "t", "seed": 1, "duration": 600.0, "tick": 20,
        "events": [{"at": 5, "kind": "deploy", "name": "web", "replicas": 3,
                    "cpu": "500m", "memory": "256Mi"}],
    }
    doc.update(over)
    return doc


# -- scenario schema: loud rejects (satellite 1) -----------------------------

class TestScenarioValidation:
    def test_minimal_document_parses(self):
        sc = parse_scenario(_doc())
        assert sc.name == "t" and len(sc.events) == 1
        assert sc.nodepools[0].name == "default"

    def test_unknown_top_level_key_rejected(self):
        with pytest.raises(ScenarioError, match=r"unknown key 'tikc'"):
            parse_scenario(_doc(tikc=5))

    def test_unknown_event_kind_rejected(self):
        doc = _doc()
        doc["events"].append({"at": 9, "kind": "depoy", "name": "x"})
        with pytest.raises(ScenarioError,
                           match=r"unknown event kind 'depoy'.*deploy"):
            parse_scenario(doc)

    def test_unknown_event_field_names_field_and_kind(self):
        doc = _doc()
        doc["events"][0]["fractoin"] = 0.5
        with pytest.raises(ScenarioError,
                           match=r"unknown key 'fractoin' in deploy event"):
            parse_scenario(doc)

    def test_missing_required_field_rejected(self):
        doc = _doc()
        del doc["events"][0]["cpu"]
        with pytest.raises(ScenarioError, match=r"missing required field "
                                                r"'cpu'"):
            parse_scenario(doc)

    def test_bad_type_names_field_and_value(self):
        doc = _doc()
        doc["events"][0]["replicas"] = "many"
        with pytest.raises(ScenarioError,
                           match=r"field 'replicas' in deploy event #1 "
                                 r"must be an integer"):
            parse_scenario(doc)

    def test_pdb_needs_exactly_one_constraint(self):
        for extra in ({}, {"max_unavailable": 1, "min_available": 1}):
            doc = _doc()
            doc["events"].append(
                {"at": 9, "kind": "pdb", "name": "p", "app": "web", **extra})
            with pytest.raises(ScenarioError, match="exactly one of"):
                parse_scenario(doc)

    def test_spot_reclaim_needs_fraction_or_count(self):
        doc = _doc()
        doc["events"].append({"at": 9, "kind": "spot_reclaim"})
        with pytest.raises(ScenarioError, match="at least one of"):
            parse_scenario(doc)

    def test_event_beyond_duration_rejected(self):
        doc = _doc()
        doc["events"].append({"at": 6000, "kind": "drain"})
        with pytest.raises(ScenarioError, match="beyond the scenario "
                                                "duration"):
            parse_scenario(doc)

    def test_scale_of_unknown_deployment_rejected(self):
        doc = _doc()
        doc["events"].append(
            {"at": 9, "kind": "scale", "name": "api", "replicas": 2})
        with pytest.raises(ScenarioError, match="unknown deployment 'api'"):
            parse_scenario(doc)

    def test_deploy_references_checked_in_execution_order(self):
        # the engine executes by (at, file index), not file order: a
        # scale listed BEFORE its deploy but timed after it is valid...
        doc = _doc()
        doc["events"] = [
            {"at": 9, "kind": "scale", "name": "web", "replicas": 2},
            {"at": 5, "kind": "deploy", "name": "web", "replicas": 3,
             "cpu": "500m", "memory": "256Mi"},
        ]
        assert len(parse_scenario(doc).events) == 2
        # ...and a scale timed BEFORE its deploy is rejected even with
        # the deploy first in the file (it would KeyError mid-run)
        doc["events"] = [
            {"at": 100, "kind": "deploy", "name": "web", "replicas": 3,
             "cpu": "500m", "memory": "256Mi"},
            {"at": 50, "kind": "scale", "name": "web", "replicas": 2},
        ]
        with pytest.raises(ScenarioError, match="unknown deployment 'web'"):
            parse_scenario(doc)

    def test_bad_slo_budget_rejected(self):
        with pytest.raises(ScenarioError, match="bad 'slo_budgets'"):
            parse_scenario(_doc(slo_budgets="pass=-1"))

    # -- ISSUE 11: backend + wire-chaos schema rejects -----------------------

    def test_unknown_backend_rejected(self):
        with pytest.raises(ScenarioError,
                           match=r"'backend'.*\"tensor\" or \"sidecar\""):
            parse_scenario(_doc(backend="grpc"))

    def test_wire_chaos_without_sidecar_backend_rejected(self):
        doc = _doc()
        doc["events"].append({"at": 10, "kind": "wire_chaos", "drop": 0.1,
                              "duration": 60})
        with pytest.raises(ScenarioError,
                           match=r"requires 'backend: sidecar'"):
            parse_scenario(doc)

    def test_wire_chaos_without_any_fault_rejected(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 10, "kind": "wire_chaos",
                              "duration": 60})
        with pytest.raises(ScenarioError,
                           match=r"needs at least one fault"):
            parse_scenario(doc)

    def test_wire_chaos_bad_rate_names_field(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 10, "kind": "wire_chaos", "drop": 1.7,
                              "duration": 60})
        with pytest.raises(ScenarioError,
                           match=r"field 'drop' in wire_chaos event #2 "
                                 r"must be a number in \[0, 1\]"):
            parse_scenario(doc)

    def test_wire_chaos_unknown_field_rejected(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 10, "kind": "wire_chaos", "dorp": 0.1,
                              "duration": 60})
        with pytest.raises(ScenarioError, match=r"unknown key 'dorp'"):
            parse_scenario(doc)

    def test_wire_chaos_sidecar_backend_accepted(self):
        doc = _doc(backend="sidecar")
        doc["events"].append({"at": 10, "kind": "wire_chaos",
                              "kill_server": True, "duration": 60})
        sc = parse_scenario(doc)
        assert sc.backend == "sidecar"
        assert sc.events[-1].params["kill_server"] is True

    def test_yaml_reject_names_file_and_line(self, tmp_path):
        p = tmp_path / "bad.yaml"
        p.write_text("name: x\n"
                     "duration: 600\n"
                     "events:\n"
                     "  - at: 5\n"
                     "    kind: deploy\n"
                     "    name: web\n"
                     "    replicas: 2\n"
                     "    cpu: 500m\n"
                     "    memory: 256Mi\n"
                     "    fractoin: 1\n")
        with pytest.raises(ScenarioError,
                           match=r"bad\.yaml:10: unknown key 'fractoin'"):
            load_scenario(str(p))

    def test_yaml_unknown_kind_names_its_line(self, tmp_path):
        p = tmp_path / "bad2.yaml"
        p.write_text("name: x\nduration: 600\nevents:\n"
                     "  - at: 5\n"
                     "    kind: depoy\n")
        with pytest.raises(ScenarioError, match=r"bad2\.yaml:5: unknown "
                                                r"event kind 'depoy'"):
            load_scenario(str(p))

    def test_invalid_yaml_syntax_rejected(self, tmp_path):
        p = tmp_path / "syntax.yaml"
        p.write_text("name: [unclosed\nduration: 600\n")
        with pytest.raises(ScenarioError, match="invalid YAML"):
            load_scenario(str(p))

    def test_json_scenario_loads(self, tmp_path):
        p = tmp_path / "s.json"
        p.write_text(json.dumps(_doc()))
        sc = load_scenario(str(p))
        assert sc.events[0].kind == "deploy"

    def test_library_scenarios_all_validate(self):
        names = sorted(os.listdir(SCENARIOS_DIR))
        assert len(names) >= 4
        for name in names:
            sc = load_scenario(os.path.join(SCENARIOS_DIR, name))
            assert sc.events and sc.duration > 0


# -- FakeClock: condition-variable sleepers (satellite 2) --------------------

class TestFakeClockSleepers:
    def test_zero_and_negative_sleep_return_immediately(self):
        clock = FakeClock()
        clock.sleep(0)
        clock.sleep(-5)
        assert clock.sleepers == 0

    def test_sleeper_blocks_until_step_crosses_deadline(self):
        clock = FakeClock()
        woke = threading.Event()

        def sleeper():
            clock.sleep(10.0)
            woke.set()

        t = threading.Thread(target=sleeper, daemon=True)
        t.start()
        deadline = time.time() + 5.0
        while clock.sleepers == 0 and time.time() < deadline:
            time.sleep(0.001)
        # pinned: the thread is PARKED on the condition variable (visible
        # as a registered sleeper), not spinning on now()
        assert clock.sleepers == 1
        clock.step(9.0)          # not enough: deadline not crossed
        time.sleep(0.02)
        assert not woke.is_set()
        clock.step(1.0)          # crosses: condition notify wakes it
        assert woke.wait(5.0)
        t.join(5.0)
        assert clock.sleepers == 0

    def test_multiple_sleepers_wake_only_past_their_deadlines(self):
        clock = FakeClock()
        woke = {}

        def sleeper(name, seconds):
            clock.sleep(seconds)
            woke[name] = True

        threads = [threading.Thread(target=sleeper, args=("a", 5.0),
                                    daemon=True),
                   threading.Thread(target=sleeper, args=("b", 50.0),
                                    daemon=True)]
        for t in threads:
            t.start()
        deadline = time.time() + 5.0
        while clock.sleepers < 2 and time.time() < deadline:
            time.sleep(0.001)
        assert clock.sleepers == 2
        clock.step(10.0)
        threads[0].join(5.0)
        assert woke.get("a") and not woke.get("b")
        assert clock.sleepers == 1
        clock.set_time(clock.now() + 100.0)  # set_time wakes too
        threads[1].join(5.0)
        assert woke.get("b") and clock.sleepers == 0

    def test_thread_safe_step_returns_new_now(self):
        clock = FakeClock(start=100.0)
        assert clock.step(5.0) == 105.0
        assert clock.now() == 105.0
        clock.set_time(42.0)
        assert clock.now() == 42.0

    def test_operator_run_loop_paced_by_fake_clock(self):
        """Clock plumbing: Operator.run sleeps on the INJECTED clock, so a
        simulator thread advancing a FakeClock drives the real-time loop
        without wall-clock waits."""
        from karpenter_tpu.api.objects import Pod
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options

        from factories import make_nodepool, make_pod

        clock = FakeClock()
        op = Operator(options=Options(metrics_port=0, health_probe_port=0),
                      clock=clock)
        op.store.create(make_nodepool(name="default"))
        op.store.create(make_pod(cpu="100m"))
        stop = threading.Event()
        t = threading.Thread(
            target=lambda: op.run(stop=stop.is_set, tick_seconds=1.0),
            daemon=True)
        t.start()
        deadline = time.time() + 10.0
        while time.time() < deadline:
            clock.step(1.1)
            if all(p.spec.node_name for p in op.store.list(Pod)) \
                    and op.store.list(Pod):
                break
            time.sleep(0.005)
        stop.set()
        # keep stepping until the loop wakes from its fake-clock sleep and
        # observes the stop flag (a single step can race the loop body)
        deadline = time.time() + 10.0
        while t.is_alive() and time.time() < deadline:
            clock.step(2.0)
            time.sleep(0.01)
        t.join(1.0)
        assert not t.is_alive()
        op.stop_serving()
        assert all(p.spec.node_name for p in op.store.list(Pod))


# -- engine ------------------------------------------------------------------

def _run(doc, **kw):
    sim = FleetSimulator(parse_scenario(doc), **kw)
    return sim, sim.run()


class TestEngine:
    def test_smoke_deploy_scale_drain(self):
        doc = _doc(duration=900.0)
        doc["events"] += [
            {"at": 300, "kind": "scale", "name": "web", "replicas": 6},
            {"at": 600, "kind": "drain", "count": 1},
        ]
        sim, report = _run(doc)
        assert report["final"]["pods_pending"] == 0
        assert report["final"]["pods_bound"] == 6
        assert report["churn"]["claims_created"] > 0
        tts = report["time_to_schedule"]
        assert tts["samples"] >= 6 and tts["p50_s"] > 0
        assert report["cost"]["per_pod_hour"] > 0
        assert report["solver"]["fallback_fraction"] == 0.0
        assert report["compression"] > 10
        # the ledger saw the whole story
        kinds = {e["kind"] for e in sim.ledger.entries}
        assert {"event", "solve", "node_added", "pod_bound"} <= kinds

    def test_same_seed_byte_identical_ledger_digest(self):
        doc = _doc(duration=900.0, seed=7)
        doc["events"] += [
            {"at": 200, "kind": "spot_reclaim", "fraction": 0.5},
            {"at": 400, "kind": "rolling_update", "name": "web", "batch": 2,
             "interval": 30},
        ]
        _, r1 = _run(doc)
        _, r2 = _run(doc)
        assert r1["ledger_digest"] == r2["ledger_digest"]

    def test_digest_stable_across_processes_and_hash_seeds(self, tmp_path):
        # CROSS-process byte-identity, the half the in-process test can't
        # see: Vocab.observe_requirements once iterated a SET of zone
        # values, so value indices — and the packer's index-order zone
        # tie-break for spread deploys — varied with PYTHONHASHSEED,
        # pairing the same nodes with different zones run to run
        sc = tmp_path / "spread.yaml"
        sc.write_text(
            "name: spread\nseed: 1\nduration: 600\nevents:\n"
            "  - {at: 10, kind: deploy, name: web, replicas: 6,"
            " cpu: \"2\", memory: 2Gi, spread: zone}\n")
        digests = []
        for hashseed in ("17", "4242"):
            env = dict(os.environ, PYTHONHASHSEED=hashseed)
            proc = subprocess.run(
                [sys.executable, "-m", "karpenter_tpu.sim", "run", str(sc)],
                capture_output=True, text=True, env=env, timeout=120)
            assert proc.returncode == 0, proc.stderr
            m = re.search(r'"ledger_digest": "([0-9a-f]+)"',
                          proc.stdout + proc.stderr)
            assert m, proc.stdout + proc.stderr
            digests.append(m.group(1))
        assert digests[0] == digests[1], digests

    def test_different_seed_diverges_under_chaos(self):
        # seeded randomness is the ONLY free variable: the spot-reclaim
        # wave samples its victims from the scenario RNG, so different
        # seeds reclaim different nodes (and the fleet's subsequent story
        # diverges) while same seeds stay identical (above)
        base = _doc(duration=900.0)
        base["events"][0].update(replicas=6, cpu="100")  # ~2 pods/node
        base["events"].append(
            {"at": 300, "kind": "spot_reclaim", "fraction": 0.4})
        doc_a = json.loads(json.dumps(base))
        doc_b = json.loads(json.dumps(base))
        # seeds pinned to a diverging victim pair: sample(3 nodes, 2)
        # under seed 1 picks {1,3}, under seed 4 picks {1,2}
        doc_b["seed"] = 4
        sim_a, ra = _run(doc_a)
        sim_b, rb = _run(doc_b)
        victims = [sorted(e["node"] for e in s.ledger.entries
                          if e["kind"] == "reclaim")
                   for s in (sim_a, sim_b)]
        assert victims[0] and victims[1]
        assert victims[0] != victims[1], victims
        assert ra["ledger_digest"] != rb["ledger_digest"]

    def test_spot_reclaim_replaces_capacity(self):
        doc = _doc(duration=1200.0)
        doc["events"][0]["replicas"] = 6
        doc["events"].append(
            {"at": 300, "kind": "spot_reclaim", "fraction": 1.0})
        sim, report = _run(doc)
        reclaims = [e for e in sim.ledger.entries if e["kind"] == "reclaim"]
        assert reclaims, "no spot node was reclaimed"
        assert report["churn"]["pods_replaced"] >= 1
        # replacements landed: nothing pending at the end
        assert report["final"]["pods_pending"] == 0
        assert report["final"]["pods_bound"] == 6

    def test_zonal_outage_masks_zone_until_recovery(self):
        from karpenter_tpu.api import labels as api_labels
        from karpenter_tpu.api.objects import Node
        doc = _doc(duration=3600.0, tick=15)
        doc["events"][0]["replicas"] = 6
        doc["events"].append({"at": 600, "kind": "zonal_outage",
                              "zone": "test-zone-a", "duration": 900})
        sim, report = _run(doc)
        # while the outage window lived, no NEW node landed in the zone
        outage_nodes = [
            e for e in sim.ledger.entries
            if e["kind"] == "node_added" and e["zone"] == "test-zone-a"
            and 600 <= e["t"] < 1500]
        assert outage_nodes == [], outage_nodes
        assert report["events_applied"]["zonal_outage"] == 1
        assert report["final"]["pods_pending"] == 0

    def test_pdb_constrained_drain_completes(self):
        doc = _doc(duration=1800.0)
        doc["events"][0]["replicas"] = 6
        doc["events"] += [
            {"at": 60, "kind": "pdb", "name": "web-pdb", "app": "web",
             "max_unavailable": 1},
            {"at": 600, "kind": "drain", "count": 1},
        ]
        sim, report = _run(doc)
        drained = [e for e in sim.ledger.entries if e["kind"] == "event"
                   and e.get("event") == "drain"]
        assert drained and drained[0]["nodes"]
        gone = {e["node"] for e in sim.ledger.entries
                if e["kind"] == "node_gone"}
        assert set(drained[0]["nodes"]) <= gone, "drain never completed"
        # every evicted pod rebound: drain did not strand the workload
        assert report["final"]["pods_pending"] == 0
        assert report["churn"]["pods_evicted"] >= 1

    def test_induced_slo_breach_dumps_exactly_one_flight_record(self, tmp_path):
        doc = _doc(duration=900.0)
        doc["events"] += [
            {"at": 300, "kind": "slo",
             "budgets": {"provisioner.pass": 1e-9}, "duration": 60},
            {"at": 310, "kind": "deploy", "name": "canary", "replicas": 2,
             "cpu": "100m", "memory": "128Mi"},
        ]
        sim, report = _run(doc, flightrec_dir=str(tmp_path))
        assert len(report["breaches"]) == 1, report["breaches"]
        breach = report["breaches"][0]
        assert breach["slo"] == "provisioner.pass"
        files = os.listdir(tmp_path)
        assert len(files) == 1
        lines = [json.loads(line)
                 for line in open(tmp_path / files[0]) if line.strip()]
        assert lines
        assert all(rec["meta"]["trace_id"] == breach["trace_id"]
                   for rec in lines)
        # joinable: the breaching pass is one of the ledger's solve entries
        assert breach["trace_id"] in {
            e.get("trace_id") for e in sim.ledger.entries
            if e["kind"] == "solve"}

    def test_overlapping_slo_windows_restore_baseline(self):
        # window 2 opens while window 1 is live; once BOTH close the
        # effective budgets are the pre-window baseline — a per-window
        # saved-previous snapshot would resurrect window 1's budgets at
        # window 2's later close and leave them live forever
        doc = _doc(duration=900.0)
        doc["events"] += [
            {"at": 100, "kind": "slo", "budgets": {"span.a": 100.0},
             "duration": 200},
            {"at": 200, "kind": "slo", "budgets": {"span.b": 100.0},
             "duration": 200},
        ]
        sim, _ = _run(doc)
        assert sim.op.slo.budgets == {}, sim.op.slo.budgets
        assert len([e for e in sim.ledger.entries
                    if e["kind"] == "slo_end"]) == 2

    def test_breaches_beyond_watcher_ring_reach_ledger(self, tmp_path):
        # the watcher's `breaches` deque is bounded (keep_breaches); the
        # engine consumes breaches through the on_breach hook, so a run
        # breaching more than the ring keeps still ledgers every one —
        # the old cumulative-slice read went silent past the maxlen
        from collections import deque
        doc = _doc(duration=900.0, slo_budgets="provisioner.pass=1e-9")
        doc["events"] += [
            {"at": 200, "kind": "scale", "name": "web", "replicas": 5},
            {"at": 400, "kind": "scale", "name": "web", "replicas": 7},
        ]
        sim = FleetSimulator(parse_scenario(doc),
                             flightrec_dir=str(tmp_path))
        sim.op.slo.breaches = deque(maxlen=1)
        report = sim.run()
        assert len(report["breaches"]) >= 3, report["breaches"]
        assert len([e for e in sim.ledger.entries
                    if e["kind"] == "breach"]) == len(report["breaches"])
        assert len(sim.op.slo.breaches) == 1  # the ring stayed bounded

    def test_flaky_window_injects_then_recovers(self):
        doc = _doc(duration=1200.0, seed=5)
        doc["events"] += [
            {"at": 120, "kind": "flaky", "rate": 0.4, "duration": 300},
            {"at": 180, "kind": "scale", "name": "web", "replicas": 8},
        ]
        sim, report = _run(doc)
        assert sim.injector.fired() > 0, "flaky window never fired a fault"
        assert sim.injector.rate == 0.0, "flaky window never closed"
        # the operator rode the faults out: workload fully placed
        assert report["final"]["pods_pending"] == 0
        assert report["final"]["pods_bound"] == 8

    def test_overlapping_flaky_windows_restore_live_window(self):
        # window 1 closes while window 2 is still live: the close must
        # restore window 2's rates, not unconditionally calm the injector
        # (the _ev_slo window-stack shape). Window 2 outlives the
        # scenario, so the post-run injector rates ARE its live rates.
        doc = _doc(duration=900.0)
        doc["events"] += [
            {"at": 100, "kind": "flaky", "rate": 0.2, "terminal_rate": 0.1,
             "duration": 200},
            {"at": 200, "kind": "flaky", "rate": 0.05, "duration": 5000},
        ]
        sim, _ = _run(doc)
        assert sim.injector.rate == 0.05, sim.injector.rate
        assert sim.injector.terminal_rate == 0.0
        ends = [e for e in sim.ledger.entries if e["kind"] == "flaky_end"]
        assert len(ends) == 1  # only window 1 closed in-scenario

    def test_rolling_update_reaches_new_generation(self):
        doc = _doc(duration=1800.0)
        doc["events"][0]["replicas"] = 6
        doc["events"].append({"at": 300, "kind": "rolling_update",
                              "name": "web", "batch": 2, "interval": 60})
        sim, report = _run(doc)
        done = [e for e in sim.ledger.entries if e["kind"] == "rollout_done"]
        assert done and done[0]["generation"] == 2
        from karpenter_tpu.api.objects import Pod
        gens = {p.metadata.labels.get("sim/gen")
                for p in sim.op.store.list(Pod, namespace="default")}
        assert gens == {"2"}, gens

    def test_sim_metrics_families_exported(self):
        from karpenter_tpu.metrics.registry import REGISTRY
        _run(_doc(duration=300.0))
        text = REGISTRY.expose()
        for family in ("karpenter_sim_events_applied_total",
                       "karpenter_sim_ticks_total",
                       "karpenter_sim_clock_seconds",
                       "karpenter_sim_pod_hours_total",
                       "karpenter_sim_fleet_cost_dollars_total"):
            assert family in text, family


# -- CLI ---------------------------------------------------------------------

class TestServiceBackend:
    """ISSUE 11: solver_backend=sidecar — the engine boots a real
    in-process gRPC sidecar, runs the whole session wire under the
    accelerated clock, survives wire-chaos windows and a server kill, and
    keeps the ledger digest byte-identical for the same seed."""

    DOC = {
        "name": "svc", "seed": 5, "duration": 900.0, "tick": 20,
        "backend": "sidecar",
        "events": [
            {"at": 5, "kind": "deploy", "name": "web", "replicas": 4,
             "cpu": "500m", "memory": "256Mi"},
            {"at": 120, "kind": "wire_chaos", "drop": 0.1,
             "disconnect": 0.1, "duration": 300},
            {"at": 300, "kind": "scale", "name": "web", "replicas": 8},
            {"at": 500, "kind": "wire_chaos", "kill_server": True,
             "duration": 60},
            {"at": 700, "kind": "scale", "name": "web", "replicas": 6},
        ],
    }

    def test_sidecar_backend_with_faults_completes_and_heals(self):
        import copy
        sim, report = _run(copy.deepcopy(self.DOC))
        assert report["backend"] == "sidecar"
        assert report["final"]["pods_pending"] == 0
        assert report["final"]["pods_bound"] == 6
        tts = report["time_to_schedule"]
        assert tts["samples"] > 0 and tts["p99_s"] > 0
        # the server kill forced exactly the transparent recovery path:
        # NOT_FOUND -> session recreate -> full resync
        svc = report["service"]
        assert svc["backend"] == "sidecar" and svc["deadline_s"] > 0
        assert svc["resyncs"] >= 1
        kinds = [e["kind"] for e in sim.ledger.entries]
        assert "sidecar_restart" in kinds and "wire_chaos_end" in kinds
        assert any(e.get("event") == "wire_chaos"
                   for e in sim.ledger.entries)
        # the sidecar server was torn down with the run
        assert sim.sidecar_server is None

    def test_sidecar_backend_same_seed_byte_identical_digest(self):
        import copy
        _, r1 = _run(copy.deepcopy(self.DOC))
        _, r2 = _run(copy.deepcopy(self.DOC))
        assert r1["ledger_digest"] == r2["ledger_digest"]

    def test_tensor_backend_reports_no_service_section(self):
        _, report = _run(_doc())
        assert report["backend"] == "tensor"
        assert report["service"] is None

    def test_service_faults_library_scenario_validates(self):
        sc = load_scenario(os.path.join(SCENARIOS_DIR,
                                        "service-faults.yaml"))
        assert sc.backend == "sidecar"
        assert any(e.kind == "wire_chaos" and e.params["kill_server"]
                   for e in sc.events)


class TestCli:
    def test_validate_accepts_library_scenario(self, capsys):
        from karpenter_tpu.sim.__main__ import main
        path = os.path.join(SCENARIOS_DIR, "rolling-deploy.yaml")
        assert main(["validate", path]) == 0
        assert "rolling-deploy" in capsys.readouterr().out

    def test_validate_rejects_loudly(self, tmp_path, capsys):
        from karpenter_tpu.sim.__main__ import main
        p = tmp_path / "bad.yaml"
        p.write_text("name: x\nduration: 600\nevents:\n  - at: 5\n"
                     "    kind: nope\n")
        assert main(["validate", str(p)]) == 2
        assert "unknown event kind" in capsys.readouterr().err

    def test_run_writes_report_and_ledger(self, tmp_path, capsys):
        from karpenter_tpu.sim.__main__ import main
        p = tmp_path / "s.json"
        p.write_text(json.dumps(_doc(duration=300.0)))
        out = tmp_path / "report.json"
        ledger = tmp_path / "ledger.jsonl"
        assert main(["run", str(p), "--out", str(out),
                     "--ledger", str(ledger),
                     "--flightrec-dir", str(tmp_path)]) == 0
        report = json.loads(out.read_text())
        assert report["scenario"] == "t"
        assert len(ledger.read_text().splitlines()) \
            == report["ledger_entries"]
        rendered = capsys.readouterr().out
        assert "compression" in rendered and "pod-hour" in rendered
        # report subcommand renders the saved file
        assert main(["report", str(out)]) == 0
        assert "scenario    t" in capsys.readouterr().out
        # ...and rejects a non-report file (the ledger is the classic
        # mix-up) with a clean pointer instead of a traceback
        assert main(["report", str(ledger)]) == 2
        err = capsys.readouterr().err
        assert "report rejected" in err and "run --out" in err
        assert main(["report", str(tmp_path / "missing.json")]) == 2
        assert "report rejected" in capsys.readouterr().err


# -- soak (slow: full-suite only) --------------------------------------------

@pytest.mark.slow
class TestScenarioSoaks:
    """Multi-minute scenario soaks: the library scenarios end to end."""

    @pytest.mark.parametrize("name", ["rolling-deploy.yaml",
                                      "spot-reclaim-wave.yaml",
                                      "zonal-drought.yaml",
                                      "pdb-drain.yaml",
                                      "service-faults.yaml",
                                      "disruption-wave.yaml"])
    def test_library_scenario_replays_clean(self, name):
        sc = load_scenario(os.path.join(SCENARIOS_DIR, name))
        sim = FleetSimulator(sc)
        report = sim.run()
        assert report["final"]["pods_pending"] == 0
        assert report["time_to_schedule"]["samples"] > 0
        assert report["cost"]["per_pod_hour"] > 0
        assert report["compression"] >= 100

    def test_mixed_day_deterministic_at_quarter_scale(self):
        sc1 = load_scenario(os.path.join(SCENARIOS_DIR, "mixed-day.yaml"))
        sc2 = load_scenario(os.path.join(SCENARIOS_DIR, "mixed-day.yaml"))
        for sc in (sc1, sc2):
            sc.duration = 21600.0
            sc.events = [e for e in sc.events if e.at <= 21600.0]
        r1 = FleetSimulator(sc1).run()
        r2 = FleetSimulator(sc2).run()
        assert r1["ledger_digest"] == r2["ledger_digest"]


# -- drift / expiration waves (ISSUE 14 satellite) ---------------------------

class TestDisruptionWaveEvents:
    def test_drift_and_expire_need_fraction_or_count(self):
        for kind, extra in (("drift", {}),
                            ("expire", {"expire_after": 600})):
            doc = _doc()
            doc["events"].append({"at": 9, "kind": kind, **extra})
            with pytest.raises(ScenarioError, match="at least one of"):
                parse_scenario(doc)

    def test_expire_requires_expire_after(self):
        doc = _doc()
        doc["events"].append({"at": 9, "kind": "expire", "count": 1})
        with pytest.raises(ScenarioError,
                           match="missing required field 'expire_after'"):
            parse_scenario(doc)

    def test_drift_wave_replaces_flagged_claims(self):
        """End to end: a drift wave stamps stale nodepool hashes, the
        marker controller flags Drifted, and the Drift method replaces
        the flagged claims — visible as reclaimed/terminated churn."""
        sc = parse_scenario({
            "name": "drift-wave-e2e", "seed": 7, "duration": 2400,
            "tick": 20, "disruption_interval": 60,
            "events": [
                {"at": 30, "kind": "deploy", "name": "web", "replicas": 9,
                 "cpu": "8", "memory": "8Gi", "spread": "zone"},
                {"at": 600, "kind": "drift", "count": 2},
            ]})
        report = FleetSimulator(sc).run()
        assert report["events_applied"].get("drift") == 1
        assert report["churn"]["claims_terminated"] >= 2
        assert report["final"]["pods_pending"] == 0

    def test_expire_wave_retires_oldest_claims(self):
        sc = parse_scenario({
            "name": "expire-wave-e2e", "seed": 7, "duration": 3600,
            "tick": 20, "disruption_interval": 60,
            "events": [
                {"at": 30, "kind": "deploy", "name": "web", "replicas": 9,
                 "cpu": "8", "memory": "8Gi", "spread": "zone"},
                {"at": 600, "kind": "expire", "count": 2,
                 "expire_after": 700},
            ]})
        report = FleetSimulator(sc).run()
        assert report["events_applied"].get("expire") == 1
        assert report["churn"]["claims_terminated"] >= 2
        assert report["final"]["pods_pending"] == 0
