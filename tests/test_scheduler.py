"""Host scheduler behavior suite.

Scenarios mirror behaviors from the reference suites
(pkg/controllers/provisioning/scheduling/{suite,topology,instance_selection}_test.go),
re-expressed against this framework's API.
"""

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import NodeSelectorRequirement, Taint, Toleration
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.cloudprovider.fake import fake_instance_types
from karpenter_tpu.utils import resources as res

from factories import (affinity_term, make_nodepool, make_pod, make_pods,
                       make_scheduler, spread_hostname, spread_zone)


def kwok_its():
    return kwok.construct_instance_types()


class TestBasicScheduling:
    def test_single_pod_single_node(self):
        pods = [make_pod()]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        assert len(results.new_nodeclaims) == 1
        assert results.new_nodeclaims[0].pods == pods

    def test_pods_pack_one_node(self):
        pods = make_pods(10, cpu="100m", memory="64Mi")
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        assert len(results.new_nodeclaims) == 1

    def test_large_pods_split_nodes(self):
        # 4 pods x 150 cpu only fit on 192/256-cpu instance types, one each
        pods = make_pods(4, cpu="150", memory="1Gi")
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        assert len(results.new_nodeclaims) == 4

    def test_unsatisfiable_pod_errors(self):
        pods = [make_pod(cpu="1000")]  # larger than any instance type
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert len(results.pod_errors) == 1
        assert not results.new_nodeclaims

    def test_daemonset_overhead_reserved(self):
        pods = [make_pod(cpu="700m")]
        daemon = make_pod(cpu="400m")
        daemon.is_daemonset_pod = True
        s = make_scheduler([make_nodepool()], kwok_its(), pods, daemonset_pods=[daemon])
        results = s.solve(pods)
        assert results.pod_errors == {}
        nc = results.new_nodeclaims[0]
        # 700m pod + 400m daemon exceeds a 1-cpu node's 900m allocatable
        # (100m kube-reserved overhead), so only >=2-cpu instance types remain
        assert all(it.capacity[res.CPU] >= 2000 for it in nc.instance_type_options)


class TestInstanceSelection:
    def test_node_selector_restricts_zone(self):
        pods = [make_pod(node_selector={api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-b"})]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        nc = results.new_nodeclaims[0]
        assert nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values == {"test-zone-b"}

    def test_arch_selector_filters_instance_types(self):
        pods = [make_pod(node_selector={api_labels.LABEL_ARCH: "arm64"})]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        for it in results.new_nodeclaims[0].instance_type_options:
            assert it.requirements.get(api_labels.LABEL_ARCH).has("arm64")

    def test_nodepool_requirements_apply(self):
        np = make_nodepool(requirements=[NodeSelectorRequirement(
            api_labels.CAPACITY_TYPE_LABEL_KEY, "In", (api_labels.CAPACITY_TYPE_ON_DEMAND,))])
        pods = [make_pod()]
        s = make_scheduler([np], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        ct = results.new_nodeclaims[0].requirements.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        assert ct.values == {api_labels.CAPACITY_TYPE_ON_DEMAND}

    def test_incompatible_node_selector_fails(self):
        pods = [make_pod(node_selector={api_labels.LABEL_TOPOLOGY_ZONE: "nonexistent-zone"})]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert len(results.pod_errors) == 1

    def test_custom_label_requires_nodepool_definition(self):
        # custom label not defined by any nodepool -> unschedulable
        pods = [make_pod(node_selector={"example.com/team": "infra"})]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        assert len(s.solve(pods).pod_errors) == 1
        # nodepool defining the label makes it schedulable
        np = make_nodepool(labels={"example.com/team": "infra"})
        pods2 = [make_pod(node_selector={"example.com/team": "infra"})]
        s2 = make_scheduler([np], kwok_its(), pods2)
        assert s2.solve(pods2).pod_errors == {}


class TestTaints:
    def test_tainted_pool_requires_toleration(self):
        np = make_nodepool(taints=[Taint(key="dedicated", value="infra")])
        pods = [make_pod()]
        s = make_scheduler([np], kwok_its(), pods)
        assert len(s.solve(pods).pod_errors) == 1

    def test_toleration_allows_tainted_pool(self):
        np = make_nodepool(taints=[Taint(key="dedicated", value="infra")])
        pods = [make_pod(tolerations=[Toleration(key="dedicated", operator="Exists")])]
        s = make_scheduler([np], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}

    def test_weighted_pools_ordered(self):
        heavy = make_nodepool(name="heavy", weight=50, labels={"pool": "heavy"})
        light = make_nodepool(name="light", weight=1, labels={"pool": "light"})
        from karpenter_tpu.api.nodepool import order_by_weight
        pools = order_by_weight([light, heavy])
        pods = [make_pod()]
        s = make_scheduler(pools, kwok_its(), pods)
        results = s.solve(pods)
        assert results.new_nodeclaims[0].template.nodepool_name == "heavy"


class TestTopologySpread:
    def test_zonal_spread_even(self):
        pods = make_pods(8, labels={"app": "demo"}, spread=[spread_zone()])
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        zones = {}
        for nc in results.new_nodeclaims:
            zone_req = nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
            assert zone_req.length() == 1
            z = zone_req.values_list()[0]
            zones[z] = zones.get(z, 0) + len(nc.pods)
        assert len(zones) == 4  # kwok has 4 zones
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_hostname_spread_max_skew(self):
        pods = make_pods(6, labels={"app": "demo"}, spread=[spread_hostname(max_skew=1)])
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        # maxSkew=1 with hostname topology: min count is always 0 -> 1 pod/node
        assert len(results.new_nodeclaims) == 6
        assert all(len(nc.pods) == 1 for nc in results.new_nodeclaims)

    def test_zonal_spread_restricted_zones(self):
        pods = make_pods(
            4, labels={"app": "demo"}, spread=[spread_zone()],
            node_selector=None,
            required_affinity=[[NodeSelectorRequirement(
                api_labels.LABEL_TOPOLOGY_ZONE, "In", ("test-zone-a", "test-zone-b"))]])
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        zones = {}
        for nc in results.new_nodeclaims:
            z = nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values_list()[0]
            zones[z] = zones.get(z, 0) + len(nc.pods)
        assert set(zones) == {"test-zone-a", "test-zone-b"}
        assert zones["test-zone-a"] == 2 and zones["test-zone-b"] == 2


class TestPodAffinity:
    def test_anti_affinity_hostname_one_per_node(self):
        pods = make_pods(5, labels={"app": "demo"},
                         pod_anti_affinity=[affinity_term(api_labels.LABEL_HOSTNAME)])
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        assert len(results.new_nodeclaims) == 5

    def test_zonal_affinity_colocates(self):
        pods = make_pods(6, labels={"app": "demo"},
                         pod_affinity=[affinity_term(api_labels.LABEL_TOPOLOGY_ZONE)])
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        zones = set()
        for nc in results.new_nodeclaims:
            zones.add(nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values_list()[0])
        assert len(zones) == 1

    def test_zonal_anti_affinity_late_committal(self):
        # Reference semantics (topology_test.go:2132-2176): with late committal,
        # a single batch schedules only ONE zonal anti-affinity pod — its zone
        # isn't collapsed, so all candidate domains get blocked for the rest.
        pods = make_pods(3, labels={"app": "demo"},
                         pod_anti_affinity=[affinity_term(api_labels.LABEL_TOPOLOGY_ZONE)])
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert len(results.pod_errors) == 2
        assert len(results.new_nodeclaims) == 1

    def test_zonal_anti_affinity_across_batches(self):
        # When each pod is constrained to a distinct zone, anti-affinity is
        # satisfiable within one batch: domains collapse to one zone per pod.
        zones = ["test-zone-a", "test-zone-b", "test-zone-c"]
        pods = [make_pod(labels={"app": "demo"},
                         node_selector={api_labels.LABEL_TOPOLOGY_ZONE: z},
                         pod_anti_affinity=[affinity_term(api_labels.LABEL_TOPOLOGY_ZONE)])
                for z in zones]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        assert len(results.new_nodeclaims) == 3
        got = sorted(nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values_list()[0]
                     for nc in results.new_nodeclaims)
        assert got == zones


class TestRelaxation:
    def test_impossible_preference_dropped(self):
        pods = [make_pod(preferred_affinity=[
            (10, [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE, "In", ("no-such-zone",))])])]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        assert len(results.new_nodeclaims) == 1

    def test_multiple_required_terms_or_semantics(self):
        pods = [make_pod(required_affinity=[
            [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE, "In", ("no-such-zone",))],
            [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE, "In", ("test-zone-c",))],
        ])]
        s = make_scheduler([make_nodepool()], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        nc = results.new_nodeclaims[0]
        assert nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values == {"test-zone-c"}


class TestLimits:
    def test_nodepool_limits_cap_nodes(self):
        np = make_nodepool(limits={"cpu": "2"})
        pods = make_pods(10, cpu="900m")
        s = make_scheduler([np], kwok_its(), pods)
        results = s.solve(pods)
        # with a 2-cpu limit and subtractMax pessimism, most pods can't get nodes
        assert len(results.pod_errors) > 0
        assert len(results.new_nodeclaims) <= 2

    def test_fallback_pool_when_limited(self):
        limited = make_nodepool(name="limited", weight=10, limits={"cpu": "1"},
                                labels={"pool": "limited"})
        fallback = make_nodepool(name="fallback", labels={"pool": "fallback"})
        pods = make_pods(4, cpu="2")
        s = make_scheduler([limited, fallback], kwok_its(), pods)
        results = s.solve(pods)
        assert results.pod_errors == {}
        pools = {nc.template.nodepool_name for nc in results.new_nodeclaims}
        assert "fallback" in pools
