"""Instance selection, minValues, Gt/Lt, and relaxation behaviors
(reference shapes: instance_selection_test.go + suite_test.go scenarios)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider.kwok import (construct_instance_types,
                                              make_instance_type, price_for)
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.scheduling.requirement import GT, IN, LT, Requirement
from karpenter_tpu.scheduling.requirements import Requirements

from factories import (make_nodepool, make_pod, make_pods, make_scheduler,
                       spread_zone)


class _MinValuesReq:
    def __init__(self, key, operator, values, min_values):
        self.key = key
        self.operator = operator
        self.values = tuple(values)
        self.min_values = min_values


class TestInstanceSelection:
    def test_cheapest_type_heads_launch_list(self):
        its = construct_instance_types()[:48]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        opts = r.new_nodeclaims[0].instance_type_options
        prices = [min(o.price for o in it.offerings) for it in opts]
        assert prices[0] == min(prices)

    def test_on_demand_selector_excludes_spot_pricing(self):
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={
            api_labels.CAPACITY_TYPE_LABEL_KEY:
                api_labels.CAPACITY_TYPE_ON_DEMAND})])
        assert not r.pod_errors
        reqs = r.new_nodeclaims[0].requirements
        ct = reqs.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        assert ct.has(api_labels.CAPACITY_TYPE_ON_DEMAND)
        assert not ct.has(api_labels.CAPACITY_TYPE_SPOT)

    def test_gt_requirement_on_numeric_label(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("company.io/generation", "Gt", ("3",))])
        its = []
        for gen in (2, 4):
            it = make_instance_type(4, 2, api_labels.ARCHITECTURE_AMD64, "linux")
            it.name = f"gen{gen}-4x"
            it.requirements.add(Requirement(api_labels.LABEL_INSTANCE_TYPE,
                                            IN, [it.name]))
            it.requirements.add(Requirement("company.io/generation", IN,
                                            [str(gen)]))
            its.append(it)
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        names = {it.name for it in r.new_nodeclaims[0].instance_type_options}
        assert names == {"gen4-4x"}

    def test_lt_requirement(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("company.io/generation", "Lt", ("3",))])
        its = []
        for gen in (2, 4):
            it = make_instance_type(4, 2, api_labels.ARCHITECTURE_AMD64, "linux")
            it.name = f"gen{gen}-4x"
            it.requirements.add(Requirement(api_labels.LABEL_INSTANCE_TYPE,
                                            IN, [it.name]))
            it.requirements.add(Requirement("company.io/generation", IN,
                                            [str(gen)]))
            its.append(it)
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        names = {it.name for it in r.new_nodeclaims[0].instance_type_options}
        assert names == {"gen2-4x"}

    def test_min_values_keeps_flexibility(self):
        """NodeSelectorRequirementWithMinValues: launch list must retain >= N
        distinct instance types (nodeclaim.go SatisfiesMinValues)."""
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 5)])
        its = construct_instance_types()[:48]
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        nc = r.new_nodeclaims[0]
        assert len(nc.instance_type_options) >= 5
        r.truncate_instance_types(10)
        assert len(r.new_nodeclaims[0].instance_type_options) <= 10
        assert len(r.new_nodeclaims[0].instance_type_options) >= 5

    def test_min_values_unsatisfiable_errors(self):
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 500)])
        its = construct_instance_types()[:24]
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert r.pod_errors


class TestRelaxation:
    def test_preferred_zone_honored_when_possible(self):
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", preferred_affinity=[
            (1, [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                         "In", ("test-zone-b",))])])])
        assert not r.pod_errors
        zone = r.new_nodeclaims[0].requirements.get(
            api_labels.LABEL_TOPOLOGY_ZONE)
        assert zone.has("test-zone-b") and zone.values_list() == ["test-zone-b"]

    def test_impossible_preferred_zone_relaxed(self):
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", preferred_affinity=[
            (1, [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                         "In", ("zone-on-the-moon",))])])])
        assert not r.pod_errors

    def test_schedule_anyway_spread_relaxes(self):
        from karpenter_tpu.api.objects import (LabelSelector,
                                               TopologySpreadConstraint)
        its = construct_instance_types()[:24]
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE, "In",
                                    ("test-zone-a",))])
        s = make_scheduler([pool], its, [])
        # spread over zones is impossible with one zone; ScheduleAnyway lets
        # all pods land in zone-a
        pods = make_pods(4, cpu="500m", labels={"app": "x"}, spread=[
            TopologySpreadConstraint(
                topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=1,
                label_selector=LabelSelector(match_labels={"app": "x"}),
                when_unsatisfiable="ScheduleAnyway")])
        r = s.solve(pods)
        assert not r.pod_errors


class TestExistingNodeOrder:
    def test_initialized_nodes_fill_first(self):
        from karpenter_tpu.api.nodeclaim import NodeClaim
        from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta
        from karpenter_tpu.state.statenode import StateNode
        from karpenter_tpu.utils import resources as res

        def node(name, initialized):
            labels = {api_labels.LABEL_HOSTNAME: name,
                      api_labels.NODEPOOL_LABEL_KEY: "default"}
            if initialized:
                labels[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
            alloc = res.parse_list({"cpu": "4", "memory": "8Gi", "pods": "110"})
            return StateNode(node=Node(
                metadata=ObjectMeta(name=name, namespace="", labels=labels),
                spec=NodeSpec(provider_id=f"t://{name}"),
                status=NodeStatus(capacity=dict(alloc), allocatable=alloc)))

        uninit = node("a-uninit", False)
        init = node("b-init", True)
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [],
                           state_nodes=[uninit, init])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        placed = [en for en in r.existing_nodes if en.pods]
        assert [en.name for en in placed] == ["b-init"]


class TestCheapestCompatibleMatrix:
    """instance_selection_test.go:87-462: under any single constraint —
    from the pool's requirements or the pod's node selector — the launch
    list is headed by the cheapest COMPATIBLE type and every option
    satisfies the constraint."""

    CASES = [
        (api_labels.LABEL_ARCH, api_labels.ARCHITECTURE_AMD64),
        (api_labels.LABEL_ARCH, api_labels.ARCHITECTURE_ARM64),
        (api_labels.LABEL_OS, "linux"),
        (api_labels.LABEL_OS, "windows"),
        (api_labels.LABEL_TOPOLOGY_ZONE, "test-zone-b"),
        (api_labels.CAPACITY_TYPE_LABEL_KEY, api_labels.CAPACITY_TYPE_SPOT),
        (api_labels.CAPACITY_TYPE_LABEL_KEY,
         api_labels.CAPACITY_TYPE_ON_DEMAND),
    ]

    def _assert_cheapest_compatible(self, r, key, value):
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        opts = nc.instance_type_options
        assert opts
        reqs = nc.requirements
        assert reqs.get(key).has(value)
        # every option admits the constraint (zone/ct live on offerings)
        for it in opts:
            if key in (api_labels.LABEL_TOPOLOGY_ZONE,
                       api_labels.CAPACITY_TYPE_LABEL_KEY):
                assert any(
                    (o.zone == value if key == api_labels.LABEL_TOPOLOGY_ZONE
                     else o.capacity_type == value)
                    for o in it.offerings if o.available), it.name
            else:
                assert it.requirements.get(key).has(value), it.name
        # cheapest compatible heads the list
        def best_price(it):
            return min((o.price for o in it.offerings
                        if o.available
                        and (key != api_labels.LABEL_TOPOLOGY_ZONE
                             or o.zone == value)
                        and (key != api_labels.CAPACITY_TYPE_LABEL_KEY
                             or o.capacity_type == value)), default=float("inf"))
        prices = [best_price(it) for it in opts]
        assert prices[0] == min(prices)

    @pytest.mark.parametrize("key,value", CASES)
    def test_pod_constraint(self, key, value):
        its = construct_instance_types()[:64]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={key: value})])
        self._assert_cheapest_compatible(r, key, value)

    @pytest.mark.parametrize("key,value", CASES)
    def test_pool_constraint(self, key, value):
        its = construct_instance_types()[:64]
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(key, "In", (value,))])
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        self._assert_cheapest_compatible(r, key, value)

    def test_combined_pool_and_pod_constraints(self):
        """instance_selection_test.go:331-462: pool pins capacity type, the
        pod pins zone — both must hold simultaneously."""
        its = construct_instance_types()[:64]
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(api_labels.CAPACITY_TYPE_LABEL_KEY, "In",
                                    (api_labels.CAPACITY_TYPE_SPOT,))])
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={
            api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-b"})])
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        assert nc.requirements.get(
            api_labels.CAPACITY_TYPE_LABEL_KEY).has("spot")
        assert nc.requirements.get(
            api_labels.LABEL_TOPOLOGY_ZONE).has("test-zone-b")

    def test_no_match_pod_arch_fails(self):
        """instance_selection_test.go:463-482."""
        its = construct_instance_types()[:32]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m",
                              node_selector={api_labels.LABEL_ARCH: "arm"})])
        assert r.pod_errors and not r.new_nodeclaims

    def test_no_match_pool_arch_pod_zone_fails(self):
        """instance_selection_test.go:512-545: pool restricts to a zone the
        requested arch has no capacity in? Here: pool pins an arch value the
        catalog lacks entirely."""
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(api_labels.LABEL_ARCH, "In", ("s390x",))])
        its = construct_instance_types()[:32]
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={
            api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-b"})])
        assert r.pod_errors and not r.new_nodeclaims

    def test_large_pod_selects_instance_with_enough_resources(self):
        """instance_selection_test.go:546-599."""
        its = construct_instance_types()[:64]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="7", memory="8Gi")])
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        for it in nc.instance_type_options:
            assert it.allocatable()["cpu"] >= 7000, it.name
