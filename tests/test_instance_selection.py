"""Instance selection, minValues, Gt/Lt, and relaxation behaviors
(reference shapes: instance_selection_test.go + suite_test.go scenarios).

The vector battery at the bottom runs each selection scenario against BOTH
solvers — the host oracle and the tensor path — since instance selection
is the component where the two are most likely to drift (price ordering,
offering admission, minValues floors)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider.kwok import (GROUP_INSTANCE_FAMILY,
                                              construct_instance_types,
                                              make_instance_type, price_for)
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.scheduling.requirement import GT, IN, LT, Requirement
from karpenter_tpu.scheduling.requirements import Requirements

from factories import (make_nodepool, make_pod, make_pods, make_scheduler,
                       spread_zone)

PATHS = ("host", "tensor")


def solve_on(path, pools, its, pods, **kw):
    """Solve on the named path; returns (results, tensor_scheduler_or_None)."""
    if path == "host":
        return make_scheduler(pools, its, pods, **kw).solve(pods), None
    ts = TensorScheduler(pools, {p.name: list(its) for p in pools}, **kw)
    return ts.solve(pods), ts


class _MinValuesReq:
    def __init__(self, key, operator, values, min_values):
        self.key = key
        self.operator = operator
        self.values = tuple(values)
        self.min_values = min_values


class TestInstanceSelection:
    def test_cheapest_type_heads_launch_list(self):
        its = construct_instance_types()[:48]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        opts = r.new_nodeclaims[0].instance_type_options
        prices = [min(o.price for o in it.offerings) for it in opts]
        assert prices[0] == min(prices)

    def test_on_demand_selector_excludes_spot_pricing(self):
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={
            api_labels.CAPACITY_TYPE_LABEL_KEY:
                api_labels.CAPACITY_TYPE_ON_DEMAND})])
        assert not r.pod_errors
        reqs = r.new_nodeclaims[0].requirements
        ct = reqs.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        assert ct.has(api_labels.CAPACITY_TYPE_ON_DEMAND)
        assert not ct.has(api_labels.CAPACITY_TYPE_SPOT)

    def test_gt_requirement_on_numeric_label(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("company.io/generation", "Gt", ("3",))])
        its = []
        for gen in (2, 4):
            it = make_instance_type(4, 2, api_labels.ARCHITECTURE_AMD64, "linux")
            it.name = f"gen{gen}-4x"
            it.requirements.add(Requirement(api_labels.LABEL_INSTANCE_TYPE,
                                            IN, [it.name]))
            it.requirements.add(Requirement("company.io/generation", IN,
                                            [str(gen)]))
            its.append(it)
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        names = {it.name for it in r.new_nodeclaims[0].instance_type_options}
        assert names == {"gen4-4x"}

    def test_lt_requirement(self):
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("company.io/generation", "Lt", ("3",))])
        its = []
        for gen in (2, 4):
            it = make_instance_type(4, 2, api_labels.ARCHITECTURE_AMD64, "linux")
            it.name = f"gen{gen}-4x"
            it.requirements.add(Requirement(api_labels.LABEL_INSTANCE_TYPE,
                                            IN, [it.name]))
            it.requirements.add(Requirement("company.io/generation", IN,
                                            [str(gen)]))
            its.append(it)
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        names = {it.name for it in r.new_nodeclaims[0].instance_type_options}
        assert names == {"gen2-4x"}

    def test_min_values_keeps_flexibility(self):
        """NodeSelectorRequirementWithMinValues: launch list must retain >= N
        distinct instance types (nodeclaim.go SatisfiesMinValues)."""
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 5)])
        its = construct_instance_types()[:48]
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        nc = r.new_nodeclaims[0]
        assert len(nc.instance_type_options) >= 5
        r.truncate_instance_types(10)
        assert len(r.new_nodeclaims[0].instance_type_options) <= 10
        assert len(r.new_nodeclaims[0].instance_type_options) >= 5

    def test_min_values_unsatisfiable_errors(self):
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 500)])
        its = construct_instance_types()[:24]
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        assert r.pod_errors


class TestRelaxation:
    def test_preferred_zone_honored_when_possible(self):
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", preferred_affinity=[
            (1, [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                         "In", ("test-zone-b",))])])])
        assert not r.pod_errors
        zone = r.new_nodeclaims[0].requirements.get(
            api_labels.LABEL_TOPOLOGY_ZONE)
        assert zone.has("test-zone-b") and zone.values_list() == ["test-zone-b"]

    def test_impossible_preferred_zone_relaxed(self):
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", preferred_affinity=[
            (1, [NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                         "In", ("zone-on-the-moon",))])])])
        assert not r.pod_errors

    def test_schedule_anyway_spread_relaxes(self):
        from karpenter_tpu.api.objects import (LabelSelector,
                                               TopologySpreadConstraint)
        its = construct_instance_types()[:24]
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE, "In",
                                    ("test-zone-a",))])
        s = make_scheduler([pool], its, [])
        # spread over zones is impossible with one zone; ScheduleAnyway lets
        # all pods land in zone-a
        pods = make_pods(4, cpu="500m", labels={"app": "x"}, spread=[
            TopologySpreadConstraint(
                topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=1,
                label_selector=LabelSelector(match_labels={"app": "x"}),
                when_unsatisfiable="ScheduleAnyway")])
        r = s.solve(pods)
        assert not r.pod_errors


class TestExistingNodeOrder:
    def test_initialized_nodes_fill_first(self):
        from karpenter_tpu.api.nodeclaim import NodeClaim
        from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta
        from karpenter_tpu.state.statenode import StateNode
        from karpenter_tpu.utils import resources as res

        def node(name, initialized):
            labels = {api_labels.LABEL_HOSTNAME: name,
                      api_labels.NODEPOOL_LABEL_KEY: "default"}
            if initialized:
                labels[api_labels.NODE_INITIALIZED_LABEL_KEY] = "true"
            alloc = res.parse_list({"cpu": "4", "memory": "8Gi", "pods": "110"})
            return StateNode(node=Node(
                metadata=ObjectMeta(name=name, namespace="", labels=labels),
                spec=NodeSpec(provider_id=f"t://{name}"),
                status=NodeStatus(capacity=dict(alloc), allocatable=alloc)))

        uninit = node("a-uninit", False)
        init = node("b-init", True)
        its = construct_instance_types()[:24]
        s = make_scheduler([make_nodepool()], its, [],
                           state_nodes=[uninit, init])
        r = s.solve([make_pod(cpu="500m")])
        assert not r.pod_errors
        placed = [en for en in r.existing_nodes if en.pods]
        assert [en.name for en in placed] == ["b-init"]


class TestCheapestCompatibleMatrix:
    """instance_selection_test.go:87-462: under any single constraint —
    from the pool's requirements or the pod's node selector — the launch
    list is headed by the cheapest COMPATIBLE type and every option
    satisfies the constraint."""

    CASES = [
        (api_labels.LABEL_ARCH, api_labels.ARCHITECTURE_AMD64),
        (api_labels.LABEL_ARCH, api_labels.ARCHITECTURE_ARM64),
        (api_labels.LABEL_OS, "linux"),
        (api_labels.LABEL_OS, "windows"),
        (api_labels.LABEL_TOPOLOGY_ZONE, "test-zone-b"),
        (api_labels.CAPACITY_TYPE_LABEL_KEY, api_labels.CAPACITY_TYPE_SPOT),
        (api_labels.CAPACITY_TYPE_LABEL_KEY,
         api_labels.CAPACITY_TYPE_ON_DEMAND),
    ]

    def _assert_cheapest_compatible(self, r, key, value):
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        opts = nc.instance_type_options
        assert opts
        reqs = nc.requirements
        assert reqs.get(key).has(value)
        # every option admits the constraint (zone/ct live on offerings)
        for it in opts:
            if key in (api_labels.LABEL_TOPOLOGY_ZONE,
                       api_labels.CAPACITY_TYPE_LABEL_KEY):
                assert any(
                    (o.zone == value if key == api_labels.LABEL_TOPOLOGY_ZONE
                     else o.capacity_type == value)
                    for o in it.offerings if o.available), it.name
            else:
                assert it.requirements.get(key).has(value), it.name
        # cheapest compatible heads the list
        def best_price(it):
            return min((o.price for o in it.offerings
                        if o.available
                        and (key != api_labels.LABEL_TOPOLOGY_ZONE
                             or o.zone == value)
                        and (key != api_labels.CAPACITY_TYPE_LABEL_KEY
                             or o.capacity_type == value)), default=float("inf"))
        prices = [best_price(it) for it in opts]
        assert prices[0] == min(prices)

    @pytest.mark.parametrize("key,value", CASES)
    def test_pod_constraint(self, key, value):
        its = construct_instance_types()[:64]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={key: value})])
        self._assert_cheapest_compatible(r, key, value)

    @pytest.mark.parametrize("key,value", CASES)
    def test_pool_constraint(self, key, value):
        its = construct_instance_types()[:64]
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(key, "In", (value,))])
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m")])
        self._assert_cheapest_compatible(r, key, value)

    def test_combined_pool_and_pod_constraints(self):
        """instance_selection_test.go:331-462: pool pins capacity type, the
        pod pins zone — both must hold simultaneously."""
        its = construct_instance_types()[:64]
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(api_labels.CAPACITY_TYPE_LABEL_KEY, "In",
                                    (api_labels.CAPACITY_TYPE_SPOT,))])
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={
            api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-b"})])
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        assert nc.requirements.get(
            api_labels.CAPACITY_TYPE_LABEL_KEY).has("spot")
        assert nc.requirements.get(
            api_labels.LABEL_TOPOLOGY_ZONE).has("test-zone-b")

    def test_no_match_pod_arch_fails(self):
        """instance_selection_test.go:463-482."""
        its = construct_instance_types()[:32]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="500m",
                              node_selector={api_labels.LABEL_ARCH: "arm"})])
        assert r.pod_errors and not r.new_nodeclaims

    def test_no_match_pool_arch_pod_zone_fails(self):
        """instance_selection_test.go:512-545: pool restricts to a zone the
        requested arch has no capacity in? Here: pool pins an arch value the
        catalog lacks entirely."""
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement(api_labels.LABEL_ARCH, "In", ("s390x",))])
        its = construct_instance_types()[:32]
        s = make_scheduler([pool], its, [])
        r = s.solve([make_pod(cpu="500m", node_selector={
            api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-b"})])
        assert r.pod_errors and not r.new_nodeclaims

    def test_large_pod_selects_instance_with_enough_resources(self):
        """instance_selection_test.go:546-599."""
        its = construct_instance_types()[:64]
        s = make_scheduler([make_nodepool()], its, [])
        r = s.solve([make_pod(cpu="7", memory="8Gi")])
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        for it in nc.instance_type_options:
            assert it.allocatable()["cpu"] >= 7000, it.name


def _best_price(it, captype=None, zone=None):
    return min((o.price for o in it.offerings
                if o.available
                and (captype is None or o.capacity_type == captype)
                and (zone is None or o.zone == zone)), default=float("inf"))


def _gen_catalog(gens):
    """One 4-cpu amd64/linux type per generation value, distinguishable by a
    numeric company.io/generation label (the reference's Gt/Lt vectors)."""
    its = []
    for gen in gens:
        it = make_instance_type(4, 2, api_labels.ARCHITECTURE_AMD64, "linux")
        it.name = f"gen{gen}-4x"
        it.requirements.add(Requirement(api_labels.LABEL_INSTANCE_TYPE,
                                        IN, [it.name]))
        it.requirements.add(Requirement("company.io/generation", IN,
                                        [str(gen)]))
        its.append(it)
    return its


@pytest.mark.parametrize("path", PATHS)
class TestInstanceSelectionVectors:
    """instance_selection_test.go vector battery, both solve paths."""

    def test_spot_offering_heads_unrestricted_price_order(self, path):
        """Spot is priced at 0.7x on-demand in the kwok catalog; with no
        capacity-type constraint the launch head must be cheapest by its
        spot offering (instance_selection_test.go capacity-type ordering)."""
        its = construct_instance_types()[:48]
        r, _ = solve_on(path, [make_nodepool()], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        opts = r.new_nodeclaims[0].instance_type_options
        prices = [_best_price(it) for it in opts]
        assert prices[0] == min(prices)
        head = opts[0]
        cheapest = min(head.offerings, key=lambda o: o.price)
        assert cheapest.capacity_type == api_labels.CAPACITY_TYPE_SPOT

    def test_on_demand_pool_prices_by_on_demand_offerings(self, path):
        its = construct_instance_types()[:48]
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            api_labels.CAPACITY_TYPE_LABEL_KEY, "In",
            (api_labels.CAPACITY_TYPE_ON_DEMAND,))])
        r, _ = solve_on(path, [pool], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        [nc] = r.new_nodeclaims
        ct = nc.requirements.get(api_labels.CAPACITY_TYPE_LABEL_KEY)
        assert ct.values_list() == [api_labels.CAPACITY_TYPE_ON_DEMAND]
        opts = nc.instance_type_options
        prices = [_best_price(it, captype=api_labels.CAPACITY_TYPE_ON_DEMAND)
                  for it in opts]
        assert prices[0] == min(prices)

    def test_spot_unavailable_falls_back_to_on_demand(self, path):
        """Capacity-type fallback: with every spot offering unavailable the
        launch list orders (and launches) by on-demand offerings."""
        its = construct_instance_types()[:24]
        for it in its:
            for o in it.offerings:
                if o.capacity_type == api_labels.CAPACITY_TYPE_SPOT:
                    o.available = False
        r, _ = solve_on(path, [make_nodepool()], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        opts = r.new_nodeclaims[0].instance_type_options
        assert opts
        for it in opts:
            avail = [o for o in it.offerings if o.available]
            assert avail and all(
                o.capacity_type == api_labels.CAPACITY_TYPE_ON_DEMAND
                for o in avail)

    def test_zone_pinned_pod_prices_by_that_zone(self, path):
        """Zone x price: the order must rank by offerings IN the admitted
        zone, not by a cheaper offering elsewhere."""
        its = construct_instance_types()[:48]
        # make zone-b artificially cheap for half the catalog: a zone-a pod
        # must not be ranked by those zone-b prices
        for it in its[::2]:
            for o in it.offerings:
                if o.zone == "test-zone-b":
                    o.price *= 0.1
        r, _ = solve_on(path, [make_nodepool()], its, [make_pod(
            cpu="500m",
            node_selector={api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-a"})])
        assert not r.pod_errors
        opts = r.new_nodeclaims[0].instance_type_options
        prices = [_best_price(it, zone="test-zone-a") for it in opts]
        assert prices[0] == min(prices)

    def test_arch_partition_splits_claims(self, path):
        its = construct_instance_types()[:64]
        pods = (make_pods(3, cpu="500m", node_selector={
                    api_labels.LABEL_ARCH: api_labels.ARCHITECTURE_AMD64})
                + make_pods(3, cpu="500m", node_selector={
                    api_labels.LABEL_ARCH: api_labels.ARCHITECTURE_ARM64}))
        r, _ = solve_on(path, [make_nodepool()], its, pods)
        assert not r.pod_errors
        archs = set()
        for nc in r.new_nodeclaims:
            its_archs = {it.requirements.get(api_labels.LABEL_ARCH)
                         .values_list()[0] for it in nc.instance_type_options}
            assert len(its_archs) == 1, "claim mixes architectures"
            archs |= its_archs
        assert archs == {api_labels.ARCHITECTURE_AMD64,
                         api_labels.ARCHITECTURE_ARM64}

    def test_os_partition_splits_claims(self, path):
        its = construct_instance_types()[:64]
        pods = (make_pods(3, cpu="500m",
                          node_selector={api_labels.LABEL_OS: "linux"})
                + make_pods(3, cpu="500m",
                            node_selector={api_labels.LABEL_OS: "windows"}))
        r, _ = solve_on(path, [make_nodepool()], its, pods)
        assert not r.pod_errors
        oses = set()
        for nc in r.new_nodeclaims:
            its_os = {it.requirements.get(api_labels.LABEL_OS)
                      .values_list()[0] for it in nc.instance_type_options}
            assert len(its_os) == 1, "claim mixes operating systems"
            oses |= its_os
        assert oses == {"linux", "windows"}

    def test_not_in_zone_pool_excludes_zone(self, path):
        its = construct_instance_types()[:24]
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            api_labels.LABEL_TOPOLOGY_ZONE, "NotIn", ("test-zone-b",))])
        r, _ = solve_on(path, [pool], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        zone = r.new_nodeclaims[0].requirements.get(
            api_labels.LABEL_TOPOLOGY_ZONE)
        assert not zone.has("test-zone-b")
        assert zone.has("test-zone-a")

    def test_gt_lt_window_selects_interior_generations(self, path):
        its = _gen_catalog((1, 2, 3, 4, 5))
        pool = make_nodepool(requirements=[
            NodeSelectorRequirement("company.io/generation", "Gt", ("1",)),
            NodeSelectorRequirement("company.io/generation", "Lt", ("5",))])
        r, _ = solve_on(path, [pool], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        names = {it.name for it in r.new_nodeclaims[0].instance_type_options}
        assert names == {"gen2-4x", "gen3-4x", "gen4-4x"}

    def test_instance_type_selector_pins_single_type(self, path):
        its = construct_instance_types()[:24]
        target = its[7].name
        r, _ = solve_on(path, [make_nodepool()], its, [make_pod(
            cpu="500m",
            node_selector={api_labels.LABEL_INSTANCE_TYPE: target})])
        assert not r.pod_errors
        assert [it.name for it in
                r.new_nodeclaims[0].instance_type_options] == [target]

    def test_not_in_instance_type_excludes_it(self, path):
        its = construct_instance_types()[:24]
        excluded = {its[0].name, its[1].name}
        pool = make_nodepool(requirements=[NodeSelectorRequirement(
            api_labels.LABEL_INSTANCE_TYPE, "NotIn", tuple(excluded))])
        r, _ = solve_on(path, [pool], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        names = {it.name for it in r.new_nodeclaims[0].instance_type_options}
        assert not (names & excluded)
        assert names

    def test_fully_unavailable_type_never_selected(self, path):
        its = construct_instance_types()[:24]
        dead = its[0]
        for o in dead.offerings:
            o.available = False
        r, _ = solve_on(path, [make_nodepool()], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        assert dead.name not in {
            it.name for it in r.new_nodeclaims[0].instance_type_options}

    def test_oversized_pod_fails_everywhere(self, path):
        its = construct_instance_types()[:24]
        r, _ = solve_on(path, [make_nodepool()], its,
                        [make_pod(cpu="9999", memory="9999Gi")])
        assert r.pod_errors and not r.new_nodeclaims

    def test_min_values_with_truncation_keeps_floor(self, path):
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 30)])
        its = construct_instance_types()[:64]
        r, _ = solve_on(path, [pool], its, [make_pod(cpu="500m")])
        assert not r.pod_errors
        assert len(r.new_nodeclaims[0].instance_type_options) >= 30
        r.truncate_instance_types(35)
        opts = r.new_nodeclaims[0].instance_type_options
        assert 30 <= len(opts) <= 35


class TestMinValuesPackingPressure:
    """The round-6 packer enforces the minValues floor DURING packing: the
    host oracle refuses per-pod adds that would drop a claim below the
    floor (scheduler.py:159-162), so accumulated load must never narrow a
    tensor claim's launch list under it either."""

    def test_tensor_claims_keep_floor_under_load(self):
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 20)])
        its = construct_instance_types()[:48]
        pods = make_pods(400, cpu="500m", memory="512Mi",
                         labels={"app": "mv"})
        r, ts = solve_on("tensor", [pool], its, pods)
        assert ts.fallback_reason == "", ts.fallback_reason
        assert not r.pod_errors
        assert len(r.new_nodeclaims) > 1, "load should need several nodes"
        for nc in r.new_nodeclaims:
            assert len(nc.instance_type_options) >= 20, \
                (f"claim narrowed below the minValues floor: "
                 f"{len(nc.instance_type_options)}")

    def test_host_oracle_agrees_on_floor_under_load(self):
        pool = make_nodepool(requirements=[
            _MinValuesReq(api_labels.LABEL_INSTANCE_TYPE, "Exists", (), 20)])
        its = construct_instance_types()[:48]
        pods = make_pods(400, cpu="500m", memory="512Mi",
                         labels={"app": "mv"})
        r, _ = solve_on("host", [pool], its, pods)
        assert not r.pod_errors
        for nc in r.new_nodeclaims:
            assert len(nc.instance_type_options) >= 20

    def test_min_values_on_other_key_demotes_to_host_path(self):
        """Distinct-value floors on non-instance-type keys need per-key
        value counting; the tensor front end hands those to the oracle
        rather than silently ignoring the floor."""
        pool = make_nodepool(requirements=[
            _MinValuesReq(GROUP_INSTANCE_FAMILY, "Exists", (), 2)])
        its = construct_instance_types()[:48]
        r, ts = solve_on("tensor", [pool], its, [make_pod(cpu="500m")])
        assert ts.fallback_reason != "", \
            "expected a host fallback for non-instance-type minValues"
        assert not r.pod_errors
        families = set()
        for it in r.new_nodeclaims[0].instance_type_options:
            families |= set(it.requirements.get(
                GROUP_INSTANCE_FAMILY).values_list())
        assert len(families) >= 2
