"""Tensor packer vs host oracle scheduler: node-count parity on scenario
batteries including the reference benchmark's diverse pod mix
(scheduling_benchmark_test.go:233-247)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import NodeSelectorRequirement, Taint, Toleration
from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import (affinity_term, make_nodepool, make_pod, make_pods,
                       make_scheduler, spread_hostname, spread_zone)


def tensor_solve(nodepools, its, pods, **kw):
    if not isinstance(its, dict):
        its = {np.name: list(its) for np in nodepools}
    ts = TensorScheduler(nodepools, its, force_tensor=True, **kw)
    results = ts.solve(pods)
    assert ts.fallback_reason == "", f"unexpected fallback: {ts.fallback_reason}"
    return results


def host_solve(nodepools, its, pods, **kw):
    s = make_scheduler(nodepools, its, pods, **kw)
    return s.solve(pods)


def both(pods_fn, nodepools=None, its=None):
    nodepools = nodepools or [make_nodepool()]
    its = its if its is not None else kwok.construct_instance_types()
    t = tensor_solve(nodepools, its, pods_fn())
    h = host_solve(nodepools, its, pods_fn())
    return t, h


class TestPlainParity:
    def test_single_group(self):
        t, h = both(lambda: make_pods(50, cpu="500m", memory="512Mi"))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)

    def test_mixed_sizes(self):
        def pods():
            return (make_pods(20, cpu="2", memory="4Gi")
                    + make_pods(30, cpu="500m", memory="1Gi")
                    + make_pods(10, cpu="100m", memory="128Mi"))
        t, h = both(pods)
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        th, hh = len(t.new_nodeclaims), len(h.new_nodeclaims)
        assert abs(th - hh) <= max(1, round(0.02 * hh)), (th, hh)

    def test_unschedulable(self):
        t, h = both(lambda: make_pods(3, cpu="1000"))
        assert len(t.pod_errors) == len(h.pod_errors) == 3

    def test_tainted_pool_toleration(self):
        np_ = make_nodepool(taints=[Taint(key="dedicated", value="x")])
        tol = [Toleration(key="dedicated", operator="Exists")]
        t, h = both(lambda: make_pods(10, tolerations=tol), nodepools=[np_])
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)

    def test_zone_selector(self):
        def pods():
            return make_pods(12, cpu="1",
                             node_selector={api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-c"})
        t, h = both(pods)
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)
        for nc in t.new_nodeclaims:
            assert nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values == {"test-zone-c"}


class TestTopologyParity:
    def test_zonal_spread(self):
        t, h = both(lambda: make_pods(16, labels={"app": "demo"}, spread=[spread_zone()]))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        t_zones = sorted(nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values_list()[0]
                         for nc in t.new_nodeclaims)
        h_zones = sorted(nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values_list()[0]
                         for nc in h.new_nodeclaims)
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)
        assert t_zones == h_zones

    def test_hostname_spread(self):
        t, h = both(lambda: make_pods(6, labels={"app": "demo"},
                                      spread=[spread_hostname(max_skew=1)]))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 6

    def test_hostname_anti_affinity(self):
        t, h = both(lambda: make_pods(
            7, labels={"app": "demo"},
            pod_anti_affinity=[affinity_term(api_labels.LABEL_HOSTNAME)]))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 7

    def test_zonal_affinity(self):
        t, h = both(lambda: make_pods(
            9, labels={"app": "demo"},
            pod_affinity=[affinity_term(api_labels.LABEL_TOPOLOGY_ZONE)]))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)
        t_zones = {nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE).values_list()[0]
                   for nc in t.new_nodeclaims}
        assert len(t_zones) == 1

    def test_zonal_anti_affinity_late_committal(self):
        t, h = both(lambda: make_pods(
            3, labels={"app": "demo"},
            pod_anti_affinity=[affinity_term(api_labels.LABEL_TOPOLOGY_ZONE)]))
        assert len(t.pod_errors) == len(h.pod_errors) == 2
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 1

    def test_hostname_affinity_single_node(self):
        t, h = both(lambda: make_pods(
            5, cpu="100m", labels={"app": "demo"},
            pod_affinity=[affinity_term(api_labels.LABEL_HOSTNAME)]))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 1


class TestBenchmarkMixParity:
    """The reference benchmark's diverse mix: 1/6 each generic, zone-spread,
    host-spread, host-affinity, zone-affinity, host-anti-affinity."""

    def _mix(self, n_per_kind):
        pods = []
        pods += make_pods(n_per_kind, cpu="1", memory="2Gi")
        pods += make_pods(n_per_kind, cpu="500m", memory="1Gi",
                          labels={"app": "spread-z"}, spread=[spread_zone(value="spread-z")])
        pods += make_pods(n_per_kind, cpu="500m", memory="1Gi",
                          labels={"app": "spread-h"}, spread=[spread_hostname(value="spread-h")])
        pods += make_pods(n_per_kind, cpu="250m", memory="512Mi",
                          labels={"app": "aff-h"},
                          pod_affinity=[affinity_term(api_labels.LABEL_HOSTNAME,
                                                      value="aff-h")])
        pods += make_pods(n_per_kind, cpu="250m", memory="512Mi",
                          labels={"app": "aff-z"},
                          pod_affinity=[affinity_term(api_labels.LABEL_TOPOLOGY_ZONE,
                                                      value="aff-z")])
        pods += make_pods(n_per_kind, cpu="250m", memory="512Mi",
                          labels={"app": "anti-h"},
                          pod_anti_affinity=[affinity_term(api_labels.LABEL_HOSTNAME,
                                                           value="anti-h")])
        return pods

    @pytest.mark.parametrize("n", [6, 18])
    def test_mix_parity(self, n):
        its = kwok.construct_instance_types()
        np_ = [make_nodepool()]
        t = tensor_solve(np_, its, self._mix(n))
        h = host_solve(np_, its, self._mix(n))
        assert len(t.pod_errors) == len(h.pod_errors), (t.pod_errors, h.pod_errors)
        th, hh = len(t.new_nodeclaims), len(h.new_nodeclaims)
        # BASELINE.md north star: within 2% of the oracle (was 5% before the
        # cohort zone-commit + per-node-cap overfill fixes, round 5)
        assert abs(th - hh) <= max(1, round(0.02 * hh)), (th, hh)


class TestInstanceTypePruning:
    def test_cohort_drops_outgrown_instance_types(self):
        """nodeclaim.go:108-117 parity: an instance type that fit the first
        pod must leave the claim's option list once the accumulated load
        outgrows it — a phantom small option poisons price ordering and the
        consolidation price filter (the launch would pick an undersized
        node)."""
        its = kwok.construct_instance_types()
        t = tensor_solve([make_nodepool()], its,
                         make_pods(2, cpu="1500m", memory="256Mi"))
        assert not t.pod_errors
        for nc in t.new_nodeclaims:
            total = sum(p.requests().get("cpu", 0) for p in nc.pods)
            for it in nc.instance_type_options:
                assert it.allocatable().get("cpu", 0) >= total, \
                    (it.name, total)

    def test_oversized_daemon_overhead_excludes_type(self, ):
        """A daemonset whose overhead outgrows every instance type in a
        resource the PODS never request must make those types infeasible —
        the host folds daemon requests into the claim's request vector, so
        both paths must error the pods identically (not crash)."""
        its = kwok.construct_instance_types()[:24]
        daemon = make_pod(cpu="100m", memory="64Mi")
        daemon.container_requests[0]["ephemeral-storage"] = \
            100 * 1024**3 * 1000  # 100Gi scaled: exceeds every type
        pods = make_pods(4, cpu="250m")
        t = tensor_solve([make_nodepool()], its, pods,
                         daemonset_pods=[daemon])
        h = host_solve([make_nodepool()], its, pods,
                       daemonset_pods=[daemon])
        assert len(t.pod_errors) == len(h.pod_errors) == 4
        assert not t.new_nodeclaims and not h.new_nodeclaims

    def test_limit_filtered_fill_keeps_viable_options(self):
        """With nodepool limits excluding the max-capacity type, the fill
        must be sized from the limit-filtered set — never producing a claim
        whose pods outgrow every surviving option."""
        its = kwok.construct_instance_types()
        pool = make_nodepool(limits={"cpu": "4"})
        t = tensor_solve([pool], its, make_pods(16, cpu="250m"))
        for nc in t.new_nodeclaims:
            assert nc.pods and nc.instance_type_options
            total = sum(p.requests().get("cpu", 0) for p in nc.pods)
            assert any(it.allocatable().get("cpu", 0) >= total
                       for it in nc.instance_type_options)
        h = host_solve([make_nodepool(limits={"cpu": "4"})], its,
                       make_pods(16, cpu="250m"))
        assert len(t.pod_errors) == len(h.pod_errors)


class TestDeterminism:
    def test_identical_batches_solve_identically(self):
        """Two solves of the same batch (fresh scheduler each) must make
        byte-identical decisions — the disruption validator depends on
        re-simulation stability (validation.go:83-215), and tie-breaks are
        deterministic by design (domain-name order, price-name lexsort)."""
        its = kwok.construct_instance_types()

        def batch():
            return (make_pods(40, cpu="500m", memory="512Mi")
                    + make_pods(12, labels={"app": "s"},
                                spread=[spread_zone(key="app", value="s")])
                    + make_pods(8, labels={"app": "a"},
                                pod_anti_affinity=[
                                    affinity_term(api_labels.LABEL_HOSTNAME,
                                                  value="a")]))

        def key(results):
            return sorted(
                (nc.template.nodepool_name,
                 tuple(sorted(nc.requirements.get(
                     api_labels.LABEL_TOPOLOGY_ZONE).values)),
                 tuple(it.name for it in nc.instance_type_options),
                 len(nc.pods))
                for nc in results.new_nodeclaims)

        r1 = tensor_solve([make_nodepool()], its, batch())
        r2 = tensor_solve([make_nodepool()], its, batch())
        assert key(r1) == key(r2)
        assert len(r1.pod_errors) == len(r2.pod_errors)


class TestFallback:
    def test_unsupported_topology_falls_back(self):
        # region-key spread isn't kernel-supported -> host path
        from karpenter_tpu.api.objects import LabelSelector, TopologySpreadConstraint
        pods = [make_pod(labels={"app": "x"}, spread=[TopologySpreadConstraint(
            topology_key=api_labels.LABEL_TOPOLOGY_REGION,
            label_selector=LabelSelector(match_labels={"app": "x"}))])]
        its = {"default": kwok.construct_instance_types()}
        ts = TensorScheduler([make_nodepool()], its)
        results = ts.solve(pods)
        assert ts.fallback_reason != ""
        assert results.pod_errors == {}

    def test_cross_group_selector_falls_back(self):
        pods = (make_pods(2, labels={"app": "x"}, spread=[spread_zone(key="app", value="x")])
                + make_pods(2, cpu="200m", labels={"app": "x", "extra": "y"}))
        its = {"default": kwok.construct_instance_types()}
        ts = TensorScheduler([make_nodepool()], its)
        ts.solve(pods)
        assert ts.fallback_reason != ""
