"""Candidate-gating corpus, ported from
/root/reference/pkg/controllers/disruption/suite_test.go:635-1660 — the
NewCandidate eligibility tables (do-not-disrupt across pod classes, PDB
exemptions, TerminationGracePeriod x disruption-class interplay, budget
counting) plus the disruption-cost ordering rules (:781-852). Go ranges
cited per test; candidates come from the expectations harness and are
probed through disruption.helpers.get_candidates directly.
"""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import COND_INSTANCE_TERMINATING
from karpenter_tpu.api.objects import OwnerReference
from karpenter_tpu.disruption.helpers import (build_disruption_budget_mapping,
                                              get_candidates)
from karpenter_tpu.utils import disruption as disruption_utils

from expectations import (OD, SPOT, bind_pod, cheapest_instance,
                          consolidation_nodepool, make_env,
                          make_nodeclaim_and_node, make_pdb)
from factories import make_nodepool, make_pod

DND = api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY


def candidates(env, disruption_class="graceful"):
    return get_candidates(env.cluster, env.provisioner, lambda c: True,
                          disruption_class=disruption_class,
                          recorder=env.recorder)


def _owned_by(kind, name="owner"):
    return [OwnerReference(kind=kind, name=name, uid=f"{kind}-{name}",
                           controller=True)]


class TestDoNotDisruptPodClasses:
    """suite_test.go:853-1214."""

    def _node_with(self, env, pod):
        nc, node = make_nodeclaim_and_node(
            env, instance_type=cheapest_instance(OD))
        bind_pod(env, node, pod)
        env.clock.step(60)
        return nc, node

    def test_plain_dnd_pod_blocks_graceful(self):
        """:853-880."""
        env = make_env()
        p = make_pod(cpu="100m")
        p.metadata.annotations[DND] = "true"
        self._node_with(env, p)
        assert not candidates(env)

    def test_dnd_mirror_pod_blocks(self):
        """:881-918: 'We will allow Mirror Pods ... to block disruption
        using this annotation' (statenode.go:221-223)."""
        env = make_env()
        p = make_pod(cpu="100m")
        p.metadata.annotations[DND] = "true"
        p.metadata.owner_refs = _owned_by("Node")
        self._node_with(env, p)
        assert not candidates(env)

    def test_dnd_daemonset_pod_blocks(self):
        """:919-957."""
        env = make_env()
        p = make_pod(cpu="100m")
        p.metadata.annotations[DND] = "true"
        p.metadata.owner_refs = _owned_by("DaemonSet")
        self._node_with(env, p)
        assert not candidates(env)

    def test_dnd_terminating_pod_does_not_block(self):
        """:1147-1176: a pod already terminating isn't active — its
        annotation is moot."""
        env = make_env()
        p = make_pod(cpu="100m")
        p.metadata.annotations[DND] = "true"
        nc, node = self._node_with(env, p)
        live = env.store.get(type(p), p.metadata.name, p.metadata.namespace)
        live.metadata.deletion_timestamp = env.clock.now()
        env.store.update(live)
        assert len(candidates(env)) == 1

    @pytest.mark.parametrize("phase", ["Succeeded", "Failed"])
    def test_dnd_terminal_pod_does_not_block(self, phase):
        """:1177-1214."""
        env = make_env()
        p = make_pod(cpu="100m")
        p.metadata.annotations[DND] = "true"
        nc, node = self._node_with(env, p)
        live = env.store.get(type(p), p.metadata.name, p.metadata.namespace)
        live.status.phase = phase
        env.store.update(live)
        assert len(candidates(env)) == 1

    def test_dnd_node_annotation_blocks(self):
        """:1215-1237 (validate_node_disruptable)."""
        env = make_env()
        make_nodeclaim_and_node(
            env, instance_type=cheapest_instance(OD),
            annotations={DND: "true"})
        env.clock.step(60)
        assert not candidates(env)


class TestPDBPodClasses:
    """suite_test.go:1238-1513."""

    def _guarded_node(self, env, owner_kind=None, phase=None,
                      terminating=False):
        nc, node = make_nodeclaim_and_node(
            env, instance_type=cheapest_instance(OD))
        p = make_pod(cpu="100m", labels={"app": "pdb-guard"})
        if owner_kind:
            p.metadata.owner_refs = _owned_by(owner_kind)
        bind_pod(env, node, p)
        make_pdb(env, {"app": "pdb-guard"}, max_unavailable="0")
        if phase or terminating:
            live = env.store.get(type(p), p.metadata.name,
                                 p.metadata.namespace)
            if phase:
                live.status.phase = phase
            if terminating:
                live.metadata.deletion_timestamp = env.clock.now()
            env.store.update(live)
        env.clock.step(60)
        return nc, node

    def test_blocking_pdb_blocks(self):
        """:1238-1273."""
        env = make_env()
        self._guarded_node(env)
        assert not candidates(env)

    def test_blocking_pdb_on_daemonset_pod_blocks(self):
        """:1274-1320: daemonset pods are NOT PDB-exempt."""
        env = make_env()
        self._guarded_node(env, owner_kind="DaemonSet")
        assert not candidates(env)

    def test_blocking_pdb_on_mirror_pod_does_not_block(self):
        """:1321-1366: mirror pods are exempt from PDB gating."""
        env = make_env()
        self._guarded_node(env, owner_kind="Node")
        assert len(candidates(env)) == 1

    def test_blocking_pdb_on_terminal_pod_does_not_block(self):
        """:1432-1475."""
        env = make_env()
        self._guarded_node(env, phase="Succeeded")
        assert len(candidates(env)) == 1

    def test_blocking_pdb_on_terminating_pod_does_not_block(self):
        """:1476-1513."""
        env = make_env()
        self._guarded_node(env, terminating=True)
        assert len(candidates(env)) == 1


class TestTGPClassInterplay:
    """suite_test.go:958-1146: TerminationGracePeriod flips do-not-disrupt
    and PDB blockers ONLY for the eventual class."""

    def _tgp_node(self, env, tgp, blocker):
        nc, node = make_nodeclaim_and_node(
            env, instance_type=cheapest_instance(OD))
        if tgp is not None:
            nc.spec.termination_grace_period = tgp
            env.store.update(nc)
        p = make_pod(cpu="100m", labels={"app": "tgp"})
        if blocker == "dnd":
            p.metadata.annotations[DND] = "true"
        bind_pod(env, node, p)
        if blocker == "pdb":
            make_pdb(env, {"app": "tgp"}, max_unavailable="0")
        env.clock.step(60)

    @pytest.mark.parametrize("blocker", ["dnd", "pdb"])
    def test_tgp_unblocks_eventual(self, blocker):
        """:958-1018: TGP set -> eventual-class candidates form despite
        the blocker."""
        env = make_env()
        self._tgp_node(env, 300.0, blocker)
        assert len(candidates(env, disruption_class="eventual")) == 1

    @pytest.mark.parametrize("blocker", ["dnd", "pdb"])
    def test_tgp_does_not_unblock_graceful(self, blocker):
        """:1019-1083."""
        env = make_env()
        self._tgp_node(env, 300.0, blocker)
        assert not candidates(env, disruption_class="graceful")

    @pytest.mark.parametrize("blocker", ["dnd", "pdb"])
    def test_no_tgp_blocks_eventual_too(self, blocker):
        """:1084-1146."""
        env = make_env()
        self._tgp_node(env, None, blocker)
        assert not candidates(env, disruption_class="eventual")


class TestCandidateEligibility:
    """suite_test.go:1514-1660."""

    def test_node_only_representation_excluded(self):
        """:1514-1532: a bare Node (no claim) is unmanaged."""
        from karpenter_tpu.api.objects import (Node, NodeSpec, NodeStatus,
                                               ObjectMeta)
        from karpenter_tpu.utils import resources as res
        env = make_env()
        alloc = res.parse_list({"cpu": "4", "memory": "8Gi", "pods": "100"})
        env.store.create(Node(
            metadata=ObjectMeta(name="bare", labels={
                api_labels.LABEL_HOSTNAME: "bare"}),
            spec=NodeSpec(provider_id="bare://1"),
            status=NodeStatus(capacity=dict(alloc), allocatable=alloc)))
        env.settle()
        env.clock.step(60)
        assert not candidates(env)

    def test_nominated_candidate_excluded(self):
        """:1552-1572."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=cheapest_instance(OD))
        env.clock.step(60)
        env.cluster.nominate_node_for_pod(node.name, make_pod(cpu="100m"))
        assert not candidates(env)

    def test_uninitialized_candidate_excluded(self):
        """:1616-1635."""
        env = make_env()
        make_nodeclaim_and_node(env, instance_type=cheapest_instance(OD),
                                initialized=False)
        env.clock.step(60)
        assert not candidates(env)

    def test_deleting_candidate_excluded(self):
        """:1573-1594."""
        env = make_env()
        nc, node = make_nodeclaim_and_node(
            env, instance_type=cheapest_instance(OD))
        bind_pod(env, node, cpu="100m")
        env.store.delete(node)
        env.clock.step(60)
        assert not candidates(env)


class TestBudgetCounting:
    """suite_test.go:635-780: which nodes count toward the per-pool
    disruption budget denominator and the disrupting numerator."""

    def test_uninitialized_nodes_not_counted(self):
        """:648-678: a 50% budget over {1 initialized, 1 uninitialized}
        pool allows ceil(50% of 1) = 1, not ceil(50% of 2)."""
        pool = consolidation_nodepool(budgets=("50%",))
        env = make_env(pool)
        make_nodeclaim_and_node(env, instance_type=cheapest_instance(OD))
        make_nodeclaim_and_node(env, instance_type=cheapest_instance(OD),
                                initialized=False)
        env.clock.step(60)
        allowed = build_disruption_budget_mapping(env.cluster,
                                                  "Underutilized")
        assert allowed["default"] == 1

    def test_terminating_condition_claims_not_counted(self):
        """:679-710."""
        env = make_env(consolidation_nodepool(budgets=("100%",)))
        nc0, _ = make_nodeclaim_and_node(env,
                                         instance_type=cheapest_instance(OD))
        nc1, _ = make_nodeclaim_and_node(env,
                                         instance_type=cheapest_instance(OD))
        live = env.store.get(type(nc1), nc1.name)
        live.conditions.set_true(COND_INSTANCE_TERMINATING,
                                 reason="Terminating", now=env.clock.now())
        env.store.update(live)
        env.clock.step(60)
        allowed = build_disruption_budget_mapping(env.cluster,
                                                  "Underutilized")
        assert allowed["default"] == 1  # only nc0 counts

    def test_never_negative(self):
        """:711-731: more disrupting nodes than budget floors at 0."""
        pool = consolidation_nodepool(budgets=("1",))
        env = make_env(pool)
        for _ in range(3):
            nc, node = make_nodeclaim_and_node(
                env, instance_type=cheapest_instance(OD))
        # two nodes marked for deletion consume more than the budget of 1
        sns = list(env.cluster.state_nodes(deep_copy=False))
        env.cluster.mark_for_deletion(sns[0].provider_id, sns[1].provider_id)
        env.clock.step(60)
        allowed = build_disruption_budget_mapping(env.cluster,
                                                  "Underutilized")
        assert allowed["default"] == 0


class TestDisruptionCost:
    """suite_test.go:781-852 over utils/disruption.py eviction_cost."""

    def test_standard_cost_baseline(self):
        p = make_pod(cpu="100m")
        assert disruption_utils.eviction_cost(p) == 1.0

    def test_positive_deletion_cost_raises(self):
        p = make_pod(cpu="100m")
        p.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] \
            = "100"
        assert disruption_utils.eviction_cost(p) > 1.0

    def test_negative_deletion_cost_lowers(self):
        p = make_pod(cpu="100m")
        p.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] \
            = "-100"
        assert disruption_utils.eviction_cost(p) < 1.0

    def test_higher_deletion_cost_costs_more(self):
        lo_, hi = make_pod(cpu="100m"), make_pod(cpu="100m")
        lo_.metadata.annotations[
            "controller.kubernetes.io/pod-deletion-cost"] = "100"
        hi.metadata.annotations[
            "controller.kubernetes.io/pod-deletion-cost"] = "10000"
        assert disruption_utils.eviction_cost(hi) > \
            disruption_utils.eviction_cost(lo_)

    def test_priority_raises_cost(self):
        normal, important = make_pod(cpu="100m"), make_pod(cpu="100m")
        important.spec.priority = 1_000_000
        assert disruption_utils.eviction_cost(important) > \
            disruption_utils.eviction_cost(normal)
