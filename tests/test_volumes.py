"""Volume topology + CSI attach-limit behavior
(reference: volumetopology.go + volumeusage.go suites)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (Node, NodeSelectorRequirement,
                                       NodeSelectorTerm, ObjectMeta, Pod, PVCRef)
from karpenter_tpu.api.storage import (CSINode, CSINodeDriver, CSIVolumeSource,
                                       PersistentVolume, PersistentVolumeClaim,
                                       PersistentVolumeSpec, PVCSpec,
                                       StorageClass, TopologySelector)
from karpenter_tpu.cloudprovider.kwok import KWOK_ZONES, KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.mgr, e.provisioner = clock, store, mgr, provisioner
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def make_volume_pod(claim, cpu="500m", **kw):
    pod = make_pod(cpu=cpu, **kw)
    pod.spec.volumes.append(PVCRef(claim_name=claim))
    return pod


class TestVolumeTopology:
    def test_bound_pv_zone_pins_pod(self, env):
        zone = KWOK_ZONES[2]
        env.store.create(PersistentVolume(
            metadata=ObjectMeta(name="pv-1", namespace=""),
            spec=PersistentVolumeSpec(
                csi=CSIVolumeSource(driver="ebs.csi"),
                node_affinity_terms=[NodeSelectorTerm(match_expressions=(
                    NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                            "In", (zone,)),))])))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-1", namespace="default"),
            spec=PVCSpec(volume_name="pv-1")))
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("pvc-1"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_storageclass_topology_pins_unbound_pvc(self, env):
        zone = KWOK_ZONES[1]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="zonal-sc", namespace=""),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-1", namespace="default"),
            spec=PVCSpec(storage_class_name="zonal-sc")))
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("pvc-1"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_missing_pvc_pod_not_provisioned(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("ghost-pvc"))
        settle(env)
        assert env.store.list(Node) == []


class TestVolumeScenarios:
    """suite_test.go:2726-3282 (VolumeUsage context)."""

    def test_shared_pvc_pods_share_a_node(self, env):
        """suite_test.go:2777-2830: many pods over ONE PVC count a single
        attachment — no spurious node fan-out."""
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="shared", namespace="default"),
            spec=PVCSpec(storage_class_name="sc")))
        env.store.create(make_nodepool(name="default"))
        for i in range(4):
            env.store.create(make_volume_pod("shared", cpu="100m",
                                             name=f"sharer-{i}"))
        settle(env)
        assert len(env.store.list(Node)) == 1
        for p in env.store.list(Pod):
            assert p.spec.node_name

    def test_nfs_volumes_unconstrained(self, env):
        """suite_test.go:2831-2868: non-CSI volumes have no attach limit
        and never block scheduling."""
        env.store.create(PersistentVolume(
            metadata=ObjectMeta(name="nfs-pv", namespace=""),
            spec=PersistentVolumeSpec()))  # no CSI source
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="nfs-pvc", namespace="default"),
            spec=PVCSpec(volume_name="nfs-pv")))
        env.store.create(make_nodepool(name="default"))
        for i in range(3):
            env.store.create(make_volume_pod("nfs-pvc", cpu="100m",
                                             name=f"nfs-{i}"))
        settle(env)
        assert len(env.store.list(Node)) == 1

    def test_ephemeral_volume_with_named_storage_class(self, env):
        """suite_test.go:2869-2980: the ephemeral template's class drives
        topology before the claim exists."""
        zone = KWOK_ZONES[3]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="eph-sc", namespace=""),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True,
                                       storage_class_name="eph-sc"))
        env.store.create(pod)
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_ephemeral_volume_missing_class_unschedulable(self, env):
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True,
                                       storage_class_name="no-such-sc"))
        env.store.create(pod)
        settle(env)
        assert env.store.list(Node) == []

    def test_ephemeral_volume_default_storage_class(self, env):
        """suite_test.go:2981-3075: no class named anywhere -> the default-
        annotated StorageClass resolves."""
        from karpenter_tpu.api.storage import DEFAULT_SC_ANNOTATION
        zone = KWOK_ZONES[0]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="cluster-default", namespace="",
                                annotations={DEFAULT_SC_ANNOTATION: "true"}),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True))
        env.store.create(pod)
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_newest_default_storage_class_wins(self, env):
        """suite_test.go:3076-3180: multiple default-annotated classes —
        the newest one resolves."""
        from karpenter_tpu.api.storage import DEFAULT_SC_ANNOTATION
        old_zone, new_zone = KWOK_ZONES[1], KWOK_ZONES[2]
        old = StorageClass(
            metadata=ObjectMeta(name="old-default", namespace="",
                                annotations={DEFAULT_SC_ANNOTATION: "true"}),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[old_zone])])
        env.store.create(old)
        env.clock.step(10)
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="new-default", namespace="",
                                annotations={DEFAULT_SC_ANNOTATION: "true"}),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[new_zone])]))
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True))
        env.store.create(pod)
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == \
            new_zone


class TestAttachLimits:
    def test_csi_attach_limit_forces_second_node(self, env):
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""), provisioner="ebs.csi"))
        for i in range(3):
            env.store.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"pvc-{i}", namespace="default"),
                spec=PVCSpec(storage_class_name="sc")))
        env.store.create(make_nodepool(name="default"))
        # first pod lands and its node gets a 1-volume attach limit
        env.store.create(make_volume_pod("pvc-0", cpu="100m"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        env.store.create(CSINode(
            metadata=ObjectMeta(name=nodes[0].name, namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=1)]))
        # second volume pod can't attach there; a new node appears
        env.store.create(make_volume_pod("pvc-1", cpu="100m"))
        settle(env)
        assert len(env.store.list(Node)) == 2
        for p in env.store.list(Pod):
            assert p.spec.node_name


class TestTensorVolumePath:
    """Ephemeral-volume pods ride the TENSOR path (VERDICT r4 item 2):
    per-pod claims linearize CSI attach limits into per-node caps, so the
    blanket host demotion is lifted for the common dynamic-PVC shape."""

    def _eph_pods(self, n, sc="sc", cpu="100m"):
        ref = PVCRef(claim_name="scratch", ephemeral=True,
                     storage_class_name=sc)
        pods = []
        for i in range(n):
            p = make_pod(cpu=cpu, name=f"eph-{i}")
            p.spec.volumes.append(ref)
            pods.append(p)
        return pods

    def _env_cluster(self, env):
        from karpenter_tpu.provisioning.provisioner import StateClusterView
        from karpenter_tpu.state.cluster import Cluster
        return StateClusterView(env.store, Cluster(env.store, env.clock))

    def test_ephemeral_pods_stay_on_tensor_path(self, env):
        from karpenter_tpu.cloudprovider.kwok import construct_instance_types
        from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        its = construct_instance_types()[:16]
        ts = TensorScheduler([make_nodepool(name="default")],
                             {"default": its},
                             cluster=self._env_cluster(env))
        r = ts.solve(self._eph_pods(6))
        assert ts.fallback_reason == ""
        assert ts.partition == (6, 0)  # no host stragglers
        assert not r.pod_errors

    def test_shared_pvc_still_demotes(self, env):
        """Non-ephemeral claims keep set-dedup semantics only the host
        oracle models; the partition must route them host-side."""
        from karpenter_tpu.provisioning.grouping import partition_pods
        pods = [make_volume_pod("shared-claim", cpu="100m")
                for _ in range(3)]
        groups, leftover, reason = partition_pods(pods)
        assert not groups and len(leftover) == 3
        assert "host-side" in reason

    def test_attach_limit_parity_with_host_oracle(self, env):
        """Existing node with a CSINode attach limit: tensor and host
        solves place the same pods on the node and open the same number of
        fresh nodes (volumeusage.go:201-208)."""
        from karpenter_tpu.cloudprovider.kwok import construct_instance_types
        from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
        from factories import make_scheduler, make_state_node
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        sn = make_state_node("big-node", cpu="64", memory="256Gi",
                             zone=KWOK_ZONES[0])
        env.store.create(CSINode(
            metadata=ObjectMeta(name="big-node", namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=2)]))
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        pods = self._eph_pods(5)
        view = self._env_cluster(env)
        ts = TensorScheduler([pool], {"default": its}, state_nodes=[sn],
                             cluster=view)
        r = ts.solve(pods)
        assert ts.fallback_reason == ""
        assert not r.pod_errors
        on_node = sum(len(en.pods) for en in r.existing_nodes)
        assert on_node == 2  # capacity admits all 5; the attach limit gates
        host = make_scheduler([pool], {"default": its}, pods,
                              state_nodes=[sn], cluster=view)
        hr = host.solve(pods)
        host_on_node = sum(len(en.pods) for en in hr.existing_nodes)
        assert host_on_node == on_node
        assert len(hr.new_nodeclaims) == len(r.new_nodeclaims)

    def test_groups_share_node_driver_budget(self, env):
        """Two groups drawing on one driver: the node budget is shared, not
        per-group (the limit is per node+driver, volumeusage.go:201-208)."""
        from karpenter_tpu.cloudprovider.kwok import construct_instance_types
        from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
        from factories import make_state_node
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        sn = make_state_node("big-node", cpu="64", memory="256Gi",
                             zone=KWOK_ZONES[0])
        env.store.create(CSINode(
            metadata=ObjectMeta(name="big-node", namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=3)]))
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        pods = (self._eph_pods(2, cpu="100m")
                + self._eph_pods(2, cpu="200m"))
        ts = TensorScheduler([pool], {"default": its}, state_nodes=[sn],
                             cluster=self._env_cluster(env))
        r = ts.solve(pods)
        assert ts.fallback_reason == ""
        assert not r.pod_errors
        on_node = sum(len(en.pods) for en in r.existing_nodes)
        assert on_node == 3  # two groups, ONE shared 3-slot budget

    def test_attach_limits_over_the_wire(self, env):
        """Sidecar session path: volume facts ride as state-node riders and
        per-template driver counts; the server enforces the same caps."""
        from karpenter_tpu.cloudprovider.kwok import construct_instance_types
        from karpenter_tpu.sidecar.client import RemoteScheduler, SolverSession
        from karpenter_tpu.sidecar.server import serve
        from factories import make_state_node
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        sn = make_state_node("big-node", cpu="64", memory="256Gi",
                             zone=KWOK_ZONES[0])
        env.store.create(CSINode(
            metadata=ObjectMeta(name="big-node", namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=2)]))
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        pods = self._eph_pods(5)
        server, port = serve()
        try:
            session = SolverSession(f"127.0.0.1:{port}")
            rs = RemoteScheduler(f"127.0.0.1:{port}", [pool],
                                 {"default": its}, state_nodes=[sn],
                                 cluster=self._env_cluster(env),
                                 session=session)
            r = rs.solve(pods)
            assert rs.fallback_reason == ""
            assert not r.pod_errors
            assert sum(len(en.pods) for en in r.existing_nodes) == 2
            session.close()
        finally:
            server.stop(0)

    def test_partition_seam_shares_attach_budget(self, env):
        """Mixed batch: ephemeral pods (tensor side) + shared-PVC pods
        (host side) against one limited node — the host pass must see the
        slots the tensor pass consumed (no double-booking across the
        partition seam)."""
        from karpenter_tpu.cloudprovider.kwok import construct_instance_types
        from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
        from factories import make_state_node
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        for i in range(2):
            env.store.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"host-pvc-{i}", namespace="default"),
                spec=PVCSpec(storage_class_name="sc")))
        sn = make_state_node("big-node", cpu="64", memory="256Gi",
                             zone=KWOK_ZONES[0])
        env.store.create(CSINode(
            metadata=ObjectMeta(name="big-node", namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=2)]))
        its = construct_instance_types()[:16]
        pool = make_nodepool(name="default")
        pods = (self._eph_pods(2)
                + [make_volume_pod(f"host-pvc-{i}", cpu="100m")
                   for i in range(2)])
        ts = TensorScheduler([pool], {"default": its}, state_nodes=[sn],
                             cluster=self._env_cluster(env))
        r = ts.solve(pods)
        assert ts.partition == (2, 2)  # ephemeral tensor-side, shared host
        assert not r.pod_errors
        on_node = sum(len(en.pods) for en in r.existing_nodes)
        assert on_node == 2  # limit 2: tensor takes both; host opens fresh
        assert r.new_nodeclaims


class TestLocalVolumeHostnameAffinity:
    """volumetopology.go:136-144 + provisioning/suite_test.go:1821-1905:
    local/hostPath PVs pin to a hostname that dies with the node, so the
    hostname requirement is dropped at scheduling time (the zone part is
    kept) — otherwise the pod could never be provisioned a new node."""

    def _bound_local_pv(self, env, name="pv-local", local=True,
                        host_path=False, zone=None):
        env.store.create(make_nodepool(name="default"))
        exprs = [NodeSelectorRequirement(
            api_labels.LABEL_HOSTNAME, "In", ("dead-node-1",))]
        if zone:
            exprs.append(NodeSelectorRequirement(
                api_labels.LABEL_TOPOLOGY_ZONE, "In", (zone,)))
        env.store.create(PersistentVolume(
            metadata=ObjectMeta(name=name, namespace=""),
            spec=PersistentVolumeSpec(
                local=local, host_path=host_path,
                node_affinity_terms=[NodeSelectorTerm(
                    match_expressions=tuple(exprs))])))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-local", namespace="default"),
            spec=PVCSpec(volume_name=name)))

    def test_local_pv_hostname_affinity_ignored(self, env):
        zone = KWOK_ZONES[1]
        self._bound_local_pv(env, zone=zone)
        pod = make_volume_pod("pvc-local")
        env.store.create(pod)
        settle(env)
        # schedulable despite the dead-node hostname pin; zone still honored
        assert pod.spec.node_name, "pod must schedule"
        node = env.store.get(Node, pod.spec.node_name)
        assert node.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_host_path_pv_hostname_affinity_ignored(self, env):
        self._bound_local_pv(env, local=False, host_path=True)
        pod = make_volume_pod("pvc-local")
        env.store.create(pod)
        settle(env)
        assert pod.spec.node_name

    def test_non_local_pv_keeps_hostname_affinity(self, env):
        """A network volume's hostname pin (if any) is real: the pod must
        NOT schedule to some other node."""
        self._bound_local_pv(env, local=False, host_path=False)
        pod = make_volume_pod("pvc-local")
        env.store.create(pod)
        settle(env)
        assert not pod.spec.node_name  # dead-node-1 doesn't exist

    def test_local_pv_codec_round_trip(self, env):
        from karpenter_tpu.kube.k8s_codec import pv_from_k8s, pv_to_k8s
        pv = PersistentVolume(
            metadata=ObjectMeta(name="pv-x", namespace=""),
            spec=PersistentVolumeSpec(local=True))
        out = pv_from_k8s(pv_to_k8s(pv))
        assert out.spec.local and not out.spec.host_path
        nfs = PersistentVolume(metadata=ObjectMeta(name="pv-y", namespace=""),
                               spec=PersistentVolumeSpec())
        out = pv_from_k8s(pv_to_k8s(nfs))
        assert not out.spec.local and not out.spec.host_path
