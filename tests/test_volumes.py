"""Volume topology + CSI attach-limit behavior
(reference: volumetopology.go + volumeusage.go suites)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (Node, NodeSelectorRequirement,
                                       NodeSelectorTerm, ObjectMeta, Pod, PVCRef)
from karpenter_tpu.api.storage import (CSINode, CSINodeDriver, CSIVolumeSource,
                                       PersistentVolume, PersistentVolumeClaim,
                                       PersistentVolumeSpec, PVCSpec,
                                       StorageClass, TopologySelector)
from karpenter_tpu.cloudprovider.kwok import KWOK_ZONES, KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.mgr, e.provisioner = clock, store, mgr, provisioner
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    env.mgr.run_until_quiet()


def make_volume_pod(claim, cpu="500m", **kw):
    pod = make_pod(cpu=cpu, **kw)
    pod.spec.volumes.append(PVCRef(claim_name=claim))
    return pod


class TestVolumeTopology:
    def test_bound_pv_zone_pins_pod(self, env):
        zone = KWOK_ZONES[2]
        env.store.create(PersistentVolume(
            metadata=ObjectMeta(name="pv-1", namespace=""),
            spec=PersistentVolumeSpec(
                csi=CSIVolumeSource(driver="ebs.csi"),
                node_affinity_terms=[NodeSelectorTerm(match_expressions=(
                    NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                            "In", (zone,)),))])))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-1", namespace="default"),
            spec=PVCSpec(volume_name="pv-1")))
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("pvc-1"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_storageclass_topology_pins_unbound_pvc(self, env):
        zone = KWOK_ZONES[1]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="zonal-sc", namespace=""),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-1", namespace="default"),
            spec=PVCSpec(storage_class_name="zonal-sc")))
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("pvc-1"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_missing_pvc_pod_not_provisioned(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("ghost-pvc"))
        settle(env)
        assert env.store.list(Node) == []


class TestVolumeScenarios:
    """suite_test.go:2726-3282 (VolumeUsage context)."""

    def test_shared_pvc_pods_share_a_node(self, env):
        """suite_test.go:2777-2830: many pods over ONE PVC count a single
        attachment — no spurious node fan-out."""
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""),
            provisioner="ebs.csi"))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="shared", namespace="default"),
            spec=PVCSpec(storage_class_name="sc")))
        env.store.create(make_nodepool(name="default"))
        for i in range(4):
            env.store.create(make_volume_pod("shared", cpu="100m",
                                             name=f"sharer-{i}"))
        settle(env)
        assert len(env.store.list(Node)) == 1
        for p in env.store.list(Pod):
            assert p.spec.node_name

    def test_nfs_volumes_unconstrained(self, env):
        """suite_test.go:2831-2868: non-CSI volumes have no attach limit
        and never block scheduling."""
        env.store.create(PersistentVolume(
            metadata=ObjectMeta(name="nfs-pv", namespace=""),
            spec=PersistentVolumeSpec()))  # no CSI source
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="nfs-pvc", namespace="default"),
            spec=PVCSpec(volume_name="nfs-pv")))
        env.store.create(make_nodepool(name="default"))
        for i in range(3):
            env.store.create(make_volume_pod("nfs-pvc", cpu="100m",
                                             name=f"nfs-{i}"))
        settle(env)
        assert len(env.store.list(Node)) == 1

    def test_ephemeral_volume_with_named_storage_class(self, env):
        """suite_test.go:2869-2980: the ephemeral template's class drives
        topology before the claim exists."""
        zone = KWOK_ZONES[3]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="eph-sc", namespace=""),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True,
                                       storage_class_name="eph-sc"))
        env.store.create(pod)
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_ephemeral_volume_missing_class_unschedulable(self, env):
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True,
                                       storage_class_name="no-such-sc"))
        env.store.create(pod)
        settle(env)
        assert env.store.list(Node) == []

    def test_ephemeral_volume_default_storage_class(self, env):
        """suite_test.go:2981-3075: no class named anywhere -> the default-
        annotated StorageClass resolves."""
        from karpenter_tpu.api.storage import DEFAULT_SC_ANNOTATION
        zone = KWOK_ZONES[0]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="cluster-default", namespace="",
                                annotations={DEFAULT_SC_ANNOTATION: "true"}),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True))
        env.store.create(pod)
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_newest_default_storage_class_wins(self, env):
        """suite_test.go:3076-3180: multiple default-annotated classes —
        the newest one resolves."""
        from karpenter_tpu.api.storage import DEFAULT_SC_ANNOTATION
        old_zone, new_zone = KWOK_ZONES[1], KWOK_ZONES[2]
        old = StorageClass(
            metadata=ObjectMeta(name="old-default", namespace="",
                                annotations={DEFAULT_SC_ANNOTATION: "true"}),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[old_zone])])
        env.store.create(old)
        env.clock.step(10)
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="new-default", namespace="",
                                annotations={DEFAULT_SC_ANNOTATION: "true"}),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[new_zone])]))
        env.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="100m")
        pod.spec.volumes.append(PVCRef(claim_name="scratch", ephemeral=True))
        env.store.create(pod)
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == \
            new_zone


class TestAttachLimits:
    def test_csi_attach_limit_forces_second_node(self, env):
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""), provisioner="ebs.csi"))
        for i in range(3):
            env.store.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"pvc-{i}", namespace="default"),
                spec=PVCSpec(storage_class_name="sc")))
        env.store.create(make_nodepool(name="default"))
        # first pod lands and its node gets a 1-volume attach limit
        env.store.create(make_volume_pod("pvc-0", cpu="100m"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        env.store.create(CSINode(
            metadata=ObjectMeta(name=nodes[0].name, namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=1)]))
        # second volume pod can't attach there; a new node appears
        env.store.create(make_volume_pod("pvc-1", cpu="100m"))
        settle(env)
        assert len(env.store.list(Node)) == 2
        for p in env.store.list(Pod):
            assert p.spec.node_name
