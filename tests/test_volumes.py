"""Volume topology + CSI attach-limit behavior
(reference: volumetopology.go + volumeusage.go suites)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (Node, NodeSelectorRequirement,
                                       NodeSelectorTerm, ObjectMeta, Pod, PVCRef)
from karpenter_tpu.api.storage import (CSINode, CSINodeDriver, CSIVolumeSource,
                                       PersistentVolume, PersistentVolumeClaim,
                                       PersistentVolumeSpec, PVCSpec,
                                       StorageClass, TopologySelector)
from karpenter_tpu.cloudprovider.kwok import KWOK_ZONES, KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.mgr, e.provisioner = clock, store, mgr, provisioner
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    env.mgr.run_until_quiet()


def make_volume_pod(claim, cpu="500m", **kw):
    pod = make_pod(cpu=cpu, **kw)
    pod.spec.volumes.append(PVCRef(claim_name=claim))
    return pod


class TestVolumeTopology:
    def test_bound_pv_zone_pins_pod(self, env):
        zone = KWOK_ZONES[2]
        env.store.create(PersistentVolume(
            metadata=ObjectMeta(name="pv-1", namespace=""),
            spec=PersistentVolumeSpec(
                csi=CSIVolumeSource(driver="ebs.csi"),
                node_affinity_terms=[NodeSelectorTerm(match_expressions=(
                    NodeSelectorRequirement(api_labels.LABEL_TOPOLOGY_ZONE,
                                            "In", (zone,)),))])))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-1", namespace="default"),
            spec=PVCSpec(volume_name="pv-1")))
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("pvc-1"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_storageclass_topology_pins_unbound_pvc(self, env):
        zone = KWOK_ZONES[1]
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="zonal-sc", namespace=""),
            provisioner="ebs.csi",
            allowed_topologies=[TopologySelector(
                key=api_labels.LABEL_TOPOLOGY_ZONE, values=[zone])]))
        env.store.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="pvc-1", namespace="default"),
            spec=PVCSpec(storage_class_name="zonal-sc")))
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("pvc-1"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        assert nodes[0].metadata.labels[api_labels.LABEL_TOPOLOGY_ZONE] == zone

    def test_missing_pvc_pod_not_provisioned(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_volume_pod("ghost-pvc"))
        settle(env)
        assert env.store.list(Node) == []


class TestAttachLimits:
    def test_csi_attach_limit_forces_second_node(self, env):
        env.store.create(StorageClass(
            metadata=ObjectMeta(name="sc", namespace=""), provisioner="ebs.csi"))
        for i in range(3):
            env.store.create(PersistentVolumeClaim(
                metadata=ObjectMeta(name=f"pvc-{i}", namespace="default"),
                spec=PVCSpec(storage_class_name="sc")))
        env.store.create(make_nodepool(name="default"))
        # first pod lands and its node gets a 1-volume attach limit
        env.store.create(make_volume_pod("pvc-0", cpu="100m"))
        settle(env)
        nodes = env.store.list(Node)
        assert len(nodes) == 1
        env.store.create(CSINode(
            metadata=ObjectMeta(name=nodes[0].name, namespace=""),
            drivers=[CSINodeDriver(name="ebs.csi", allocatable_count=1)]))
        # second volume pod can't attach there; a new node appears
        env.store.create(make_volume_pod("pvc-1", cpu="100m"))
        settle(env)
        assert len(env.store.list(Node)) == 2
        for p in env.store.list(Pod):
            assert p.spec.node_name
