"""Scheduler scenario corpus, ported from
/root/reference/pkg/controllers/provisioning/scheduling/suite_test.go
(3,916 LoC) and instance_selection_test.go (1,566 LoC) — the families the
round-4 suites left thin. Each test cites its Go source range; scenarios in
the kernel's feature set assert tensor-vs-host parity via the
test_binpack_parity helpers, stateful ones drive the expectations harness.
"""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import (NodeSelectorRequirement, Taint,
                                       Toleration)
from karpenter_tpu.cloudprovider import kwok

from expectations import consolidation_nodepool, make_env
from factories import make_nodepool, make_pod, make_pods, make_state_node
from test_binpack_parity import both, host_solve, tensor_solve


def _its(n=48):
    return kwok.construct_instance_types()[:n]


class TestRestrictedLabels:
    """suite_test.go:396-466 Constraints Validation."""

    def test_restricted_label_selector_fails(self):
        """:397-407: kubernetes.io/hostname (RestrictedLabels) in a node
        selector never schedules."""
        for key in api_labels.RESTRICTED_LABELS:
            t, h = both(lambda: [make_pod(
                cpu="100m", node_selector={key: "test"})])
            assert len(t.pod_errors) == len(h.pod_errors) == 1, key

    def test_restricted_domain_selector_fails(self):
        """:408-418: any key under a restricted domain fails."""
        for domain in api_labels.RESTRICTED_LABEL_DOMAINS:
            t, h = both(lambda: [make_pod(
                cpu="100m", node_selector={f"{domain}/test": "test"})])
            assert len(t.pod_errors) == len(h.pod_errors) == 1, domain

    def test_exception_domain_labels_schedule(self):
        """:419-432: pool-defined requirements under the exceptions list
        (node.kubernetes.io etc.) are legal and stamp the claim."""
        for domain in api_labels.LABEL_DOMAIN_EXCEPTIONS:
            key = f"{domain}/test"
            pool = make_nodepool(requirements=[NodeSelectorRequirement(
                key=key, operator="In", values=("test-value",))])
            t, h = both(lambda: [make_pod(cpu="100m")], nodepools=[pool])
            assert not t.pod_errors and not h.pod_errors, domain
            for r in (t, h):
                req = r.new_nodeclaims[0].requirements.get(key)
                assert req.has("test-value"), domain

    def test_exception_subdomain_labels_schedule(self):
        """:433-446: subdomains of exception domains are legal too."""
        for domain in api_labels.LABEL_DOMAIN_EXCEPTIONS:
            key = f"subdomain.{domain}/test"
            pool = make_nodepool(requirements=[NodeSelectorRequirement(
                key=key, operator="In", values=("test-value",))])
            t, h = both(lambda: [make_pod(cpu="100m")], nodepools=[pool])
            assert not t.pod_errors and not h.pod_errors, domain


class TestSelectorOperatorMatrix:
    """suite_test.go:467-643 Scheduling Logic: every operator against
    defined and undefined keys, both solver paths."""

    POOL_KEY = "example.com/tier"

    def _pool(self):
        return make_nodepool(requirements=[NodeSelectorRequirement(
            key=self.POOL_KEY, operator="In", values=("gold", "silver"))])

    def _req_pod(self, op, values=()):
        return make_pod(cpu="100m", required_affinity=[[
            NodeSelectorRequirement(key=self.POOL_KEY, operator=op,
                                    values=tuple(values))]])

    @pytest.mark.parametrize("op,values,ok", [
        ("In", ("gold",), True),          # :522-533 matching value
        ("In", ("bronze",), False),       # :569-579 different value
        ("NotIn", ("gold",), True),       # :580-591 NotIn different ok
        ("NotIn", ("gold", "silver"), False),  # :534-544 all excluded
        ("Exists", (), True),             # :545-556 defined key
        ("DoesNotExist", (), False),      # :557-568 defined key fails
    ])
    def test_operator_against_pool_defined_key(self, op, values, ok):
        t, h = both(lambda: [self._req_pod(op, values)],
                    nodepools=[self._pool()])
        want = 0 if ok else 1
        assert len(t.pod_errors) == len(h.pod_errors) == want, (op, values)

    @pytest.mark.parametrize("op,values,ok", [
        ("In", ("x",), False),            # :475-483 In on undefined key
        ("NotIn", ("x",), True),          # :484-493 NotIn on undefined ok
        ("Exists", (), False),            # :494-502 Exists on undefined
        ("DoesNotExist", (), True),       # :503-512 DoesNotExist ok
    ])
    def test_operator_against_undefined_key(self, op, values, ok):
        t, h = both(lambda: [self._req_pod(op, values)])
        want = 0 if ok else 1
        assert len(t.pod_errors) == len(h.pod_errors) == want, (op, values)

    def test_compatible_pods_share_one_node_across_groups(self):
        """:592-611: a gold-pinned pod and an unconstrained pod co-locate
        (the claim narrows to gold); both paths agree on ONE node."""
        def pods():
            return [self._req_pod("In", ("gold",)),
                    make_pod(cpu="100m")]
        t, h = both(pods, nodepools=[self._pool()])
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 1

    def test_incompatible_pods_split_nodes(self):
        """:612-631: gold-pinned and silver-pinned pods cannot share."""
        def pods():
            return [self._req_pod("In", ("gold",)),
                    self._req_pod("In", ("silver",))]
        t, h = both(pods, nodepools=[self._pool()])
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 2


class TestTaintsInFlight:
    """suite_test.go:2006-2152 Taints + the in-flight claim reuse rules."""

    def test_tolerating_pods_share_tainted_pool_claim(self):
        pool = make_nodepool(taints=[Taint(key="dedicated", value="x")])
        tol = [Toleration(key="dedicated", operator="Exists")]
        t, h = both(lambda: make_pods(4, cpu="100m", tolerations=tol),
                    nodepools=[pool])
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims) == 1

    def test_untainted_existing_node_reused(self):
        """:2007-2029 'should assume pod will schedule to a tainted node
        with no taints': an initialized empty live node takes the pod
        instead of a fresh claim."""
        sn = make_state_node("live-ok", cpu="8", memory="16Gi")
        t = tensor_solve([make_nodepool()], _its(),
                         [make_pod(cpu="100m")], state_nodes=[sn])
        assert not t.pod_errors
        assert not t.new_nodeclaims
        assert any(en.pods for en in t.existing_nodes)

    def test_tainted_existing_node_not_assumed(self):
        """:2030-2062 'should not assume pod will schedule to a tainted
        node': a NoSchedule-tainted live node is skipped; a fresh claim
        opens."""
        sn = make_state_node("live-tainted", cpu="8", memory="16Gi")
        sn.node.spec.taints.append(Taint(key="foo.com/taint",
                                         value="tainted"))
        t = tensor_solve([make_nodepool()], _its(),
                         [make_pod(cpu="100m")], state_nodes=[sn])
        assert not t.pod_errors
        assert t.new_nodeclaims, "pod was parked on the tainted node"
        assert not any(en.pods for en in t.existing_nodes)

    def test_startup_taints_do_not_block(self):
        """startup taints clear during initialization; scheduling proceeds
        without tolerations (suite_test.go:2063-2152 family)."""
        pool = make_nodepool(startup_taints=[Taint(key="boot", value="x")])
        t, h = both(lambda: make_pods(3, cpu="100m"), nodepools=[pool])
        assert not t.pod_errors and not h.pod_errors


class TestDaemonsetOverhead:
    """suite_test.go:2153-2426 Daemonsets."""

    def test_selector_restricted_daemonset_skips_other_pools(self):
        """:2263-2310 family: a daemonset pinned to pool A must not inflate
        pool B's overhead."""
        pool_a = make_nodepool(name="pool-a", labels={"team": "a"})
        pool_b = make_nodepool(name="pool-b", labels={"team": "b"})
        daemon = make_pod(cpu="3", memory="4Gi",
                          node_selector={"team": "a"})
        its = _its()
        # pods pinned to pool-b: the daemonset overhead must NOT shrink
        # their per-node capacity
        t = tensor_solve([pool_a, pool_b],
                         {"pool-a": its, "pool-b": its},
                         make_pods(4, cpu="800m",
                                   node_selector={"team": "b"}),
                         daemonset_pods=[daemon])
        h = host_solve([pool_a, pool_b], {"pool-a": its, "pool-b": its},
                       make_pods(4, cpu="800m",
                                 node_selector={"team": "b"}),
                       daemonset_pods=[daemon])
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)
        for nc in t.new_nodeclaims:
            # 4x800m = 3200m fits a c-4x WITHOUT the daemon's 3 cpu; if the
            # overhead were wrongly charged, every surviving option would
            # need >= 6200m — so a sub-6200m option proves the exclusion
            assert min(it.allocatable().get("cpu", 0)
                       for it in nc.instance_type_options) < 6200, \
                "daemonset overhead leaked into pool-b sizing"

    def test_intolerant_daemonset_skips_tainted_pool(self):
        """daemon pods that don't tolerate the pool's taints contribute no
        overhead there (scheduler.py _daemon_pod_compatible)."""
        pool = make_nodepool(taints=[Taint(key="dedicated", value="x")])
        tol = [Toleration(key="dedicated", operator="Exists")]
        daemon = make_pod(cpu="3", memory="4Gi")  # no toleration
        t = tensor_solve([pool], _its(),
                         make_pods(4, cpu="800m", tolerations=tol),
                         daemonset_pods=[daemon])
        assert not t.pod_errors
        [nc] = t.new_nodeclaims
        # 3200m of pods; the intolerant daemon's 3 cpu must NOT raise the
        # floor to 6200m — a smaller option must survive
        assert min(it.allocatable().get("cpu", 0)
                   for it in nc.instance_type_options) < 6200, \
            "intolerant daemonset still charged overhead"

    def test_daemonset_overhead_sizes_instance_choice(self):
        """:2153-2262: a 1cpu/1Gi daemonset raises the per-node floor — a
        node sized for the pod alone can't launch."""
        daemon = make_pod(cpu="1", memory="1Gi")
        t = tensor_solve([make_nodepool()], _its(),
                         [make_pod(cpu="900m", memory="900Mi")])
        td = tensor_solve([make_nodepool()], _its(),
                          [make_pod(cpu="900m", memory="900Mi")],
                          daemonset_pods=[daemon])
        assert not td.pod_errors
        bare_min = min(
            min(it.allocatable().get("cpu", 0)
                for it in nc.instance_type_options)
            for nc in t.new_nodeclaims)
        with_ds_min = min(
            min(it.allocatable().get("cpu", 0)
                for it in nc.instance_type_options)
            for nc in td.new_nodeclaims)
        assert with_ds_min >= bare_min
        assert with_ds_min >= 1900  # pod + daemon cpu


class TestInstanceSelectionInvariants:
    """instance_selection_test.go: the claim's launch list must satisfy the
    pod's constraints entirely and stay price-ordered — across every
    well-known dimension and mixed batches."""

    CASES = [
        ({"node_selector": {api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-b"}},
         api_labels.LABEL_TOPOLOGY_ZONE, {"test-zone-b"}),
        ({"node_selector": {api_labels.LABEL_ARCH: "arm64"}},
         api_labels.LABEL_ARCH, {"arm64"}),
        ({"node_selector": {api_labels.LABEL_OS: "windows"}},
         api_labels.LABEL_OS, {"windows"}),
        ({"node_selector": {api_labels.CAPACITY_TYPE_LABEL_KEY: "spot"}},
         api_labels.CAPACITY_TYPE_LABEL_KEY, {"spot"}),
        ({"node_selector": {api_labels.LABEL_INSTANCE_TYPE:
                            "c-4x-amd64-linux"}},
         api_labels.LABEL_INSTANCE_TYPE, {"c-4x-amd64-linux"}),
    ]

    @pytest.mark.parametrize("podkw,key,allowed", CASES)
    def test_launch_list_satisfies_constraint(self, podkw, key, allowed):
        t, h = both(lambda: [make_pod(cpu="100m", **podkw)])
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            [nc] = r.new_nodeclaims
            for it in nc.instance_type_options:
                req = it.requirements.get(key)
                assert req is None or any(req.has(v) for v in allowed), \
                    (it.name, key)

    def test_launch_list_price_sorted(self):
        """types.go:117-134 OrderByPrice: cheapest first, name tiebreak.
        The tensor path pre-sorts its option lists; the host oracle applies
        OrderByPrice at claim materialization (to_nodeclaim), so only the
        tensor list is asserted here."""
        t, _h = both(lambda: make_pods(3, cpu="500m"))
        for nc in t.new_nodeclaims:
            keyed = [(min(o.price for o in it.offerings), it.name)
                     for it in nc.instance_type_options]
            assert keyed == sorted(keyed)

    def test_mixed_constraint_batch_launches_per_dimension(self):
        """instance_selection_test.go mixed batches: one batch with pods
        pinned to different zones/captypes yields per-dimension claims,
        each satisfying its own pods, both paths at equal node counts."""
        def pods():
            return (make_pods(3, cpu="100m", labels={"app": "za"},
                              node_selector={
                                  api_labels.LABEL_TOPOLOGY_ZONE:
                                  "test-zone-a"})
                    + make_pods(3, cpu="100m", labels={"app": "zb"},
                                node_selector={
                                    api_labels.LABEL_TOPOLOGY_ZONE:
                                    "test-zone-b"})
                    + make_pods(3, cpu="100m", labels={"app": "sp"},
                                node_selector={
                                    api_labels.CAPACITY_TYPE_LABEL_KEY:
                                    "spot"}))
        t, h = both(pods)
        assert not t.pod_errors and not h.pod_errors
        assert len(t.new_nodeclaims) == len(h.new_nodeclaims)
        for nc in t.new_nodeclaims:
            zones = {p.spec.node_selector.get(api_labels.LABEL_TOPOLOGY_ZONE)
                     for p in nc.pods}
            zones.discard(None)
            assert len(zones) <= 1, "cross-zone pods share a claim"

    def test_fallback_to_cheaper_unconstrained_types(self):
        """A constrained pod must not drag the whole batch onto its pricier
        types: unconstrained pods still launch with the cheapest options."""
        def pods():
            # the pinned pod nearly fills its m-8x (8 cpu), so the free
            # pods CANNOT ride along and must get their own claim
            return ([make_pod(cpu="7500m", labels={"app": "pin"},
                              node_selector={api_labels.LABEL_INSTANCE_TYPE:
                                             "m-8x-amd64-linux"})]
                    + make_pods(3, cpu="1", labels={"app": "free"}))
        t, h = both(pods)
        assert not t.pod_errors and not h.pod_errors
        for r in (t, h):
            free_claims = [nc for nc in r.new_nodeclaims
                           if all(not p.spec.node_selector
                                  for p in nc.pods)]
            assert free_claims, "free pods rode the pinned claim"
            m8x = next(it for it in kwok.construct_instance_types()
                       if it.name == "m-8x-amd64-linux")
            m8x_price = min(o.price for o in m8x.offerings)
            for nc in free_claims:
                cheapest = min(min(o.price for o in it.offerings)
                               for it in nc.instance_type_options)
                # the pinned m-8x tier must not leak into the free claim:
                # its cheapest option is a right-sized type, strictly
                # cheaper than the pinned pod's instance type
                assert cheapest < m8x_price, (cheapest, m8x_price)


class TestSchedulingMetrics:
    """suite_test.go:3646+ Metrics: the solve stamps its duration family."""

    def test_scheduling_duration_observes(self):
        from karpenter_tpu.metrics.registry import SCHEDULING_DURATION
        env = make_env(consolidation_nodepool())
        before = SCHEDULING_DURATION.count({})
        env.store.create(make_pod(cpu="100m"))
        env.settle()
        assert SCHEDULING_DURATION.count({}) > before


class TestExistingNodePressure:
    """suite_test.go:2427-2607 Existing Nodes."""

    def test_existing_capacity_fills_before_new_nodes(self):
        sns = [make_state_node(f"live-{i}", cpu="4", memory="8Gi")
               for i in range(3)]
        t = tensor_solve([make_nodepool()], _its(),
                         make_pods(9, cpu="1"), state_nodes=sns)
        assert not t.pod_errors
        filled = sum(1 for en in t.existing_nodes if en.pods)
        assert filled == 3, "existing capacity skipped"
        assert len(t.new_nodeclaims) == 0

    def test_daemonset_overhead_on_existing_nodes(self):
        """:2549-2607: live nodes' remaining capacity already reflects
        their daemonsets via allocatable; the solver packs to what's
        available, not nameplate."""
        sn = make_state_node("live-small", cpu="2", memory="4Gi")
        t = tensor_solve([make_nodepool()], _its(),
                         make_pods(4, cpu="1"), state_nodes=[sn])
        assert not t.pod_errors
        on_live = sum(len(en.pods) for en in t.existing_nodes)
        assert on_live <= 2, "overpacked the live node"
        assert t.new_nodeclaims
