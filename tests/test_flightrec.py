"""Flight recorder + deterministic replay (ISSUE 4).

Covers the black-box contract end to end:

- codec round trip: a captured record survives record -> JSONL -> load ->
  decode -> re-encode byte-identically, seeded from the parity fuzzer's
  scenario generator so the property holds across pools x taints x
  selectors x spreads x affinities;
- replay: the replayed tensor decision is byte-identical to the recorded
  digest and tensor/host parity holds (the CLI's verdicts);
- schema versioning: unknown versions are rejected loudly;
- the ring: bounded, metrics pair, capture failures never raise;
- the hooks: a live Provisioner.reconcile and a live disruption pass each
  land a record; /debug/flightrecorder serves and dumps the ring;
- the wall-clock-leak satellites: condition timestamps and envtest object
  metadata follow the injected clock.
"""

import json
import random
import urllib.request

import pytest

from karpenter_tpu.flightrec import (FlightRecorder, SCHEMA_VERSION,
                                     TraceVersionError, loads_record,
                                     replay_record, replay_trace)
from karpenter_tpu.flightrec.record import (decode_solve_payload,
                                            encode_solve_payload, load_trace)
from karpenter_tpu.metrics.registry import (FLIGHTREC_DROPPED,
                                            FLIGHTREC_RECORDS)
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod
from test_parity_fuzzer import gen_catalog, gen_nodepools, gen_pods

pytestmark = pytest.mark.replay


def _norm(d):
    return json.loads(json.dumps(d))


def _record_solve(seed: int, recorder=None):
    rng = random.Random(seed)
    pools = gen_nodepools(rng)
    its = {p.name: gen_catalog(rng) for p in pools}
    pods = gen_pods(random.Random(seed + 1), pools)
    # `recorder or ...` would discard an EMPTY recorder (len() == 0 is falsy)
    rec = recorder if recorder is not None else FlightRecorder(capacity=8)
    ts = TensorScheduler(pools, its)
    ts.flight_recorder = rec
    ts.solve(pods)
    return rec, ts, pods


# -- codec round trip (satellite: property test over fuzzer scenarios) ------


@pytest.mark.parametrize("seed", [1000, 1004, 1011, 1019, 1027, 1033])
def test_record_roundtrip_and_replay(seed):
    rec, ts, _pods = _record_solve(seed)
    line = rec.lines()[-1]
    loaded = loads_record(line)
    assert loaded["v"] == SCHEMA_VERSION
    assert loaded["kind"] == "provisioning"

    # decode -> re-encode is byte-identical (JSON-normalized): the wire
    # codec loses nothing the solver reads
    payload = loaded["solve"]
    nodepools, its, pods, sns, daemons, _cv = decode_solve_payload(payload)
    re_encoded = encode_solve_payload(nodepools, its, pods, state_nodes=sns,
                                      daemonset_pods=daemons)
    for key in ("nodepools", "catalog", "pool_instance_types", "pods",
                "state_nodes", "daemonset_pods"):
        assert _norm(re_encoded[key]) == _norm(payload[key]), key

    # offline replay reproduces the recorded decision byte-identically and
    # passes the tensor/host parity contract
    report = replay_record(loaded)
    assert report.deterministic is True, report.render()
    assert report.parity is True, report.render()


def test_unknown_schema_version_is_rejected():
    rec, _, _ = _record_solve(1002)
    d = json.loads(rec.lines()[-1])
    d["v"] = SCHEMA_VERSION + 1
    with pytest.raises(TraceVersionError) as exc:
        loads_record(json.dumps(d))
    assert f"v{SCHEMA_VERSION + 1}" in str(exc.value)
    with pytest.raises(TraceVersionError):
        loads_record(json.dumps({"kind": "provisioning"}))  # v missing


# -- the ring ---------------------------------------------------------------


def test_ring_is_bounded_and_counts_drops():
    records0 = sum(FLIGHTREC_RECORDS.value({"kind": k})
                   for k in ("provisioning", "disruption"))
    evicted0 = FLIGHTREC_DROPPED.value({"reason": "evicted"})
    rec = FlightRecorder(capacity=2)
    for seed in (1000, 1001, 1002):
        _record_solve(seed, recorder=rec)
    assert len(rec) == 2
    records1 = sum(FLIGHTREC_RECORDS.value({"kind": k})
                   for k in ("provisioning", "disruption"))
    assert records1 - records0 == 3
    assert FLIGHTREC_DROPPED.value({"reason": "evicted"}) - evicted0 == 1
    # the survivors are the two NEWEST captures, oldest-first eviction:
    # pin against each seed's deterministic batch size
    def pod_count(seed):
        rng = random.Random(seed)
        pools = gen_nodepools(rng)
        for p in pools:
            gen_catalog(rng)
        return len(gen_pods(random.Random(seed + 1), pools))

    assert [r.meta["pods"] for r in rec.records()] == \
        [pod_count(1001), pod_count(1002)]


def test_capture_failure_never_raises():
    dropped0 = FLIGHTREC_DROPPED.value({"reason": "capture_error"})
    rec = FlightRecorder(capacity=2)
    rec.capture_provisioning(object(), [], object(), 0.0)  # not a scheduler
    assert FLIGHTREC_DROPPED.value({"reason": "capture_error"}) == dropped0 + 1
    assert len(rec) == 0


# -- hooks ------------------------------------------------------------------


def _make_env(flightrec=None):
    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.kube.store import Store
    from karpenter_tpu.provisioning.provisioner import Provisioner
    from karpenter_tpu.state.cluster import Cluster
    from karpenter_tpu.state.informers import wire_informers

    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    provisioner = Provisioner(store, cluster, provider, clock,
                              flight_recorder=flightrec)
    return clock, store, cluster, provisioner


def test_provisioner_reconcile_records_the_solve():
    rec = FlightRecorder(capacity=4)
    clock, store, cluster, provisioner = _make_env(rec)
    store.create(make_nodepool())
    store.create(make_pod(cpu="500m"))
    provisioner.trigger()
    clock.step(1.2)  # past the batch idle window
    provisioner.reconcile()
    assert len(rec) == 1
    r = rec.records()[-1]
    assert r.kind == "provisioning"
    assert r.meta["pods"] == 1
    assert r.meta["claims"] == 1
    report = replay_record(loads_record(rec.lines()[-1]))
    assert report.deterministic is True and report.parity is True, \
        report.render()


def _consolidatable_cluster(n_nodes: int):
    """bench_consolidation's fabric at test scale: N underutilized 4-cpu
    nodes, one 200m pod each — a guaranteed multi-node consolidation win."""
    import bench
    from karpenter_tpu.api import labels as api_labels
    from karpenter_tpu.api.nodeclaim import (COND_CONSOLIDATABLE,
                                             COND_INITIALIZED, COND_LAUNCHED,
                                             COND_REGISTERED, NodeClaim,
                                             NodeClaimSpec)
    from karpenter_tpu.api.objects import (Node, NodeSpec, NodeStatus,
                                           ObjectMeta, Pod, PodSpec)
    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.kube.store import Store
    from karpenter_tpu.provisioning.provisioner import Provisioner
    from karpenter_tpu.state.cluster import Cluster
    from karpenter_tpu.state.informers import wire_informers
    from karpenter_tpu.utils import resources as res

    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    catalog = bench._catalog()
    provider = KwokCloudProvider(instance_types=catalog, store=store)
    provisioner = Provisioner(store, cluster, provider, clock)
    store.create(make_nodepool())
    big = next(it for it in catalog
               if it.capacity.get("cpu") == 4000 and "amd64-linux" in it.name)
    for i in range(n_nodes):
        name = f"fr-node-{i:03d}"
        labels = {
            api_labels.LABEL_HOSTNAME: name,
            api_labels.NODEPOOL_LABEL_KEY: "default",
            api_labels.NODE_INITIALIZED_LABEL_KEY: "true",
            api_labels.NODE_REGISTERED_LABEL_KEY: "true",
            api_labels.LABEL_INSTANCE_TYPE: big.name,
            api_labels.LABEL_TOPOLOGY_ZONE: "test-zone-a",
            api_labels.CAPACITY_TYPE_LABEL_KEY:
                api_labels.CAPACITY_TYPE_ON_DEMAND,
        }
        nc = NodeClaim(metadata=ObjectMeta(name=f"fr-nc-{i:03d}",
                                           namespace="", labels=dict(labels)),
                       spec=NodeClaimSpec())
        nc.status.provider_id = f"fr://{i}"
        nc.status.node_name = name
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED,
                     COND_CONSOLIDATABLE):
            nc.conditions.set_true(cond, now=clock.now())
        store.create(nc)
        store.create(Node(
            metadata=ObjectMeta(name=name, namespace="", labels=labels),
            spec=NodeSpec(provider_id=f"fr://{i}"),
            status=NodeStatus(capacity=dict(big.capacity),
                              allocatable=big.allocatable())))
        store.create(Pod(
            metadata=ObjectMeta(name=f"fr-pod-{i}", namespace="default"),
            spec=PodSpec(node_name=name),
            container_requests=[res.parse_list(
                {"cpu": "200m", "memory": "128Mi"})]))
    return clock, store, cluster, provisioner


def test_disruption_pass_records_the_decision():
    from karpenter_tpu.disruption.controller import (DisruptionController,
                                                     OrchestrationQueue)
    clock, store, cluster, provisioner = _consolidatable_cluster(12)
    rec = FlightRecorder(capacity=4, clock=clock)
    queue = OrchestrationQueue(store, cluster, clock)
    controller = DisruptionController(store, cluster, provisioner, queue,
                                      clock, flight_recorder=rec)
    controller.reconcile()
    assert len(rec) == 1
    r = rec.records()[-1]
    assert r.kind == "disruption"
    cmd = r.meta["command"]
    assert cmd["decision"] in ("delete", "replace")
    assert cmd["candidates"]
    assert len(r.meta["rejections"]) == 12 - len(cmd["candidates"])
    report = replay_record(loads_record(rec.lines()[-1]))
    assert report.deterministic is True, report.render()
    assert report.parity is True, report.render()


def test_replace_decision_replays_deterministically():
    """Consolidation post-processes replacement claims IN PLACE after the
    solve (price re-sort + remove_instance_types_by_price), so the recorded
    instance-type signatures differ from raw solver output by design — the
    replay comparison must judge the solver-level decision (pool/zones/
    fill/errors), or every 'replace' trace false-alarms as nondeterministic."""
    from karpenter_tpu.disruption.controller import (DisruptionController,
                                                     OrchestrationQueue)
    # ONE underutilized node: its pod has nowhere to go, so the decision is
    # a replacement launch with a cheaper instance type
    clock, store, cluster, provisioner = _consolidatable_cluster(1)
    rec = FlightRecorder(capacity=4, clock=clock)
    queue = OrchestrationQueue(store, cluster, clock)
    controller = DisruptionController(store, cluster, provisioner, queue,
                                      clock, flight_recorder=rec)
    controller.reconcile()
    assert len(rec) == 1
    r = rec.records()[-1]
    assert r.meta["command"]["decision"] == "replace"
    assert r.meta["command"]["replacements"]
    report = replay_record(loads_record(rec.lines()[-1]))
    assert report.deterministic is True, report.render()
    assert report.parity is True, report.render()


def test_debug_flightrecorder_endpoint(tmp_path, monkeypatch):
    from karpenter_tpu.operator.server import ServingGroup
    rec, _, _ = _record_solve(1000)
    serving = ServingGroup(0, 0, flightrec=rec).start()
    try:
        base = f"http://127.0.0.1:{serving.metrics_port}"
        body = urllib.request.urlopen(
            f"{base}/debug/flightrecorder").read().decode()
        assert "provisioning" in body and "records 1" in body
        jl = urllib.request.urlopen(
            f"{base}/debug/flightrecorder?format=jsonl").read().decode()
        assert loads_record(jl.strip().splitlines()[-1])["kind"] == \
            "provisioning"
        # dump=0 is NOT a dump request (parse_qs truthiness trap)
        body = urllib.request.urlopen(
            f"{base}/debug/flightrecorder?dump=0").read().decode()
        assert "records 1" in body and "dumped" not in body
        # endpoint-triggered dump lands in the configured directory only
        monkeypatch.setenv("KARPENTER_FLIGHTREC_DIR", str(tmp_path))
        body = urllib.request.urlopen(
            f"{base}/debug/flightrecorder?dump=1&name=../../esc.jsonl"
        ).read().decode()
        assert "dumped 1 records" in body
        assert (tmp_path / "esc.jsonl").exists()  # basename-only: no escape
        assert len(load_trace(str(tmp_path / "esc.jsonl"))) == 1
    finally:
        serving.stop()


# -- CLI smoke (satellite: tier-1 record -> dump -> replay -> clean verdict)


def test_cli_replay_smoke(tmp_path, capsys):
    from karpenter_tpu.flightrec.__main__ import main
    rec, _, _ = _record_solve(1005)
    path = str(tmp_path / "trace.jsonl")
    assert rec.dump(path) == 1
    assert main(["show", path]) == 0
    shown = capsys.readouterr().out
    assert "1 records" in shown
    assert main(["replay", path]) == 0
    out = capsys.readouterr().out
    assert "deterministic=ok" in out and "parity=ok" in out
    assert "0 verdict failures" in out
    # replay_trace agrees with the CLI
    reports = replay_trace(path)
    assert len(reports) == 1 and reports[0].ok


def test_cli_replay_delta_record_byte_identical(tmp_path, capsys):
    """ISSUE 6 satellite: a DELTA-path record (solve encoded through a
    persistent ProblemState) replays byte-identically through the CLI.
    Replay always rebuilds the problem COLD, so a clean deterministic
    verdict on an encode_kind="delta" record pins the tentpole's
    determinism contract — delta encode == cold encode — forever."""
    from karpenter_tpu.flightrec.__main__ import main
    from karpenter_tpu.provisioning.problem_state import ProblemState
    rng = random.Random(2026)
    pools = gen_nodepools(rng)
    its = {p.name: gen_catalog(rng) for p in pools}
    pods = gen_pods(random.Random(2027), pools)
    ps = ProblemState()
    ts = TensorScheduler(pools, its, problem_state=ps)
    ts.solve(pods)  # cold pass seeds the persistent state
    rec = FlightRecorder(capacity=4)
    ts2 = TensorScheduler(pools, its, problem_state=ps)
    ts2.flight_recorder = rec
    ts2.solve(pods)
    assert ts2.encode_kind == "delta", ts2.fallback_reason
    loaded = loads_record(rec.lines()[-1])
    assert loaded["meta"]["encode_kind"] == "delta"
    path = str(tmp_path / "delta.jsonl")
    assert rec.dump(path) == 1
    assert main(["replay", path]) == 0
    out = capsys.readouterr().out
    assert "deterministic=ok" in out
    assert "0 verdict failures" in out


def test_cli_rejects_future_schema(tmp_path, capsys):
    from karpenter_tpu.flightrec.__main__ import main
    path = str(tmp_path / "future.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 99, "kind": "provisioning"}) + "\n")
    assert main(["replay", path]) == 2
    assert "v99" in capsys.readouterr().err


def test_deferred_encode_filters_bound_batch_from_cluster_view():
    """A deferred materialize sees the LIVE cluster view — including the
    solve's own pods after the provisioner binds them. The encode must
    drop them (they were pending at solve time), or replay counts the
    batch's topology against itself and reports a false nondeterminism."""
    from factories import spread_zone
    pods = [make_pod(name=f"cv-{i}", labels={"app": "cv"},
                     spread=[spread_zone(max_skew=1, key="app", value="cv")])
            for i in range(2)]
    bystander = make_pod(name="cv-other", labels={"app": "cv"})
    bystander.spec.node_name = "node-a"
    for p in pods:
        p.spec.node_name = "node-a"  # bound AFTER the solve, pre-dump

    class LiveView:
        def list_pods(self, namespace, selector):
            return [p for p in pods + [bystander]
                    if selector.matches(p.labels)]

        def node_labels(self, node_name):
            return {"topology.kubernetes.io/zone": "test-zone-a"}

        def for_pods_with_anti_affinity(self):
            return iter(())

    payload = encode_solve_payload([make_nodepool()], {"default": []}, pods,
                                   cluster=LiveView())
    uids = {p["uid"] for p in payload["cluster"]["pods"]}
    assert bystander.uid in uids
    assert not ({p.uid for p in pods} & uids), \
        "batch pods leaked into the recorded cluster view"


# -- state-node wire fidelity (host ports ride the encode) ------------------


def test_state_node_host_ports_roundtrip():
    from karpenter_tpu.sidecar.codec import WireStateNode, state_node_to_dict
    d = {"name": "n1", "labels": {}, "taints": [], "allocatable": {},
         "capacity": {}, "pod_requests": {}, "daemonset_requests": {},
         "initialized": True, "managed": False,
         "host_ports": [["uid-1", "0.0.0.0", 8080, "TCP"]]}
    sn = WireStateNode(d)
    assert sn.host_port_usage().conflicts_triples(
        [("0.0.0.0", 8080, "TCP")])
    assert not sn.host_port_usage().conflicts_triples(
        [("0.0.0.0", 9090, "TCP")])
    assert sn.managed() is False
    d2 = state_node_to_dict(sn)
    assert d2["host_ports"] == [["uid-1", "0.0.0.0", 8080, "TCP"]]
    assert d2["managed"] is False


# -- wall-clock-leak satellites ---------------------------------------------


def test_condition_default_timestamp_follows_injected_clock():
    from karpenter_tpu.api import nodeclaim as nc_api
    prev = nc_api.set_condition_clock(FakeClock(42.0))
    try:
        cs = nc_api.ConditionSet()
        cs.set_true("Launched", reason="Test")  # no explicit now
        assert cs.get("Launched").last_transition_time == 42.0
    finally:
        nc_api.set_condition_clock(prev)


def test_envtest_timestamps_follow_injected_clock():
    from karpenter_tpu.kube.envtest import EnvtestServer
    from karpenter_tpu.kube.k8s_codec import ts_to_k8s
    clock = FakeClock(1_700_000_000.0)
    with EnvtestServer(clock=clock) as srv:
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/pods",
            data=json.dumps({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "p1",
                             "finalizers": ["test/finalizer"]},
                "spec": {}}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        created = json.loads(urllib.request.urlopen(req).read())
        assert created["metadata"]["creationTimestamp"] == \
            ts_to_k8s(1_700_000_000.0)
        clock.step(30.0)
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/pods/p1", method="DELETE")
        deleted = json.loads(urllib.request.urlopen(req).read())
        assert deleted["metadata"]["deletionTimestamp"] == \
            ts_to_k8s(1_700_000_030.0)
