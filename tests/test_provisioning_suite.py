"""Scenario port of /root/reference/pkg/controllers/provisioning/
suite_test.go (2,253 LoC): batcher windows, deleting-NodePool gating,
init/sidecar-container resource math, nodeclaim request shapes (owner refs,
hash stability), daemonset schedulability edges, and partial scheduling
under limits."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.nodepool import NodePool
from karpenter_tpu.api.objects import (Node, NodeSelectorRequirement, Pod,
                                       Taint, Toleration)
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import (BATCH_IDLE_SECONDS,
                                                    BATCH_MAX_SECONDS, Batcher,
                                                    Binder, PodTrigger,
                                                    Provisioner)
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod

OD = {api_labels.CAPACITY_TYPE_LABEL_KEY: api_labels.CAPACITY_TYPE_ON_DEMAND}


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.provisioner = provisioner
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


class TestBatcher:
    """suite_test.go:115-206."""

    def test_fires_after_idle_window(self):
        clock = FakeClock()
        b = Batcher(clock)
        b.trigger()
        assert not b.ready()
        clock.step(BATCH_IDLE_SECONDS + 0.01)
        assert b.ready()

    def test_new_pod_extends_idle_window(self):
        clock = FakeClock()
        b = Batcher(clock)
        b.trigger()
        clock.step(BATCH_IDLE_SECONDS * 0.8)
        b.trigger()  # new arrival: idle window restarts
        clock.step(BATCH_IDLE_SECONDS * 0.8)
        assert not b.ready()
        clock.step(BATCH_IDLE_SECONDS * 0.3)
        assert b.ready()

    def test_max_window_caps_extension(self):
        clock = FakeClock()
        b = Batcher(clock)
        b.trigger()
        # keep poking just inside the idle window forever
        elapsed = 0.0
        while elapsed < BATCH_MAX_SECONDS:
            clock.step(BATCH_IDLE_SECONDS * 0.9)
            elapsed += BATCH_IDLE_SECONDS * 0.9
            b.trigger()
        assert b.ready()  # max duration wins

    def test_reset_clears_window(self):
        clock = FakeClock()
        b = Batcher(clock)
        b.trigger()
        clock.step(BATCH_IDLE_SECONDS + 1)
        b.reset()
        assert not b.ready()


class TestDeletingNodePool:
    """suite_test.go:216-226."""

    def test_deleting_nodepool_receives_no_capacity(self, env):
        pool = make_nodepool(name="default")
        pool.metadata.finalizers.append("karpenter.sh/termination")
        env.store.create(pool)
        env.store.delete(pool)  # finalizer holds it: deleting, still listed
        env.store.create(make_pod(cpu="500m"))
        settle(env)
        assert env.store.list(NodeClaim) == []
        assert env.store.list(Node) == []

    def test_live_pool_still_used_when_other_deletes(self, env):
        doomed = make_nodepool(name="doomed")
        doomed.metadata.finalizers.append("karpenter.sh/termination")
        env.store.create(doomed)
        env.store.delete(doomed)
        env.store.create(make_nodepool(name="live"))
        env.store.create(make_pod(cpu="500m"))
        settle(env)
        claims = env.store.list(NodeClaim)
        assert len(claims) == 1
        assert claims[0].nodepool_name == "live"


class TestSidecarContainerMath:
    """suite_test.go:424-578: native sidecars (init containers with
    restartPolicy=Always) run for the pod's whole life, so they ADD to the
    main containers but also accompany every later init container."""

    def _pod(self, containers, inits):
        p = make_pod()
        p.container_requests = [res.parse_list({"cpu": c}) for c in containers]
        p.init_container_requests = [
            (res.parse_list({"cpu": c}), True) if sidecar
            else res.parse_list({"cpu": c})
            for c, sidecar in inits]
        return p

    def test_init_before_sidecar(self):
        """init runs alone (1500m), THEN the sidecar starts: steady state
        1000m + 500m = 1500m, peak = 1500m."""
        p = self._pod(["1"], [("1500m", False), ("500m", True)])
        assert p.requests()["cpu"] == 1500

    def test_sidecar_before_init_smaller_init(self):
        """sidecar (500m) is already running when the init (700m) runs:
        peak = 1200m, steady state = 1500m -> 1500m wins."""
        p = self._pod(["1"], [("500m", True), ("700m", False)])
        assert p.requests()["cpu"] == 1500

    def test_sidecar_before_init_bigger_init(self):
        """init (1500m) runs alongside the earlier sidecar (500m):
        peak = 2000m beats steady state 1500m."""
        p = self._pod(["1"], [("500m", True), ("1500m", False)])
        assert p.requests()["cpu"] == 2000

    def test_plain_init_max_semantics(self):
        p = self._pod(["250m", "250m"], [("1", False), ("2", False)])
        assert p.requests()["cpu"] == 2000

    def test_scheduling_accounts_for_sidecar_peak(self, env):
        """A pod whose init+sidecar peak exceeds the sum of its containers
        must get a node sized for the peak."""
        env.store.create(make_nodepool(name="default"))
        p = self._pod(["1"], [("500m", True), ("2500m", False)])
        p.spec.node_selector = dict(OD)
        env.store.create(p)
        settle(env)
        [nc] = env.store.list(NodeClaim)
        # peak = 3000m + pod overhead; a 2-cpu shape can't hold it
        assert nc.spec.resources_requests["cpu"] >= 3000


class TestNodeClaimRequestShape:
    """suite_test.go:353-383, 1335-1612."""

    def test_owner_reference_points_at_nodepool(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="500m"))
        settle(env)
        [nc] = env.store.list(NodeClaim)
        [ref] = [r for r in nc.metadata.owner_refs if r.kind == "NodePool"]
        assert ref.name == "default"
        assert ref.block_owner_deletion

    def test_hash_annotation_stamped_from_scheduling_time_pool(self, env):
        """suite_test.go:353-383: the claim's nodepool-hash annotation must
        match the pool revision that scheduled it."""
        pool = make_nodepool(name="default")
        env.store.create(pool)
        env.store.create(make_pod(cpu="500m"))
        settle(env)
        [nc] = env.store.list(NodeClaim)
        assert nc.metadata.annotations[
            api_labels.NODEPOOL_HASH_ANNOTATION_KEY] == pool.static_hash()

    def test_pool_requirements_propagate_to_claim(self, env):
        pool = make_nodepool(name="default", requirements=[
            NodeSelectorRequirement(api_labels.LABEL_ARCH, "In", ("amd64",))])
        env.store.create(pool)
        env.store.create(make_pod(cpu="500m"))
        settle(env)
        [nc] = env.store.list(NodeClaim)
        by_key = {r.key: r for r in nc.spec.requirements}
        assert tuple(by_key[api_labels.LABEL_ARCH].values) == ("amd64",)
        assert api_labels.LABEL_INSTANCE_TYPE in by_key

    def test_resource_requests_include_daemon_overhead(self, env):
        env.store.create(make_nodepool(name="default"))
        ds = make_pod(cpu="250m")
        ds.is_daemonset_pod = True
        env.store.create(ds)
        env.store.create(make_pod(cpu="500m", name="workload"))
        settle(env)
        claims = env.store.list(NodeClaim)
        assert claims
        # requests cover workload + daemonset overhead + pod slots
        assert claims[0].spec.resources_requests["cpu"] >= 750


class TestDaemonSetSchedulability:
    """suite_test.go:912-1187: which daemonsets count toward overhead."""

    def _provision(self, env, ds, pool=None, workload_tolerations=()):
        env.store.create(pool or make_nodepool(name="default"))
        ds.is_daemonset_pod = True
        env.store.create(ds)
        env.store.create(make_pod(cpu="500m", name="workload",
                                  tolerations=list(workload_tolerations)))
        settle(env)
        claims = env.store.list(NodeClaim)
        assert claims
        return claims[0]

    def test_daemonset_without_matching_toleration_ignored(self, env):
        """suite_test.go:912-943: pool taints the nodes; a daemonset that
        doesn't tolerate them can't run there, so no overhead."""
        pool = make_nodepool(name="default",
                             taints=[Taint(key="team", value="a",
                                           effect="NoSchedule")])
        ds = make_pod(cpu="2")
        nc = self._provision(env, ds, pool=pool, workload_tolerations=[
            Toleration(key="team", operator="Equal", value="a",
                       effect="NoSchedule")])
        assert nc.spec.resources_requests["cpu"] < 2000

    def test_tolerating_daemonset_counted(self, env):
        pool = make_nodepool(name="default",
                             taints=[Taint(key="team", value="a",
                                           effect="NoSchedule")])
        ds = make_pod(cpu="2", tolerations=[
            Toleration(key="team", operator="Equal", value="a",
                       effect="NoSchedule")])
        nc = self._provision(env, ds, pool=pool, workload_tolerations=[
            Toleration(key="team", operator="Equal", value="a",
                       effect="NoSchedule")])
        assert nc.spec.resources_requests["cpu"] >= 2500

    def test_daemonset_with_incompatible_node_selector_ignored(self, env):
        ds = make_pod(cpu="2", node_selector={"example.com/fleet": "other"})
        nc = self._provision(env, ds)
        assert nc.spec.resources_requests["cpu"] < 2000

    def test_daemonset_with_incompatible_preference_still_counted(self, env):
        """suite_test.go:1121-1148: preferences relax, so the daemonset still
        lands and must be counted."""
        ds = make_pod(cpu="2", preferred_affinity=[
            (1, [NodeSelectorRequirement("example.com/fleet", "In",
                                         ("other",))])])
        nc = self._provision(env, ds)
        assert nc.spec.resources_requests["cpu"] >= 2500

    def test_daemonset_notin_on_unspecified_key_counted(self, env):
        """suite_test.go:966-988: NotIn on a key the node doesn't define is
        satisfied."""
        ds = make_pod(cpu="2", required_affinity=[
            [NodeSelectorRequirement("example.com/fleet", "NotIn",
                                     ("other",))]])
        nc = self._provision(env, ds)
        assert nc.spec.resources_requests["cpu"] >= 2500


class TestLimitsPartialScheduling:
    """suite_test.go:579-721."""

    def test_partial_schedule_when_limits_hit(self, env):
        pool = make_nodepool(name="default", limits={"cpu": "3"})
        env.store.create(pool)
        for i in range(4):
            env.store.create(make_pod(cpu="1500m", name=f"p-{i}",
                                      node_selector=dict(OD)))
        settle(env, rounds=8)
        scheduled = [p for p in env.store.list(Pod) if p.spec.node_name]
        unscheduled = [p for p in env.store.list(Pod) if not p.spec.node_name]
        assert scheduled, "some pods must schedule inside the limit"
        assert unscheduled, "the limit must strand the rest"

    def test_no_schedule_when_limits_already_exceeded(self, env):
        pool = make_nodepool(name="default", limits={"cpu": "1"})
        env.store.create(pool)
        env.store.create(make_pod(cpu="1500m", node_selector=dict(OD)))
        settle(env)
        assert env.store.list(NodeClaim) == []

    def test_scheduling_resumes_when_limit_lifted(self, env):
        pool = make_nodepool(name="default", limits={"cpu": "1"})
        env.store.create(pool)
        env.store.create(make_pod(cpu="1500m", node_selector=dict(OD)))
        settle(env)
        assert env.store.list(NodeClaim) == []
        pool.spec.limits = {}
        env.store.update(pool)
        env.provisioner.trigger()
        settle(env)
        assert len(env.store.list(NodeClaim)) == 1


class TestDeletingNodeCarryover:
    """suite_test.go:384-423: pods bound to a deleting node are re-planned
    onto ONE new node (they ride the pending set; the deleting node is not
    packable)."""

    def test_pods_on_deleting_node_consolidate_onto_one_replacement(self, env):
        env.store.create(make_nodepool(name="default"))
        pods = [make_pod(cpu="500m", name=f"carry-{i}") for i in range(3)]
        for p in pods:
            env.store.create(p)
        settle(env)
        [nc] = env.store.list(NodeClaim)
        node = env.store.get(Node, nc.status.node_name)
        assert all(env.store.get(Pod, p.name, p.namespace).spec.node_name
                   == node.name for p in pods)
        # the node starts deleting (finalizer holds it); pods stay bound —
        # the drain unbinds them later — but provisioning must already plan
        # replacement capacity for them, together, on ONE new claim
        env.store.delete(node)
        env.provisioner.trigger()
        settle(env, rounds=8)
        new_claims = [c for c in env.store.list(NodeClaim)
                      if c.name != nc.name]
        assert len(new_claims) == 1
        # sized for all three carried pods (3 x 500m + slots)
        assert new_claims[0].spec.resources_requests["cpu"] >= 1500


class TestNodePoolWeightPriority:
    """suite_test.go:2175+: the highest-weight pool wins when multiple can
    satisfy the pod."""

    def test_highest_weight_pool_always_selected(self, env):
        env.store.create(make_nodepool(name="light", weight=1))
        env.store.create(make_nodepool(name="heavy", weight=100))
        for i in range(3):
            env.store.create(make_pod(cpu="500m", name=f"w-{i}"))
            settle(env, rounds=3)
        for nc in env.store.list(NodeClaim):
            assert nc.nodepool_name == "heavy"

    def test_weight_loser_takes_overflow_when_winner_limited(self, env):
        heavy = make_nodepool(name="heavy", weight=100, limits={"cpu": "1"})
        env.store.create(heavy)
        env.store.create(make_nodepool(name="light", weight=1))
        env.store.create(make_pod(cpu="1500m", name="big",
                                  node_selector=dict(OD)))
        settle(env)
        [nc] = env.store.list(NodeClaim)
        assert nc.nodepool_name == "light"  # heavy's limit excluded it
