"""Partitioned solve: tensor bulk + host stragglers sharing one capacity/
topology state (VERDICT r1 item 4; scheduler.go:267-283 semantics per pod)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import HostPort, LabelSelector, TopologySpreadConstraint
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.provisioning.grouping import partition_pods
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import make_nodepool, make_pod, make_pods, make_scheduler, spread_zone


def _its(n=48):
    return construct_instance_types()[:n]


class TestPartitionPods:
    def test_clean_batch_has_no_leftover(self):
        pods = make_pods(10, cpu="100m") + make_pods(
            5, cpu="200m", labels={"app": "s"},
            spread=[spread_zone(key="app", value="s")])
        groups, leftover, reason = partition_pods(pods)
        assert len(groups) == 2 and not leftover and reason == ""

    def test_host_port_pods_split_out(self):
        plain = make_pods(10, cpu="100m")
        ported = [make_pod(cpu="100m", host_ports=[HostPort(port=8080 + i)])
                  for i in range(3)]
        groups, leftover, reason = partition_pods(plain + ported)
        assert sum(g.count for g in groups) == 10
        assert len(leftover) == 3
        assert "host port" in reason

    def test_coupled_groups_both_demoted(self):
        # A's spread selector {tier=x} self-matches AND matches B's labels:
        # shared domain counts -> both must be host-side
        sel = LabelSelector(match_labels={"tier": "x"})
        spread = [TopologySpreadConstraint(
            topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=1,
            label_selector=sel)]
        a = make_pods(4, cpu="100m", labels={"app": "a", "tier": "x"},
                      spread=spread)
        b = make_pods(4, cpu="200m", labels={"app": "b", "tier": "x"})
        c = make_pods(4, cpu="300m", labels={"app": "c"})
        groups, leftover, reason = partition_pods(a + b + c)
        assert sum(g.count for g in groups) == 4          # only c stays
        assert len(leftover) == 8
        assert "couple" in reason

    def test_leftover_coupling_demotes_group(self):
        # the host-port pod's spread selector matches group A's labels:
        # A's counts are shared with a host-path pod -> A demoted too
        sel = LabelSelector(match_labels={"app": "a"})
        ported = [make_pod(cpu="100m", labels={"app": "a"},
                           host_ports=[HostPort(port=9000)],
                           spread=[TopologySpreadConstraint(
                               topology_key=api_labels.LABEL_TOPOLOGY_ZONE,
                               max_skew=1, label_selector=sel)])]
        a = make_pods(4, cpu="100m", labels={"app": "a"})
        c = make_pods(4, cpu="300m", labels={"app": "c"})
        groups, leftover, reason = partition_pods(ported + a + c)
        assert sum(g.count for g in groups) == 4          # only c stays
        assert len(leftover) == 5


class TestPartitionedSolve:
    def test_mixed_batch_fully_schedules(self):
        its = _its()
        pool = make_nodepool()
        plain = make_pods(40, cpu="500m", memory="256Mi")
        spreadp = make_pods(12, cpu="250m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
        ported = [make_pod(cpu="100m", host_ports=[HostPort(port=8080 + i)])
                  for i in range(4)]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + spreadp + ported)
        assert not r.pod_errors
        assert ts.partition == (52, 4)
        assert ts.fallback_reason == ""
        placed = sum(len(nc.pods) for nc in r.new_nodeclaims) + \
            sum(len(en.pods) for en in r.existing_nodes)
        assert placed == 56

    def test_stragglers_pack_into_tensor_nodes(self):
        """The host pass must reuse the tensor bulk's in-flight nodes, not
        open new ones (scheduler.go:276-283)."""
        its = _its()
        pool = make_nodepool()
        plain = make_pods(10, cpu="100m", memory="64Mi")
        ported = [make_pod(cpu="100m", memory="64Mi",
                           host_ports=[HostPort(port=8080)])]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + ported)
        assert not r.pod_errors
        # everything fits one cheap node: straggler joins the tensor claim
        assert len(r.new_nodeclaims) == 1
        assert len(r.new_nodeclaims[0].pods) == 11

    def test_host_port_conflicts_respected_in_partition(self):
        its = _its()
        pool = make_nodepool()
        plain = make_pods(6, cpu="100m")
        clash = [make_pod(cpu="100m", host_ports=[HostPort(port=9090)])
                 for _ in range(2)]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + clash)
        assert not r.pod_errors
        # the two clashing pods can never share a node
        nodes_with_ports = [
            nc for nc in r.new_nodeclaims
            if any(p.spec.host_ports for p in nc.pods)]
        for nc in nodes_with_ports:
            ported = [p for p in nc.pods if p.spec.host_ports]
            assert len(ported) <= 1

    def test_node_count_parity_with_pure_host(self):
        its = _its()
        pool = make_nodepool()
        pods = (make_pods(30, cpu="500m", memory="256Mi")
                + make_pods(10, cpu="1000m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
                + [make_pod(cpu="500m", host_ports=[HostPort(port=8000 + i)])
                   for i in range(2)])
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(list(pods))
        host = make_scheduler([pool], its, list(pods))
        rh = host.solve(list(pods))
        assert not r.pod_errors and not rh.pod_errors
        assert abs(len(r.new_nodeclaims) - len(rh.new_nodeclaims)) <= \
            max(1, len(rh.new_nodeclaims) // 50 + 1)

    def test_remainder_sees_tensor_topology_counts(self):
        """Retry pods share the spread selector with their tensor-placed
        groupmates, so the host remainder's skew arithmetic must count the
        tensor half (ADVICE r2 medium): 5 tensor-placed pods leave zone
        counts (2,1,1,1); 3 retries must fill the three 1-count zones, not
        re-spread from zero into (3,2,2,1)."""
        its = _its()
        pool = make_nodepool()
        spreadp = make_pods(5, cpu="100m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
        ts = TensorScheduler([pool], {"default": its})
        r0 = ts.solve(list(spreadp))
        assert not r0.pod_errors and ts.fallback_reason == ""
        retries = make_pods(3, cpu="100m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
        r = ts._host_solve_remainder(retries, r0)
        assert not r.pod_errors
        counts = {}
        for nc in r.new_nodeclaims:
            zr = nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
            zones = zr.values_list()
            assert len(zones) == 1
            counts[zones[0]] = counts.get(zones[0], 0) + len(nc.pods)
        assert sum(counts.values()) == 8
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_limits_shared_across_partition(self):
        """NodePool limits consumed by the tensor bulk must constrain the
        host stragglers too."""
        its = _its()
        pool = make_nodepool(limits={"cpu": "8"})
        plain = make_pods(12, cpu="500m", memory="128Mi")
        ported = [make_pod(cpu="4000m", host_ports=[HostPort(port=8080)])]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + ported)
        # total cpu of launched claims stays within the 8-cpu pool limit
        # modulo the reference's subtractMax pessimism (never exceeds by
        # more than one max-instance)
        launched = sum(nc.requests.get("cpu", 0) for nc in r.new_nodeclaims)
        biggest = max(it.capacity.get("cpu", 0) for it in its)
        assert launched <= 8000 + biggest
