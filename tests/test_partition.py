"""Partitioned solve: tensor bulk + host stragglers sharing one capacity/
topology state (VERDICT r1 item 4; scheduler.go:267-283 semantics per pod)."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.objects import HostPort, LabelSelector, TopologySpreadConstraint
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.provisioning.grouping import partition_pods
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import make_nodepool, make_pod, make_pods, make_scheduler, spread_zone


def _its(n=48):
    return construct_instance_types()[:n]


class TestPartitionPods:
    def test_clean_batch_has_no_leftover(self):
        pods = make_pods(10, cpu="100m") + make_pods(
            5, cpu="200m", labels={"app": "s"},
            spread=[spread_zone(key="app", value="s")])
        groups, leftover, reason = partition_pods(pods)
        assert len(groups) == 2 and not leftover and reason == ""

    def test_unique_host_ports_merge_into_ordinary_groups(self):
        """Host-port pods tensorize (round 5): batch-unique unoccupied
        ports constrain nothing, so their pods merge into the same group
        as port-free pods of identical spec instead of exploding G."""
        plain = make_pods(10, cpu="100m")
        ported = [make_pod(cpu="100m", host_ports=[HostPort(port=8080 + i)])
                  for i in range(3)]
        groups, leftover, reason = partition_pods(
            plain + ported, port_occupied=lambda t: False)
        assert sum(g.count for g in groups) == 13
        assert not leftover
        assert not any(g.host_ports for g in groups)

    def test_conflicting_host_ports_make_capped_groups(self):
        """The same (port, protocol) used twice conflicts: those pods form
        per-spec groups carrying their triples (one pod per node)."""
        clash = [make_pod(cpu="100m", labels={"app": f"c{i}"},
                          host_ports=[HostPort(port=9000)])
                 for i in range(2)]
        groups, leftover, reason = partition_pods(
            clash, port_occupied=lambda t: False)
        assert not leftover
        assert len(groups) == 2
        assert all(g.host_ports == (("0.0.0.0", 9000, "TCP"),)
                   for g in groups)

    def test_occupied_port_makes_capped_group(self):
        """A port in use on an existing node flips its pods to conflicted
        even when batch-unique."""
        pod = make_pod(cpu="100m", host_ports=[HostPort(port=8080)])
        groups, leftover, reason = partition_pods(
            [pod], port_occupied=lambda t: any(p == 8080 for _, p, _ in t))
        assert not leftover
        [g] = groups
        assert g.host_ports == (("0.0.0.0", 8080, "TCP"),)

    def test_without_checker_port_pods_demote(self):
        """Callers that can't vouch for existing-node usage (prefix sim,
        dryrun via group_pods) keep the round-4 demotion."""
        ported = [make_pod(cpu="100m", host_ports=[HostPort(port=8080)])]
        groups, leftover, reason = partition_pods(ported)
        assert not groups and len(leftover) == 1
        assert "host ports require per-pod conflict tracking" in reason

    def test_host_port_with_hostname_affinity_demotes(self):
        from factories import affinity_term
        ported = [make_pod(cpu="100m", labels={"app": "x"},
                           host_ports=[HostPort(port=8080)],
                           pod_affinity=[affinity_term(
                               api_labels.LABEL_HOSTNAME,
                               key="app", value="x")])
                  for _ in range(2)]
        groups, leftover, reason = partition_pods(
            ported, port_occupied=lambda t: False)
        assert not groups
        assert len(leftover) == 2
        assert "host ports with hostname pod-affinity" in reason

    def test_coupled_groups_both_demoted(self):
        # A's spread selector {tier=x} self-matches AND matches B's labels:
        # shared domain counts -> both must be host-side
        sel = LabelSelector(match_labels={"tier": "x"})
        spread = [TopologySpreadConstraint(
            topology_key=api_labels.LABEL_TOPOLOGY_ZONE, max_skew=1,
            label_selector=sel)]
        a = make_pods(4, cpu="100m", labels={"app": "a", "tier": "x"},
                      spread=spread)
        b = make_pods(4, cpu="200m", labels={"app": "b", "tier": "x"})
        c = make_pods(4, cpu="300m", labels={"app": "c"})
        groups, leftover, reason = partition_pods(a + b + c)
        assert sum(g.count for g in groups) == 4          # only c stays
        assert len(leftover) == 8
        assert "couple" in reason

    def test_leftover_coupling_demotes_group(self):
        # the host-port pod's spread selector matches group A's labels:
        # A's counts are shared with a host-path pod -> A demoted too
        sel = LabelSelector(match_labels={"app": "a"})
        ported = [make_pod(cpu="100m", labels={"app": "a"},
                           host_ports=[HostPort(port=9000)],
                           spread=[TopologySpreadConstraint(
                               topology_key=api_labels.LABEL_TOPOLOGY_ZONE,
                               max_skew=1, label_selector=sel)])]
        a = make_pods(4, cpu="100m", labels={"app": "a"})
        c = make_pods(4, cpu="300m", labels={"app": "c"})
        groups, leftover, reason = partition_pods(ported + a + c)
        assert sum(g.count for g in groups) == 4          # only c stays
        assert len(leftover) == 5


class TestPartitionedSolve:
    def test_mixed_batch_fully_schedules(self):
        its = _its()
        pool = make_nodepool()
        plain = make_pods(40, cpu="500m", memory="256Mi")
        spreadp = make_pods(12, cpu="250m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
        ported = [make_pod(cpu="100m", host_ports=[HostPort(port=8080 + i)])
                  for i in range(4)]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + spreadp + ported)
        assert not r.pod_errors
        # host-port pods tensorize now: the whole batch rides the kernel
        assert ts.partition == (56, 0)
        assert ts.fallback_reason == ""
        placed = sum(len(nc.pods) for nc in r.new_nodeclaims) + \
            sum(len(en.pods) for en in r.existing_nodes)
        assert placed == 56

    def test_stragglers_pack_into_tensor_nodes(self):
        """The host pass must reuse the tensor bulk's in-flight nodes, not
        open new ones (scheduler.go:276-283)."""
        its = _its()
        pool = make_nodepool()
        plain = make_pods(10, cpu="100m", memory="64Mi")
        ported = [make_pod(cpu="100m", memory="64Mi",
                           host_ports=[HostPort(port=8080)])]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + ported)
        assert not r.pod_errors
        # everything fits one cheap node: straggler joins the tensor claim
        assert len(r.new_nodeclaims) == 1
        assert len(r.new_nodeclaims[0].pods) == 11

    def test_host_port_conflicts_respected_in_partition(self):
        its = _its()
        pool = make_nodepool()
        plain = make_pods(6, cpu="100m")
        clash = [make_pod(cpu="100m", host_ports=[HostPort(port=9090)])
                 for _ in range(2)]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + clash)
        assert not r.pod_errors
        # the two clashing pods can never share a node
        nodes_with_ports = [
            nc for nc in r.new_nodeclaims
            if any(p.spec.host_ports for p in nc.pods)]
        for nc in nodes_with_ports:
            ported = [p for p in nc.pods if p.spec.host_ports]
            assert len(ported) <= 1

    def test_node_count_parity_with_pure_host(self):
        its = _its()
        pool = make_nodepool()
        pods = (make_pods(30, cpu="500m", memory="256Mi")
                + make_pods(10, cpu="1000m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
                + [make_pod(cpu="500m", host_ports=[HostPort(port=8000 + i)])
                   for i in range(2)])
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(list(pods))
        host = make_scheduler([pool], its, list(pods))
        rh = host.solve(list(pods))
        assert not r.pod_errors and not rh.pod_errors
        assert abs(len(r.new_nodeclaims) - len(rh.new_nodeclaims)) <= \
            max(1, len(rh.new_nodeclaims) // 50 + 1)

    def test_remainder_sees_tensor_topology_counts(self):
        """Retry pods share the spread selector with their tensor-placed
        groupmates, so the host remainder's skew arithmetic must count the
        tensor half (ADVICE r2 medium): 5 tensor-placed pods leave zone
        counts (2,1,1,1); 3 retries must fill the three 1-count zones, not
        re-spread from zero into (3,2,2,1)."""
        its = _its()
        pool = make_nodepool()
        spreadp = make_pods(5, cpu="100m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
        ts = TensorScheduler([pool], {"default": its})
        r0 = ts.solve(list(spreadp))
        assert not r0.pod_errors and ts.fallback_reason == ""
        retries = make_pods(3, cpu="100m", labels={"app": "s"},
                            spread=[spread_zone(key="app", value="s")])
        r = ts._host_solve_remainder(retries, r0)
        assert not r.pod_errors
        counts = {}
        for nc in r.new_nodeclaims:
            zr = nc.requirements.get(api_labels.LABEL_TOPOLOGY_ZONE)
            zones = zr.values_list()
            assert len(zones) == 1
            counts[zones[0]] = counts.get(zones[0], 0) + len(nc.pods)
        assert sum(counts.values()) == 8
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_limits_shared_across_partition(self):
        """NodePool limits consumed by the tensor bulk must constrain the
        host stragglers too."""
        its = _its()
        pool = make_nodepool(limits={"cpu": "8"})
        plain = make_pods(12, cpu="500m", memory="128Mi")
        ported = [make_pod(cpu="4000m", host_ports=[HostPort(port=8080)])]
        ts = TensorScheduler([pool], {"default": its})
        r = ts.solve(plain + ported)
        # total cpu of launched claims stays within the 8-cpu pool limit
        # modulo the reference's subtractMax pessimism (never exceeds by
        # more than one max-instance)
        launched = sum(nc.requests.get("cpu", 0) for nc in r.new_nodeclaims)
        biggest = max(it.capacity.get("cpu", 0) for it in its)
        assert launched <= 8000 + biggest


class TestTensorHostPorts:
    """hostportusage.go:34-90 semantics on the tensor path (round 5): same
    port+protocol with overlapping IPs conflicts; distinct protocols, ports,
    or disjoint specific IPs coexist; existing usage excludes nodes. Every
    scenario asserts tensor-vs-host parity (fallback_reason stays empty)."""

    def _solve(self, pods, state_nodes=()):
        ts = TensorScheduler([make_nodepool()], {"default": _its()},
                             state_nodes=list(state_nodes),
                             force_tensor=True)
        r = ts.solve(pods)
        assert ts.fallback_reason == "", ts.fallback_reason
        return r

    def _host(self, pods, state_nodes=()):
        s = make_scheduler([make_nodepool()], _its(), pods,
                           state_nodes=list(state_nodes))
        return s.solve(pods)

    def test_same_port_group_one_pod_per_node(self):
        pods = [make_pod(cpu="100m", name=f"p-{i}",
                         host_ports=[HostPort(port=8080)])
                for i in range(5)]
        t = self._solve(pods)
        assert not t.pod_errors
        assert len(t.new_nodeclaims) == 5
        for nc in t.new_nodeclaims:
            assert len(nc.pods) == 1
        h = self._host([make_pod(cpu="100m", name=f"h-{i}",
                                 host_ports=[HostPort(port=8080)])
                        for i in range(5)])
        assert len(h.new_nodeclaims) == 5

    def test_conflicting_groups_never_share_a_node(self):
        a = [make_pod(cpu="100m", labels={"app": "a"}, name=f"a-{i}",
                      host_ports=[HostPort(port=9000)]) for i in range(3)]
        b = [make_pod(cpu="200m", labels={"app": "b"}, name=f"b-{i}",
                      host_ports=[HostPort(port=9000)]) for i in range(3)]
        t = self._solve(a + b)
        assert not t.pod_errors
        for nc in t.new_nodeclaims:
            ported = [p for p in nc.pods if p.spec.host_ports]
            assert len(ported) <= 1, "port 9000 double-booked on one node"

    def test_distinct_ports_can_share_a_node(self):
        a = [make_pod(cpu="100m", labels={"app": "a"}, name=f"a-{i}",
                      host_ports=[HostPort(port=9000)]) for i in range(2)]
        b = [make_pod(cpu="100m", labels={"app": "b"}, name=f"b-{i}",
                      host_ports=[HostPort(port=9001)]) for i in range(2)]
        filler = make_pods(6, cpu="100m")
        t = self._solve(a + b + filler)
        assert not t.pod_errors
        # a 9000-pod and a 9001-pod may legally co-locate; the solve must
        # not open one node per ported pod when ports don't clash
        per_node = [sum(1 for p in nc.pods if p.spec.host_ports)
                    for nc in t.new_nodeclaims]
        assert max(per_node, default=0) >= 2

    def test_different_protocols_do_not_conflict(self):
        a = [make_pod(cpu="100m", labels={"app": "a"}, name=f"a-{i}",
                      host_ports=[HostPort(port=9000, protocol="TCP")])
             for i in range(2)]
        b = [make_pod(cpu="100m", labels={"app": "b"}, name=f"b-{i}",
                      host_ports=[HostPort(port=9000, protocol="UDP")])
             for i in range(2)]
        t = self._solve(a + b + make_pods(4, cpu="100m"))
        assert not t.pod_errors
        per_node = [sum(1 for p in nc.pods if p.spec.host_ports)
                    for nc in t.new_nodeclaims]
        assert max(per_node, default=0) >= 2

    def test_disjoint_specific_ips_do_not_conflict(self):
        a = [make_pod(cpu="100m", labels={"app": "a"}, name="ip-a",
                      host_ports=[HostPort(port=9000, host_ip="10.0.0.1")])]
        b = [make_pod(cpu="100m", labels={"app": "b"}, name="ip-b",
                      host_ports=[HostPort(port=9000, host_ip="10.0.0.2")])]
        t = self._solve(a + b + make_pods(4, cpu="100m"))
        assert not t.pod_errors
        per_node = [sum(1 for p in nc.pods if p.spec.host_ports)
                    for nc in t.new_nodeclaims]
        assert max(per_node, default=0) >= 2

    def test_wildcard_conflicts_with_specific_ip(self):
        a = [make_pod(cpu="100m", labels={"app": "a"}, name="w-a",
                      host_ports=[HostPort(port=9000)])]  # 0.0.0.0
        b = [make_pod(cpu="100m", labels={"app": "b"}, name="w-b",
                      host_ports=[HostPort(port=9000, host_ip="10.0.0.1")])]
        t = self._solve(a + b)
        assert not t.pod_errors
        for nc in t.new_nodeclaims:
            assert sum(1 for p in nc.pods if p.spec.host_ports) <= 1

    def test_existing_node_port_occupancy_excludes_node(self):
        from factories import make_state_node
        from karpenter_tpu.scheduling.hostports import get_host_ports
        sn = make_state_node("live-1", cpu="8", memory="16Gi")
        occupant = make_pod(cpu="100m", name="occupant",
                            host_ports=[HostPort(port=8080)])
        sn.host_port_usage().add(occupant, get_host_ports(occupant))
        newcomer = make_pod(cpu="100m", name="newcomer",
                            host_ports=[HostPort(port=8080)])
        t = self._solve([newcomer], state_nodes=[sn])
        assert not t.pod_errors
        # the live node's port is taken: a fresh node must open
        assert not any(en.pods for en in t.existing_nodes)
        assert len(t.new_nodeclaims) == 1
        # a non-conflicting port lands on the live node
        other = make_pod(cpu="100m", name="other",
                         host_ports=[HostPort(port=9090)])
        t2 = self._solve([other], state_nodes=[make_state_node(
            "live-2", cpu="8", memory="16Gi")])
        assert not t2.pod_errors

    def test_port_mix_parity_with_host_oracle(self):
        """The bench shape: 10% host-port stragglers now ride the kernel;
        node counts track the oracle within the 2% clause."""
        def batch(tag):
            plain = make_pods(36, cpu="500m", memory="512Mi")
            ported = [make_pod(cpu="100m", name=f"{tag}-{i}",
                               host_ports=[HostPort(port=8000 + (i % 3))])
                      for i in range(4)]
            return plain + ported
        t = self._solve(batch("t"))
        h = self._host(batch("h"))
        assert len(t.pod_errors) == len(h.pod_errors) == 0
        th, hh = len(t.new_nodeclaims), len(h.new_nodeclaims)
        assert abs(th - hh) <= max(1, round(0.02 * hh)), (th, hh)

    def test_conflicting_groups_never_share_an_existing_node(self):
        """Two conflicting port groups against ONE live node with headroom:
        the second group must see the port the first bound mid-pack (the
        pre-solve occupancy snapshot can't know it)."""
        from factories import make_state_node
        sn = make_state_node("live-big", cpu="32", memory="64Gi")
        a = make_pod(cpu="100m", labels={"app": "a"}, name="exa",
                     host_ports=[HostPort(port=9000)])
        b = make_pod(cpu="200m", labels={"app": "b"}, name="exb",
                     host_ports=[HostPort(port=9000)])
        t = self._solve([a, b], state_nodes=[sn])
        assert not t.pod_errors
        ported_on_live = sum(
            1 for en in t.existing_nodes for p in en.pods
            if p.spec.host_ports)
        assert ported_on_live <= 1, "port 9000 double-booked on live node"
