"""Scenario port of /root/reference/pkg/controllers/nodeclaim/disruption/
drift_test.go: static-hash drift (incl. hash-version gating), requirements
drift, stale-instance-type drift, drift-condition removal, per-pool
isolation, and the Consolidatable marker's consolidateAfter semantics."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import (COND_CONSOLIDATABLE, COND_DRIFTED,
                                         NodeClaim)
from karpenter_tpu.api.nodepool import NODEPOOL_HASH_VERSION
from karpenter_tpu.api.objects import Node, NodeSelectorRequirement, Pod
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_disruption import NodeClaimDisruptionMarker
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod

ZONE = api_labels.LABEL_TOPOLOGY_ZONE


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    marker = NodeClaimDisruptionMarker(store, cluster, provider, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner),
                 NodeClaimLifecycle(store, cluster, provider, clock), marker)

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.marker = marker
    return e


def settle(env, rounds=5):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def provision_one(env, pool=None, **pod_kw):
    env.store.create(pool or make_nodepool(name="default"))
    env.store.create(make_pod(**pod_kw))
    settle(env)
    claims = env.store.list(NodeClaim)
    assert len(claims) == 1 and claims[0].launched()
    return claims[0]


def remark(env, nc):
    """Force a marker pass on the claim and return its fresh state."""
    env.marker.reconcile(nc)
    return env.store.list(NodeClaim)[0]


class TestStaticDrift:
    def test_template_change_marks_drifted(self, env):
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m")
        assert not nc.conditions.is_true(COND_DRIFTED)
        pool.spec.template.metadata_labels["team"] = "x"
        env.store.update(pool)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "NodePoolDrifted"

    def test_hash_version_mismatch_suppresses_drift(self, env):
        """drift_test.go:497-510: an old-hash-version claim must NOT be
        marked static-drifted — its hash was computed under different rules
        (hydration re-stamps it first)."""
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m")
        nc.metadata.annotations[
            api_labels.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v1"
        env.store.update(nc)
        pool.spec.template.metadata_labels["team"] = "x"
        env.store.update(pool)
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED)

    def test_missing_hash_annotation_suppresses_drift(self, env):
        """drift_test.go:488-496."""
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m")
        nc.metadata.annotations.pop(
            api_labels.NODEPOOL_HASH_ANNOTATION_KEY, None)
        env.store.update(nc)
        pool.spec.template.metadata_labels["team"] = "x"
        env.store.update(pool)
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED)

    def test_drift_clears_when_pool_reverts(self, env):
        """drift_test.go:192-203."""
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m")
        pool.spec.template.metadata_labels["team"] = "x"
        env.store.update(pool)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        del pool.spec.template.metadata_labels["team"]
        env.store.update(pool)
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED)

    def test_only_claims_of_updated_pool_drift(self, env):
        """drift_test.go:355-480: two pools, one updated — only its claims
        drift."""
        pool_a = make_nodepool(name="pool-a")
        pool_b = make_nodepool(name="pool-b")
        env.store.create(pool_a)
        env.store.create(pool_b)
        env.store.create(make_pod(cpu="500m", name="pa", node_selector={
            api_labels.NODEPOOL_LABEL_KEY: "pool-a"}))
        env.store.create(make_pod(cpu="500m", name="pb", node_selector={
            api_labels.NODEPOOL_LABEL_KEY: "pool-b"}))
        settle(env)
        pool_a.spec.template.metadata_labels["team"] = "x"
        env.store.update(pool_a)
        for nc in list(env.store.list(NodeClaim)):
            env.marker.reconcile(nc)
        for nc in env.store.list(NodeClaim):
            drifted = nc.conditions.is_true(COND_DRIFTED)
            assert drifted == (nc.nodepool_name == "pool-a"), nc.metadata.name

    def test_no_drift_when_pool_missing(self, env):
        """drift_test.go:184-191."""
        nc = provision_one(env, cpu="500m")
        from karpenter_tpu.api.nodepool import NodePool
        env.store.delete(env.store.get(NodePool, "default"))
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED)


class TestRequirementsDrift:
    def test_pool_requirements_excluding_claim_mark_drifted(self, env):
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m",
                           node_selector={ZONE: "test-zone-a"})
        pool.spec.template.spec.requirements = [
            NodeSelectorRequirement(ZONE, "In", ("test-zone-b",))]
        env.store.update(pool)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "RequirementsDrifted"

    def test_compatible_requirement_change_no_drift(self, env):
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m",
                           node_selector={ZONE: "test-zone-a"})
        pool.spec.template.spec.requirements = [
            NodeSelectorRequirement(ZONE, "In",
                                    ("test-zone-a", "test-zone-b"))]
        env.store.update(pool)
        # requirements changed -> static hash drift fires; requirements
        # themselves stay compatible. Distinguish the reasons.
        nc = remark(env, nc)
        if nc.conditions.is_true(COND_DRIFTED):
            assert nc.conditions.get(COND_DRIFTED).reason != \
                "RequirementsDrifted"


class TestInstanceTypeDrift:
    """drift_test.go:85-125 — stale instance types."""

    def test_missing_instance_type_label(self, env):
        nc = provision_one(env, cpu="500m")
        del nc.metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
        env.store.update(nc)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "InstanceTypeNotFound"

    def test_vanished_instance_type(self, env):
        nc = provision_one(env, cpu="500m")
        it_name = nc.metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
        env.provider._instance_types = [
            it for it in env.provider._instance_types if it.name != it_name]
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "InstanceTypeNotFound"

    def test_vanished_offering(self, env):
        """The claim's zone/capacity-type combination disappears from the
        type's offerings."""
        nc = provision_one(env, cpu="500m")
        it_name = nc.metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
        zone = nc.metadata.labels[ZONE]
        it = next(i for i in env.provider._instance_types
                  if i.name == it_name)
        it.offerings[:] = [o for o in it.offerings if o.zone != zone]
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "InstanceTypeNotFound"

    def test_unavailable_offering_is_not_drift(self, env):
        """Temporarily-unavailable offerings still count: the catalog data
        exists, the capacity just isn't purchasable right now."""
        nc = provision_one(env, cpu="500m")
        it_name = nc.metadata.labels[api_labels.LABEL_INSTANCE_TYPE]
        it = next(i for i in env.provider._instance_types
                  if i.name == it_name)
        for o in it.offerings:
            o.available = False
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED)


class TestConsolidatableMarker:
    def test_consolidate_after_never_clears(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.consolidate_after = None  # Never
        nc = provision_one(env, pool=pool, cpu="500m")
        env.clock.step(3600)
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_CONSOLIDATABLE)

    def test_consolidate_after_elapses(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.consolidate_after = 30.0
        nc = provision_one(env, pool=pool, cpu="500m")
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_CONSOLIDATABLE)
        env.clock.step(31)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_CONSOLIDATABLE)

    def test_pod_event_resets_consolidatable(self, env):
        pool = make_nodepool(name="default")
        pool.spec.disruption.consolidate_after = 30.0
        nc = provision_one(env, pool=pool, cpu="500m")
        env.clock.step(31)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_CONSOLIDATABLE)
        nc.status.last_pod_event_time = env.clock.now()
        env.store.update(nc)
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_CONSOLIDATABLE)


# ---------------------------------------------------------------------------
# Widened port of drift_test.go: cloud-provider drift ordering, launch
# gating, the requirement-operator table, and the static-field table.
# ---------------------------------------------------------------------------

from karpenter_tpu.api.nodeclaim import COND_LAUNCHED
from karpenter_tpu.api.objects import Taint


class TestCloudProviderDrift:
    def test_cloud_provider_drift_detected(self, env):
        nc = provision_one(env, cpu="500m")
        env.provider.is_drifted = lambda _nc: "drifted"
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "drifted"

    def test_static_drift_wins_over_cloud_provider_drift(self, env):
        """drift_test.go:126-142."""
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m")
        env.provider.is_drifted = lambda _nc: "drifted"
        pool.spec.template.metadata_labels["team"] = "x"
        env.store.update(pool)
        nc = remark(env, nc)
        assert nc.conditions.get(COND_DRIFTED).reason == "NodePoolDrifted"

    def test_requirement_drift_wins_over_cloud_provider_drift(self, env):
        """drift_test.go:143-159."""
        pool = make_nodepool(name="default")
        nc = provision_one(env, pool=pool, cpu="500m")
        env.provider.is_drifted = lambda _nc: "drifted"
        pool.spec.template.spec.requirements = [
            NodeSelectorRequirement(api_labels.LABEL_INSTANCE_TYPE,
                                    "DoesNotExist", ())]
        env.store.update(pool)
        nc = remark(env, nc)
        assert nc.conditions.get(COND_DRIFTED).reason == "RequirementsDrifted"

    def test_cleared_when_no_longer_drifted(self, env):
        """drift_test.go:192-203."""
        nc = provision_one(env, cpu="500m")
        env.provider.is_drifted = lambda _nc: "drifted"
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        env.provider.is_drifted = lambda _nc: ""
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED)


class TestLaunchGating:
    """drift_test.go:160-183: drift is only evaluated on launched claims,
    and an unlaunched claim sheds a stale Drifted condition."""

    def test_launched_unknown_removes_drifted(self, env):
        nc = provision_one(env, cpu="500m")
        env.provider.is_drifted = lambda _nc: "drifted"
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        nc.conditions.set_unknown(COND_LAUNCHED)
        env.store.update(nc)
        nc = remark(env, nc)
        assert nc.conditions.get(COND_DRIFTED) is None

    def test_launched_false_removes_drifted(self, env):
        nc = provision_one(env, cpu="500m")
        env.provider.is_drifted = lambda _nc: "drifted"
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        nc.conditions.set_false(COND_LAUNCHED, reason="LaunchFailed")
        env.store.update(nc)
        nc = remark(env, nc)
        assert nc.conditions.get(COND_DRIFTED) is None


class TestRequirementDriftTable:
    """drift_test.go:203-354 — the operator table. Each case: provision with
    compatible pool requirements + claim labels, then swap the pool
    requirements and check drift. Hash annotations are re-pinned so static
    drift never fires and only RequirementsDrifted is observed."""

    AMD = api_labels.ARCHITECTURE_AMD64
    ARM = api_labels.ARCHITECTURE_ARM64
    CT = api_labels.CAPACITY_TYPE_LABEL_KEY

    def _run(self, env, old_reqs, new_reqs, labels):
        pool = make_nodepool(name="default", requirements=old_reqs)
        nc = provision_one(env, pool=pool, cpu="500m")
        nc.metadata.labels.update(labels)
        env.store.update(nc)
        nc = remark(env, nc)
        assert not nc.conditions.is_true(COND_DRIFTED), \
            "pre-change state must not be drifted"
        pool.spec.template.spec.requirements = list(new_reqs)
        env.store.update(pool)
        # re-pin the hash so only requirement drift can fire
        nc.metadata.annotations[api_labels.NODEPOOL_HASH_ANNOTATION_KEY] = \
            pool.static_hash()
        env.store.update(nc)
        nc = remark(env, nc)
        return nc.conditions.is_true(COND_DRIFTED)

    def test_updated_requirement_drifts(self, env):
        assert self._run(
            env,
            [NodeSelectorRequirement(self.CT, "In", ("on-demand",)),
             NodeSelectorRequirement(api_labels.LABEL_ARCH, "In", (self.AMD,))],
            [NodeSelectorRequirement(self.CT, "In", ("spot",))],
            {self.CT: "on-demand", api_labels.LABEL_ARCH: self.AMD})

    def test_added_requirement_on_missing_label_drifts(self, env):
        assert self._run(
            env,
            [NodeSelectorRequirement(self.CT, "In", ("on-demand",))],
            [NodeSelectorRequirement(self.CT, "In", ("on-demand",)),
             NodeSelectorRequirement("example.com/team", "In", ("a",))],
            {self.CT: "on-demand"})

    def test_reduced_requirement_drifts(self, env):
        assert self._run(
            env,
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "In",
                                     (self.AMD, self.ARM))],
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "In",
                                     (self.ARM,))],
            {api_labels.LABEL_ARCH: self.AMD})

    def test_expanded_requirement_no_drift(self, env):
        assert not self._run(
            env,
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "In",
                                     (self.AMD,))],
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "In",
                                     (self.AMD, self.ARM))],
            {api_labels.LABEL_ARCH: self.AMD})

    def test_exists_requirement_no_drift(self, env):
        assert not self._run(
            env,
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "In",
                                     (self.AMD,))],
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "Exists", ())],
            {api_labels.LABEL_ARCH: self.AMD})

    def test_does_not_exist_requirement_drifts(self, env):
        assert self._run(
            env,
            [NodeSelectorRequirement(api_labels.LABEL_ARCH, "In",
                                     (self.AMD,))],
            [NodeSelectorRequirement(api_labels.LABEL_ARCH,
                                     "DoesNotExist", ())],
            {api_labels.LABEL_ARCH: self.AMD})

    def test_gt_satisfied_no_drift(self, env):
        assert not self._run(
            env,
            [],
            [NodeSelectorRequirement("example.com/slots", "Gt", ("5",))],
            {"example.com/slots": "10"})

    def test_lt_satisfied_no_drift(self, env):
        assert not self._run(
            env,
            [],
            [NodeSelectorRequirement("example.com/slots", "Lt", ("5",))],
            {"example.com/slots": "1"})


class TestStaticDriftFieldTable:
    """drift_test.go:456-480 — every static template field participates in
    the hash."""

    def _provision(self, env):
        pool = make_nodepool(name="default")
        spec = pool.spec.template.spec
        pool.spec.template.metadata_labels["keyLabel"] = "valueLabel"
        pool.spec.template.metadata_annotations["keyAnn"] = "valueAnn"
        spec.expire_after = 300.0
        spec.termination_grace_period = 300.0
        nc = provision_one(env, pool=pool, cpu="500m")
        assert not nc.conditions.is_true(COND_DRIFTED)
        return pool, nc

    def _assert_drifts(self, env, pool, nc, mutate):
        mutate(pool)
        env.store.update(pool)
        nc = remark(env, nc)
        assert nc.conditions.is_true(COND_DRIFTED)
        assert nc.conditions.get(COND_DRIFTED).reason == "NodePoolDrifted"

    def test_annotations(self, env):
        pool, nc = self._provision(env)
        self._assert_drifts(
            env, pool, nc,
            lambda p: p.spec.template.metadata_annotations.update(
                {"keyAnnTest": "v"}))

    def test_labels(self, env):
        pool, nc = self._provision(env)
        self._assert_drifts(
            env, pool, nc,
            lambda p: p.spec.template.metadata_labels.update(
                {"keyLabelTest": "v"}))

    def test_taints(self, env):
        pool, nc = self._provision(env)
        self._assert_drifts(
            env, pool, nc,
            lambda p: p.spec.template.spec.taints.append(
                Taint(key="keytest2taint", effect="NoExecute")))

    def test_startup_taints(self, env):
        pool, nc = self._provision(env)
        self._assert_drifts(
            env, pool, nc,
            lambda p: p.spec.template.spec.startup_taints.append(
                Taint(key="keytest2taint", effect="NoExecute")))

    def test_expire_after(self, env):
        pool, nc = self._provision(env)
        self._assert_drifts(
            env, pool, nc,
            lambda p: setattr(p.spec.template.spec, "expire_after", 6000.0))

    def test_termination_grace_period(self, env):
        pool, nc = self._provision(env)
        self._assert_drifts(
            env, pool, nc,
            lambda p: setattr(p.spec.template.spec,
                              "termination_grace_period", 6000.0))

    def test_requirements_change_is_not_static_drift(self, env):
        """Requirements are hashed OUT of the static hash (they have their
        own drift mechanism): a requirement change alone must not produce
        NodePoolDrifted."""
        pool, nc = self._provision(env)
        pool.spec.template.spec.requirements = [
            NodeSelectorRequirement(api_labels.LABEL_ARCH, "Exists", ())]
        env.store.update(pool)
        nc = remark(env, nc)
        if nc.conditions.is_true(COND_DRIFTED):
            assert nc.conditions.get(COND_DRIFTED).reason != "NodePoolDrifted"
