"""Fault-tolerant service path (ISSUE 11): the wire-level chaos layer, the
resilient client (deadlines, jittered backoff + retry budget, hedging,
transparent resync on restart), the crash-safe server (graceful drain,
tenant-fair shedding, request-digest dedupe, degraded rider), /debug/
sessions, and the seeded soak asserting decisions byte-identical to a
fault-free run."""

import json
import threading
import time
import urllib.request

import grpc
import pytest

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.sidecar import server as srv
from karpenter_tpu.sidecar.client import (RemoteScheduler, RetryPolicy,
                                          SolverSession)
from karpenter_tpu.sidecar.wire_chaos import ChaosChannel
from karpenter_tpu.utils.chaos import WireFaultInjector

from factories import make_nodepool, make_pods

pytestmark = pytest.mark.chaos


def _fast_policy(**over):
    kw = dict(deadline=10.0, max_attempts=5, backoff_base=0.002,
              backoff_cap=0.01, retry_budget=32.0, refund=1.0,
              sleep=lambda _s: None)
    kw.update(over)
    return RetryPolicy(**kw)


def _pair(addr, its, pool, tenant="", injector=None, **kw):
    channel = None
    if injector is not None:
        channel = ChaosChannel(
            grpc.insecure_channel(addr, options=srv.GRPC_OPTIONS), injector)
    kw.setdefault("retry", _fast_policy())
    session = SolverSession(addr, channel=channel, tenant=tenant, **kw)
    rs = RemoteScheduler(addr, [pool], {"default": its}, session=session)
    return rs, session


def _digest(results):
    """Canonical decision digest for RemoteResults, stable across server
    restarts and processes: claim names carry a process-global sequence,
    so identity is (nodepool, ITs, zone requirement, pod uids)."""
    from karpenter_tpu.api import labels as api_labels
    claims = sorted(
        (nc.nodepool_name,
         tuple(sorted(it.name for it in nc.instance_type_options)),
         tuple(sorted(r.values) for r in nc.api_nodeclaim.spec.requirements
               if r.key == api_labels.LABEL_TOPOLOGY_ZONE),
         tuple(sorted(p.uid for p in nc.pods)))
        for nc in results.new_nodeclaims)
    existing = sorted((en.name, tuple(sorted(p.uid for p in en.pods)))
                      for en in results.existing_nodes)
    return json.dumps([claims, existing, sorted(results.pod_errors.items())],
                      sort_keys=True)


@pytest.fixture()
def sidecar():
    server, port = srv.serve(port=0)
    yield f"127.0.0.1:{port}", server
    server.stop(grace=None)


class TestWireFaultInjector:
    def test_seeded_schedule_is_deterministic(self):
        a = WireFaultInjector(seed=7, drop=0.3, delay=0.3, duplicate=0.3,
                              disconnect=0.3)
        b = WireFaultInjector(seed=7, drop=0.3, delay=0.3, duplicate=0.3,
                              disconnect=0.3)
        assert [a.draw() for _ in range(50)] == [b.draw() for _ in range(50)]
        assert a.fired() == b.fired() > 0

    def test_at_most_one_delivery_altering_fault_per_attempt(self):
        inj = WireFaultInjector(seed=3, drop=0.9, duplicate=0.9,
                                disconnect=0.9)
        for _ in range(100):
            verdict = inj.draw()
            assert len([k for k in verdict if k != "delay"]) <= 1

    def test_disabled_draws_nothing_and_burns_no_rng(self):
        inj = WireFaultInjector(seed=1, drop=1.0)
        inj.enabled = False
        state = inj.rng.getstate()
        assert inj.draw() == []
        assert inj.rng.getstate() == state

    def test_forced_faults_preempt_random_draws(self):
        inj = WireFaultInjector(seed=1)
        inj.inject_next("drop")
        inj.inject_next("delay", "disconnect")
        assert inj.draw() == ["drop"]
        assert inj.draw() == ["delay", "disconnect"]
        assert inj.draw() == []
        assert inj.counts["drop"] == 1 and inj.counts["disconnect"] == 1

    def test_unknown_forced_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown wire fault kind"):
            WireFaultInjector().inject_next("blackhole")

    def test_forced_fault_burns_the_same_rng_draws(self):
        # a run using inject_next() must see the SAME background schedule
        # as a same-seed run without it: the forced path burns its 4 RNG
        # draws too (review fix — it returned early, shifting every
        # verdict after the forced attempt)
        base = WireFaultInjector(seed=11, drop=0.3, duplicate=0.3)
        forced = WireFaultInjector(seed=11, drop=0.3, duplicate=0.3)
        baseline = [base.draw() for _ in range(10)]
        forced.inject_next("disconnect")
        assert forced.draw() == ["disconnect"]
        assert [forced.draw() for _ in range(9)] == baseline[1:]


class TestResilientClient:
    def test_drop_is_retried_transparently(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=1)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(5, cpu="500m")
        r1 = rs.solve(pods)
        inj.inject_next("drop")
        r2 = rs.solve(pods)
        assert r2.retries == 1 and session.retries == 1
        assert session.resyncs == 0
        assert _digest(r2) == _digest(r1)
        session.close()

    def test_lost_response_recovers_from_dedupe_without_resync(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=1)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(6, cpu="500m")
        rs.solve(pods)
        pods[0:1] = make_pods(1, cpu="500m")
        inj.inject_next("disconnect")
        r = rs.solve(pods)
        # the server APPLIED the delta on the lost-response attempt; the
        # retry of identical bytes must be served from the dedupe cache —
        # no resync, no double apply (a double apply would fail the digest
        # handshake), and the session stays delta-resident
        assert r.retries == 1
        assert session.resyncs == 0
        assert session.last_encode_kind == "delta"
        with srv._SESSIONS_LOCK:
            s = [x for x in srv._SESSIONS.values()
                 if x.id == session._session_id][0]
        assert s.dedup_hits >= 1
        session.close()

    def test_duplicate_delivery_is_deduped(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=1)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj)
        pods = make_pods(4, cpu="500m")
        rs.solve(pods)
        pods[0:1] = make_pods(1, cpu="500m")
        inj.inject_next("duplicate")
        r = rs.solve(pods)
        assert r.retries == 0 and session.resyncs == 0
        with srv._SESSIONS_LOCK:
            s = [x for x in srv._SESSIONS.values()
                 if x.id == session._session_id][0]
        assert s.dedup_hits >= 1  # the second delivery never re-applied
        session.close()

    def test_deadline_exceeded_on_stalled_wire_then_recovery(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=1, delay_seconds=0.5)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj,
                            retry=_fast_policy(deadline=0.1))
        pods = make_pods(4, cpu="500m")
        rs.solve(pods)
        pods[0:1] = make_pods(1, cpu="500m")
        from karpenter_tpu.metrics.registry import SIDECAR_CLIENT_RETRIES
        before = SIDECAR_CLIENT_RETRIES.value({"code": "deadline_exceeded"})
        inj.inject_next("delay")  # 0.5s wire vs 0.1s deadline
        r = rs.solve(pods)
        assert r.retries == 1
        assert r.deadline_s == 0.1
        assert SIDECAR_CLIENT_RETRIES.value(
            {"code": "deadline_exceeded"}) == before + 1
        assert session.resyncs == 0
        session.close()

    def test_retry_budget_exhaustion_fails_fast_then_session_heals(
            self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=1)
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), injector=inj,
                            retry=_fast_policy(max_attempts=2,
                                               retry_budget=1.0,
                                               refund=0.0))
        pods = make_pods(5, cpu="500m")
        r1 = rs.solve(pods)
        # a DISTINGUISHABLE replacement (different cpu -> different wire
        # template): if the stale-mirror delta double-applies after the
        # failed solve, the row multiset visibly diverges and the digest
        # handshake must catch it
        pods[0:1] = make_pods(1, cpu="250m")
        # attempt 1 disconnects (server APPLIES, response lost), the single
        # budgeted retry drops too: the solve raises
        inj.inject_next("disconnect")
        inj.inject_next("drop")
        with pytest.raises(grpc.RpcError):
            rs.solve(pods)
        # budget dry: the next fault is not retried at all
        inj.inject_next("drop")
        with pytest.raises(grpc.RpcError):
            rs.solve(pods)
        # fault-free now: the session heals transparently — the server is
        # AHEAD of the client mirrors (the applied-but-unacked delta), so
        # the recovery path is a digest-mismatch resync, never a wedge
        r3 = rs.solve(pods)
        assert session.resyncs >= 1
        d1 = _digest(r1)
        assert isinstance(d1, str) and _digest(r3) != ""
        r4 = rs.solve(pods)
        assert session.last_encode_kind == "delta"
        assert _digest(r4) == _digest(r3)
        session.close()

    def test_hedged_solve_wins_on_dropped_primary(self, sidecar):
        addr, _ = sidecar
        inj = WireFaultInjector(seed=1)
        rs, session = _pair(
            addr, construct_instance_types()[:12],
            make_nodepool(name="default"), injector=inj,
            retry=_fast_policy(deadline=10.0, hedge_delay=0.05))
        pods = make_pods(5, cpu="500m")
        rs.solve(pods)
        pods[0:1] = make_pods(1, cpu="500m")
        # the primary is slow-dropped: it burns ~0.6s before dying, so the
        # hedge (fired at +50ms) answers first and wins
        inj.delay_seconds = 0.6
        inj.inject_next("delay", "drop")
        r = rs.solve(pods)
        assert r.hedged is True
        assert session.hedges == 1 and session.hedges_won == 1
        assert session.resyncs == 0
        from karpenter_tpu.metrics.registry import SIDECAR_CLIENT_HEDGES
        assert SIDECAR_CLIENT_HEDGES.value({"outcome": "won"}) >= 1
        session.close()

    def test_default_deadline_rider_on_results(self, sidecar):
        addr, _ = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"))
        r = rs.solve(make_pods(3, cpu="500m"))
        assert r.deadline_s == session.retry.deadline > 0
        assert r.retries == 0 and r.hedged is False
        session.close()

    def test_degraded_rider_when_circuit_open(self, sidecar):
        from karpenter_tpu.provisioning.tensor_scheduler import SOLVER_CIRCUIT
        addr, _ = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"))
        pods = make_pods(4, cpu="500m")
        r1 = rs.solve(pods)
        assert r1.degraded == ""
        for _ in range(SOLVER_CIRCUIT.threshold):
            SOLVER_CIRCUIT.record_failure()
        try:
            pods[0:1] = make_pods(1, cpu="500m")
            r2 = rs.solve(pods)
            # the breaker forced the host oracle server-side: the client
            # sees degraded=host_oracle instead of a silently slow answer
            assert r2.degraded == "host_oracle"
            assert r2.fallback_reason == "circuit_open"
            assert sum(r2.partition) == len(pods)  # partition rider rode too
        finally:
            SOLVER_CIRCUIT.reset()
        session.close()


class TestCrashSafeServer:
    def test_drain_nacks_new_rpcs_unavailable_and_readyz_flips(self):
        server, port = srv.serve(port=0)
        serving = srv.start_serving(0, 0, draining=server.draining)
        addr = f"127.0.0.1:{port}"
        try:
            rs, session = _pair(addr, construct_instance_types()[:12],
                                make_nodepool(name="default"),
                                retry=_fast_policy(max_attempts=1))
            rs.solve(make_pods(3, cpu="500m"))
            hp = serving.health_port
            assert urllib.request.urlopen(
                f"http://127.0.0.1:{hp}/readyz").status == 200
            shed = server.drain(grace=1.0)
            assert shed == 0  # nothing was queued
            with pytest.raises(urllib.request.HTTPError) as exc:
                urllib.request.urlopen(f"http://127.0.0.1:{hp}/readyz")
            assert exc.value.code == 503
            assert urllib.request.urlopen(
                f"http://127.0.0.1:{hp}/healthz").status == 200
            with pytest.raises(grpc.RpcError) as rpc_exc:
                rs.solve(make_pods(3, cpu="500m"))
            assert rpc_exc.value.code() == grpc.StatusCode.UNAVAILABLE
            assert "draining" in rpc_exc.value.details()
            from karpenter_tpu.metrics.registry import SIDECAR_DRAINING
            assert SIDECAR_DRAINING.value() == 1.0
            session.close()
        finally:
            serving.stop()
            server.stop(grace=None)
        from karpenter_tpu.metrics.registry import SIDECAR_DRAINING
        assert SIDECAR_DRAINING.value() == 0.0

    def test_drain_nacks_queued_waiters_with_retryable_shed(self):
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=8)
        q.acquire("a")  # hold the device
        results = []

        def waiter():
            try:
                q.acquire("b")
                results.append("granted")
            except srv.ShedError as e:
                results.append(e.reason)

        t = threading.Thread(target=waiter)
        t.start()
        for _ in range(200):
            if q.depth("b") == 1:
                break
            time.sleep(0.005)
        assert q.shed_all("draining") == 1
        t.join(2.0)
        assert results == ["draining"]
        q.release()

    def test_saturated_queue_sheds_burst_tenant_for_fair_one(self):
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=4)
        q.acquire("burst")  # device held
        outcomes = {}

        def enqueue(tenant, key):
            def run():
                try:
                    q.acquire(tenant)
                    outcomes[key] = "granted"
                    q.release()
                except srv.ShedError as e:
                    outcomes[key] = e.reason
            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t

        threads = []
        for i in range(4):
            threads.append(enqueue("burst", f"burst-{i}"))
            # serialize enqueue order so "newest waiter" is burst-3
            for _ in range(200):
                if q.depth("burst") == i + 1:
                    break
                time.sleep(0.005)
        assert q.depth("burst") == 4  # the queue is at its bound
        # a steady tenant under fair share (4 // 2 tenants = 2) evicts the
        # burst tenant's NEWEST waiter instead of being bounced
        t_steady = enqueue("steady", "steady-0")
        for _ in range(200):
            if q.depth("steady") == 1:
                break
            time.sleep(0.005)
        assert q.depth("steady") == 1
        # the shed THREAD publishes its outcome after waking: poll for it
        deadline = time.monotonic() + 5.0
        while "burst-3" not in outcomes and time.monotonic() < deadline:
            time.sleep(0.005)
        assert outcomes.get("burst-3") == "fairness"  # newest burst waiter
        from karpenter_tpu.metrics.registry import SIDECAR_SHED
        assert SIDECAR_SHED.value({"tenant": "burst",
                                   "reason": "fairness"}) >= 1
        # drain everything so the threads exit
        q.release()
        deadline = time.monotonic() + 5.0
        while any(t.is_alive() for t in threads + [t_steady]) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert outcomes.get("steady-0") == "granted"

    def test_fairly_saturated_queue_bounces_over_share_requester(self):
        q = srv.AdmissionQueue(max_concurrent=1, max_queued=2)
        q.acquire("a")
        held = []

        def hold(tenant):
            def run():
                try:
                    q.acquire(tenant)
                    held.append(tenant)
                    q.release()
                except srv.ShedError:
                    held.append(f"{tenant}-shed")
            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t

        threads = [hold("b"), hold("c")]
        for _ in range(200):
            if q.depth("b") + q.depth("c") == 2:
                break
            time.sleep(0.005)
        # bound 2, three tenants -> fair share 1 for everyone, and tenant
        # "a" (the requester) would exceed it: global RESOURCE_EXHAUSTED
        with pytest.raises(srv.ShedError) as exc:
            q.acquire("a")
        assert exc.value.reason == "overload"
        q.release()
        deadline = time.monotonic() + 5.0
        while any(t.is_alive() for t in threads) \
                and time.monotonic() < deadline:
            time.sleep(0.01)

    def test_debug_sessions_endpoint(self, sidecar):
        addr, _server = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), tenant="acme")
        rs.solve(make_pods(4, cpu="500m"))
        serving = srv.start_serving(0, 0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{serving.metrics_port}/debug/sessions"
            ).read().decode()
        finally:
            serving.stop()
        assert body.startswith("sessions ")
        line = next(l for l in body.splitlines()
                    if f"tenant=acme" in l)
        assert session._session_id in line
        assert "solves=1" in line and "resyncs=0" in line
        assert "queue_depth=0" in line and "in_flight=0" in line
        assert "last_solve_age_s=" in line and "dedup_hits=0" in line
        session.close()

    def test_sessions_snapshot_fields(self, sidecar):
        addr, _server = sidecar
        rs, session = _pair(addr, construct_instance_types()[:12],
                            make_nodepool(name="default"), tenant="t9")
        rs.solve(make_pods(3, cpu="500m"))
        snap = [s for s in srv.sessions_snapshot()
                if s["session"] == session._session_id]
        assert len(snap) == 1
        s = snap[0]
        assert s["tenant"] == "t9" and s["rows"] == 3
        assert s["solves"] == 1 and s["digest"]
        assert s["last_solve_age_s"] >= 0
        session.close()

    def test_zombie_request_rejected_without_corrupting_state(self, sidecar):
        # a hedge/retry loser of an OLD solve that arrives after later
        # solves evicted its response from the 2-entry dedupe cache must
        # be REJECTED (stale nonce), never re-applied on top of newer
        # state (review fix — a re-apply corrupted the session and forced
        # the resync DEVIATIONS 23 promises cannot happen)
        addr, _server = sidecar
        recorded = []

        class _Recording:
            def __init__(self, channel):
                self._channel = channel

            def unary_unary(self, method, request_serializer=None,
                            response_deserializer=None, **kw):
                inner = self._channel.unary_unary(
                    method, request_serializer=request_serializer,
                    response_deserializer=response_deserializer, **kw)
                if not method.endswith("SolveSession"):
                    return inner

                def call(request, timeout=None):
                    recorded.append(request)
                    return inner(request, timeout=timeout)
                return call

            def close(self):
                self._channel.close()

            def __getattr__(self, item):
                return getattr(self._channel, item)

        channel = _Recording(
            grpc.insecure_channel(addr, options=srv.GRPC_OPTIONS))
        session = SolverSession(addr, channel=channel,
                                retry=_fast_policy())
        rs = RemoteScheduler(addr, [make_nodepool(name="default")],
                             {"default": construct_instance_types()[:12]},
                             session=session)
        rs.solve(make_pods(4, cpu="500m"))
        zombie = recorded[0]
        rs.solve(make_pods(6, cpu="250m"))
        rs.solve(make_pods(8, cpu="250m"))  # q1 evicted from the cache
        with pytest.raises(grpc.RpcError) as exc:
            session._call("SolveSession", zombie)
        assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert "stale request nonce" in exc.value.details()
        # the zombie touched nothing: the next delta solve flows clean
        r = rs.solve(make_pods(5, cpu="500m"))
        assert session.resyncs == 0 and r.all_pods_scheduled()
        session.close()


class TestRestartRecovery:
    def _churn(self, rs, pods, rounds, tag):
        out = []
        for w in range(rounds):
            pods[w % len(pods)] = make_pods(1, cpu="500m")[0]
            out.append(_digest(rs.solve(pods)))
        return out

    def test_server_restart_mid_churn_resyncs_and_matches_oracle(self):
        """Kill and restart the server mid-churn with live tenant sessions:
        every client resyncs transparently (zero raised errors) and the
        post-recovery decisions match a never-restarted oracle run."""
        its = construct_instance_types()[:12]
        pool = make_nodepool(name="default")
        server, port = srv.serve(port=0)
        addr = f"127.0.0.1:{port}"
        tenants = {name: make_pods(n, cpu="500m")
                   for name, n in (("t-a", 6), ("t-b", 9))}
        sessions = {name: _pair(addr, its, pool, tenant=name)
                    for name in tenants}
        post = {}
        try:
            for name, pods in tenants.items():
                sessions[name][0].solve(pods)
            # kill: the listener dies, every session dies with it; a new
            # server binds the SAME port (the client channel reconnects)
            done = server.stop(0)
            if done is not None:
                done.wait(5.0)
            with srv._SESSIONS_LOCK:
                srv._SESSIONS.clear()
            server, port2 = srv.serve(port=port)
            assert port2 == port
            for name, pods in tenants.items():
                rs, session = sessions[name]
                post[name] = self._churn(rs, pods, 3, "post")
                assert session.resyncs >= 1, (
                    f"tenant {name} never resynced across the restart")
                # and the session is delta-resident again afterwards
                rs.solve(pods)
                assert session.last_encode_kind == "delta"
        finally:
            for rs, session in sessions.values():
                session.close()
            server.stop(grace=None)
        # oracle: identical churn against a never-restarted server
        oracle_server, oracle_port = srv.serve(port=0)
        oaddr = f"127.0.0.1:{oracle_port}"
        try:
            for name, n in (("t-a", 6), ("t-b", 9)):
                pods = make_pods(n, cpu="500m")
                rs, session = _pair(oaddr, its, pool, tenant=name)
                rs.solve(pods)
                want = self._churn(rs, pods, 3, "post")
                # digests are uid-based and make_pods mints fresh uids per
                # call, so compare SHAPE equality: same claim/existing/
                # error structure per round
                for got, exp in zip(post[name], want):
                    g, e = json.loads(got), json.loads(exp)
                    assert [(c[0], c[1]) for c in g[0]] == \
                        [(c[0], c[1]) for c in e[0]]
                    assert len(g[1]) == len(e[1]) and g[2] == e[2] == []
                session.close()
        finally:
            oracle_server.stop(grace=None)


class TestWireChaosSoak:
    def test_seeded_soak_converges_byte_identical_to_fault_free(self):
        """The ISSUE 11 soak: a seeded 5%-per-kind fault schedule over a
        churn stream — the client/server converge with zero wedged
        sessions and decisions byte-identical to a fault-free run of the
        SAME churn schedule (same pods, same order)."""
        import random as _random
        its = construct_instance_types()[:12]
        pool = make_nodepool(name="default")
        # ONE pod universe shared by both runs: decision digests key on
        # pod uids, so the fault-free oracle must churn the same objects
        # through the same schedule
        base0 = make_pods(12, cpu="500m")
        spare = make_pods(30, cpu="250m")

        def run(faulty: bool):
            server, port = srv.serve(port=0)
            addr = f"127.0.0.1:{port}"
            inj = WireFaultInjector(seed=99, drop=0.05, delay=0.05,
                                    duplicate=0.05, disconnect=0.05,
                                    delay_seconds=0.005)
            inj.enabled = faulty
            rs, session = _pair(addr, its, pool, injector=inj,
                                retry=_fast_policy())
            rng = _random.Random(1234)
            base = list(base0)
            digests = []
            try:
                for round_ in range(14):
                    i = rng.randrange(len(base))
                    base[i] = spare[round_ % len(spare)]
                    digests.append(_digest(rs.solve(base)))
                # convergence probe: fault-free parity re-solve of the
                # final state, cold, server-side
                inj.enabled = False
                session.parity_every = 1
                rs.solve(base)
                parity = session.last_parity
            finally:
                session.close()
                server.stop(grace=None)
            return digests, parity, session, inj

        faulted, parity_f, session_f, inj = run(faulty=True)
        clean, parity_c, session_c, _ = run(faulty=False)
        assert session_c.retries == 0
        assert inj.fired() > 0, "the 5% schedule never fired — no soak"
        assert faulted == clean, (
            "decisions diverged from the fault-free run")
        assert parity_f == "byte-identical" == parity_c
        # zero wedged sessions: every solve completed (asserted by the
        # loop finishing) and no resync was ever needed — drop retries +
        # dedupe recovery healed every fault in place
        assert session_f.resyncs == 0
        assert session_f.retries > 0
