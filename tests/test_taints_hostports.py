from karpenter_tpu.api.objects import HostPort, Pod, PodSpec, Taint, Toleration
from karpenter_tpu.scheduling import taints as st
from karpenter_tpu.scheduling.hostports import HostPortUsage, get_host_ports


def test_tolerates_exact():
    taint = Taint(key="team", value="infra", effect="NoSchedule")
    pod = Pod(spec=PodSpec(tolerations=[Toleration(key="team", operator="Equal", value="infra", effect="NoSchedule")]))
    assert st.tolerates([taint], pod) == []


def test_tolerates_exists_operator():
    taint = Taint(key="team", value="infra", effect="NoSchedule")
    pod = Pod(spec=PodSpec(tolerations=[Toleration(key="team", operator="Exists")]))
    assert st.tolerates([taint], pod) == []


def test_tolerates_empty_key_exists_tolerates_all():
    pod = Pod(spec=PodSpec(tolerations=[Toleration(operator="Exists")]))
    assert st.tolerates([Taint(key="a"), Taint(key="b", effect="NoExecute")], pod) == []


def test_not_tolerated():
    pod = Pod()
    assert len(st.tolerates([Taint(key="team", value="infra")], pod)) == 1


def test_effect_mismatch():
    taint = Taint(key="k", effect="NoExecute")
    pod = Pod(spec=PodSpec(tolerations=[Toleration(key="k", operator="Exists", effect="NoSchedule")]))
    assert st.tolerates([taint], pod)


def test_merge_dedups_by_key_effect():
    merged = st.merge([Taint(key="a")], [Taint(key="a", value="different"), Taint(key="b")])
    assert len(merged) == 2


def test_hostport_conflict_wildcard():
    usage = HostPortUsage()
    p1 = Pod(spec=PodSpec(host_ports=[HostPort(port=8080)]))
    ports1 = get_host_ports(p1)
    assert usage.conflicts(p1, ports1) == []
    usage.add(p1, ports1)
    p2 = Pod(spec=PodSpec(host_ports=[HostPort(port=8080, host_ip="10.0.0.1")]))
    assert usage.conflicts(p2, get_host_ports(p2))  # wildcard vs specific ip conflicts


def test_hostport_distinct_ips_no_conflict():
    usage = HostPortUsage()
    p1 = Pod(spec=PodSpec(host_ports=[HostPort(port=8080, host_ip="10.0.0.1")]))
    usage.add(p1, get_host_ports(p1))
    p2 = Pod(spec=PodSpec(host_ports=[HostPort(port=8080, host_ip="10.0.0.2")]))
    assert usage.conflicts(p2, get_host_ports(p2)) == []
    p3 = Pod(spec=PodSpec(host_ports=[HostPort(port=8080, host_ip="10.0.0.1")]))
    assert usage.conflicts(p3, get_host_ports(p3))


def test_hostport_protocol_disambiguates():
    usage = HostPortUsage()
    p1 = Pod(spec=PodSpec(host_ports=[HostPort(port=53, protocol="TCP")]))
    usage.add(p1, get_host_ports(p1))
    p2 = Pod(spec=PodSpec(host_ports=[HostPort(port=53, protocol="UDP")]))
    assert usage.conflicts(p2, get_host_ports(p2)) == []
