"""Concurrency hazards (SURVEY §5 race-detection row): the sidecar serves
solves from a thread pool, so everything on the solve path that is shared
across requests — the catalog-encoding LRU, the jit caches — must be
thread-safe and produce thread-count-independent results."""

import threading

import pytest

from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import (_CATALOG_CACHE,
                                                         TensorScheduler)

from factories import make_nodepool, make_pod, make_pods, spread_zone


@pytest.fixture(autouse=True)
def clean_cache():
    saved = dict(_CATALOG_CACHE)
    _CATALOG_CACHE.clear()
    yield
    _CATALOG_CACHE.clear()
    _CATALOG_CACHE.update(saved)


def one_solve(catalog, n_pods=24):
    pods = (make_pods(n_pods, cpu="500m")
            + make_pods(n_pods // 2, cpu="250m", labels={"app": "s"},
                        spread=[spread_zone(key="app", value="s")]))
    ts = TensorScheduler([make_nodepool()], {"default": list(catalog)},
                         force_tensor=True)
    r = ts.solve(pods)
    assert ts.fallback_reason == ""
    return sorted((nc.template.nodepool_name,
                   tuple(it.name for it in nc.instance_type_options),
                   len(nc.pods)) for nc in r.new_nodeclaims)


class TestConcurrentSolves:
    def test_parallel_solves_agree_with_serial(self):
        """16 concurrent solves over 3 alternating catalogs (cache churn
        across the LRU cap) must produce exactly the serial results and a
        structurally intact cache."""
        its = kwok.construct_instance_types()
        catalogs = [its[i:i + 24] for i in range(3)]
        serial = [one_solve(c) for c in catalogs]

        results = {}
        errors = []

        def worker(i):
            try:
                results[i] = one_solve(catalogs[i % 3])
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 16
        for i, r in results.items():
            assert r == serial[i % 3], f"thread {i} diverged"
        # cache stayed within its bound and entries are coherent
        from karpenter_tpu.provisioning import tensor_scheduler as ts_mod
        assert len(_CATALOG_CACHE) <= ts_mod._CATALOG_CACHE_MAX
        for ce in _CATALOG_CACHE.values():
            assert ce.vocab is not None

    def test_sidecar_concurrent_requests(self):
        """End-to-end over gRPC: the server's thread pool handles a burst
        of identical requests; every response matches."""
        import grpc

        from karpenter_tpu.sidecar.client import RemoteScheduler
        from karpenter_tpu.sidecar.server import serve

        its = kwok.construct_instance_types()[:24]
        server, port = serve(max_workers=4)
        try:
            def solve_once():
                rs = RemoteScheduler(f"127.0.0.1:{port}", [make_nodepool()],
                                     {"default": its})
                pods = make_pods(12, cpu="500m")
                r = rs.solve(pods)
                rs._channel.close()
                return (len(r.new_nodeclaims),
                        sorted(len(nc.pods) for nc in r.new_nodeclaims),
                        len(r.pod_errors))

            want = solve_once()
            got, errors = [], []

            def worker():
                try:
                    got.append(solve_once())
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert got and all(g == want for g in got)
        finally:
            server.stop(0)


class TestNoGcGuard:
    def test_nested_and_threaded_sections_restore_gc(self):
        """no_gc() must be reentrant and thread-safe: the collector resumes
        only when the LAST overlapping section exits, and the outer state is
        restored exactly."""
        import gc
        import threading
        from karpenter_tpu.utils.gcpause import no_gc
        gc.enable()  # establish the precondition (test-order independence)
        with no_gc():
            assert not gc.isenabled()
            with no_gc():  # reentrant
                assert not gc.isenabled()
            assert not gc.isenabled()  # still inside the outer section
        assert gc.isenabled()

        # staggered exits: thread 0 leaves its section FIRST while the
        # others are still inside — GC must stay off until the last exit
        inside = threading.Barrier(4, timeout=30)
        t0_exited = threading.Event()
        mid_states = []

        def worker(i):
            with no_gc():
                inside.wait()
                if i != 0:
                    assert t0_exited.wait(timeout=30)
                    mid_states.append(gc.isenabled())
            if i == 0:
                t0_exited.set()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        # after thread 0 exited, the remaining sections still held GC off
        assert mid_states == [False] * 3
        assert gc.isenabled()  # restored after the last section exits

    def test_no_gc_noop_when_already_disabled(self):
        """Inside the sidecar server (GC disabled process-wide) the guard
        must not re-enable collection on exit."""
        import gc
        from karpenter_tpu.utils.gcpause import no_gc
        gc.disable()
        try:
            with no_gc():
                assert not gc.isenabled()
            assert not gc.isenabled()  # stays off: we didn't turn it off
        finally:
            gc.enable()
