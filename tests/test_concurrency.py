"""Concurrency hazards (SURVEY §5 race-detection row): the sidecar serves
solves from a thread pool, so everything on the solve path that is shared
across requests — the catalog-encoding LRU, the jit caches — must be
thread-safe and produce thread-count-independent results."""

import threading

import pytest

from karpenter_tpu.cloudprovider import kwok
from karpenter_tpu.provisioning.tensor_scheduler import (_CATALOG_CACHE,
                                                         TensorScheduler)

from factories import make_nodepool, make_pod, make_pods, spread_zone


@pytest.fixture(autouse=True)
def clean_cache():
    saved = dict(_CATALOG_CACHE)
    _CATALOG_CACHE.clear()
    yield
    _CATALOG_CACHE.clear()
    _CATALOG_CACHE.update(saved)


def one_solve(catalog, n_pods=24):
    pods = (make_pods(n_pods, cpu="500m")
            + make_pods(n_pods // 2, cpu="250m", labels={"app": "s"},
                        spread=[spread_zone(key="app", value="s")]))
    ts = TensorScheduler([make_nodepool()], {"default": list(catalog)},
                         force_tensor=True)
    r = ts.solve(pods)
    assert ts.fallback_reason == ""
    return sorted((nc.template.nodepool_name,
                   tuple(it.name for it in nc.instance_type_options),
                   len(nc.pods)) for nc in r.new_nodeclaims)


class TestConcurrentSolves:
    def test_parallel_solves_agree_with_serial(self):
        """16 concurrent solves over 3 alternating catalogs (cache churn
        across the LRU cap) must produce exactly the serial results and a
        structurally intact cache."""
        its = kwok.construct_instance_types()
        catalogs = [its[i:i + 24] for i in range(3)]
        serial = [one_solve(c) for c in catalogs]

        results = {}
        errors = []

        def worker(i):
            try:
                results[i] = one_solve(catalogs[i % 3])
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 16
        for i, r in results.items():
            assert r == serial[i % 3], f"thread {i} diverged"
        # cache stayed within its bound and entries are coherent
        from karpenter_tpu.provisioning import tensor_scheduler as ts_mod
        assert len(_CATALOG_CACHE) <= ts_mod._CATALOG_CACHE_MAX
        for ce in _CATALOG_CACHE.values():
            assert ce.vocab is not None

    def test_sidecar_concurrent_requests(self):
        """End-to-end over gRPC: the server's thread pool handles a burst
        of identical requests; every response matches."""
        import grpc

        from karpenter_tpu.sidecar.client import RemoteScheduler
        from karpenter_tpu.sidecar.server import serve

        its = kwok.construct_instance_types()[:24]
        server, port = serve(max_workers=4)
        try:
            def solve_once():
                rs = RemoteScheduler(f"127.0.0.1:{port}", [make_nodepool()],
                                     {"default": its})
                pods = make_pods(12, cpu="500m")
                r = rs.solve(pods)
                rs._channel.close()
                return (len(r.new_nodeclaims),
                        sorted(len(nc.pods) for nc in r.new_nodeclaims),
                        len(r.pod_errors))

            want = solve_once()
            got, errors = [], []

            def worker():
                try:
                    got.append(solve_once())
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert got and all(g == want for g in got)
        finally:
            server.stop(0)


class TestCrashIsolation:
    """Fault-tolerant reconcile runtime (controller-runtime recovers
    reconcile panics and retries through a rate-limited workqueue;
    controller.go:105-117 + ItemExponentialFailureRateLimiter): a raising
    reconciler must never crash the dispatch loop or lose its item."""

    def _env(self):
        from karpenter_tpu.api.objects import ObjectMeta
        from karpenter_tpu.api.storage import StorageClass
        from karpenter_tpu.controllers.manager import Controller, Manager
        from karpenter_tpu.events.recorder import Recorder
        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        clock = FakeClock()
        store = Store(clock)
        recorder = Recorder(clock)
        mgr = Manager(store, clock, recorder=recorder)
        return clock, store, recorder, mgr, Controller, StorageClass, \
            ObjectMeta

    def _flush(self, mgr, clock, rounds=40, step=301.0):
        """Advance past every backoff delay (cap 300s) until quiet."""
        for _ in range(rounds):
            clock.step(step)
            mgr.advance(0)
            if not mgr._timers and not mgr._queue:
                return
        raise AssertionError("retry timers never drained")

    def test_raise_once_then_succeed_retries_and_forgets(self):
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class Flaky(Controller):
            name = "flaky"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(clock.now())
                if len(calls) == 1:
                    raise RuntimeError("transient")

        mgr.register(Flaky())
        store.create(SC(metadata=OM(name="a")))
        assert mgr.run_until_quiet()     # failure isolated, loop survives
        assert len(calls) == 1
        clock.step(1.0)                  # base backoff delay
        mgr.advance(0)
        assert len(calls) == 2           # retried and succeeded
        key = ("flaky", "StorageClass", "default", "a")
        assert mgr.backoff.failures(key) == 0   # forgotten on success
        assert key not in mgr.deadletter

    def test_raise_forever_quarantines_with_metric_and_event(self):
        from karpenter_tpu.metrics.registry import (RECONCILE_ERRORS,
                                                    RECONCILE_QUARANTINED)
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class Crash(Controller):
            name = "crash-forever"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                raise RuntimeError("hopeless")

        mgr.register(Crash())
        errs0 = RECONCILE_ERRORS.value({"controller": "crash-forever"})
        store.create(SC(metadata=OM(name="b")))
        assert mgr.run_until_quiet()
        self._flush(mgr, clock)
        # exactly max_retries attempts, then the dead-letter set
        assert len(calls) == mgr.max_retries
        key = ("crash-forever", "StorageClass", "default", "b")
        assert key in mgr.deadletter
        assert mgr.deadletter[key]["failures"] == mgr.max_retries
        assert RECONCILE_ERRORS.value(
            {"controller": "crash-forever"}) - errs0 == mgr.max_retries
        assert RECONCILE_QUARANTINED.value(
            {"controller": "crash-forever"}) == 1
        assert recorder.reasons_for("b") == ["ReconcileQuarantined"]
        # a fresh watch event releases the quarantine for another budget
        store.update(store.get(SC, "b", "default"))
        assert key not in mgr.deadletter
        assert RECONCILE_QUARANTINED.value(
            {"controller": "crash-forever"}) == 0
        mgr.drain()
        assert len(calls) == mgr.max_retries + 1

    def test_terminal_error_is_not_retried(self):
        from karpenter_tpu.controllers.manager import TerminalError
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class Term(Controller):
            name = "terminal"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                raise TerminalError("bad spec")

        mgr.register(Term())
        store.create(SC(metadata=OM(name="c")))
        assert mgr.run_until_quiet()
        self._flush(mgr, clock)
        assert len(calls) == 1           # no retry, ever
        key = ("terminal", "StorageClass", "default", "c")
        assert key not in mgr.deadletter  # and no quarantine
        assert not mgr._timers

    def test_insufficient_capacity_backs_off_but_never_quarantines(self):
        from karpenter_tpu.cloudprovider.types import \
            InsufficientCapacityError
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class Capacity(Controller):
            name = "capacity"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                raise InsufficientCapacityError("no capacity anywhere")

        mgr.register(Capacity())
        store.create(SC(metadata=OM(name="d")))
        assert mgr.run_until_quiet()
        for _ in range(3 * mgr.max_retries):
            clock.step(301.0)
            mgr.advance(0)
        # far past the quarantine threshold and still retrying
        assert len(calls) > mgr.max_retries + 2
        assert ("capacity", "StorageClass", "default", "d") \
            not in mgr.deadletter

    def test_exempt_failures_reset_the_quarantine_budget(self):
        """A long insufficient-capacity streak must not pre-spend the
        quarantine budget: the first transient failure after it gets the
        full max_retries budget, not instant dead-lettering."""
        from karpenter_tpu.cloudprovider.types import \
            InsufficientCapacityError
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class CapacityThenFlaky(Controller):
            name = "mixed"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                if len(calls) <= 12:
                    raise InsufficientCapacityError("no capacity")
                raise RuntimeError("transient flake")

        mgr.register(CapacityThenFlaky())
        store.create(SC(metadata=OM(name="m")))
        assert mgr.run_until_quiet()
        key = ("mixed", "StorageClass", "default", "m")
        # drive through the capacity streak and into the transient phase
        while len(calls) < 13:
            clock.step(301.0)
            mgr.advance(0)
        assert key not in mgr.deadletter   # 13th failure != instant death
        self._flush(mgr, clock)
        # quarantine only after max_retries CONSECUTIVE transient failures,
        # and the recorded count is the budget consumed, not the raw
        # backoff count inflated by the exempt capacity streak
        assert len(calls) == 12 + mgr.max_retries
        assert key in mgr.deadletter
        assert mgr.deadletter[key]["failures"] == mgr.max_retries

    def test_singleton_crash_is_isolated_and_backed_off(self):
        from karpenter_tpu.controllers.manager import SingletonController
        from karpenter_tpu.metrics.registry import RECONCILE_ERRORS
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class Engine(SingletonController):
            name = "engine"

            def reconcile(self):
                calls.append(clock.now())
                raise RuntimeError("engine stalled")

        mgr.register(Engine())
        errs0 = RECONCILE_ERRORS.value({"controller": "engine"})
        mgr.tick()                       # survives the raise
        assert len(calls) == 1
        mgr.tick()                       # inside the backoff window: skipped
        assert len(calls) == 1
        clock.step(1.1)
        mgr.tick()                       # window elapsed: retried
        assert len(calls) == 2
        assert RECONCILE_ERRORS.value({"controller": "engine"}) - errs0 == 2

    def test_exactly_once_requeue_under_concurrent_event_during_failure(self):
        """The drain() race the refactor closed: the _queued key used to be
        discarded before reconcile ran, so a store event arriving WHILE the
        reconcile was failing double-queued the item — one entry from the
        event, one from the failure-path retry. The dirty-set fold must
        leave exactly one retry."""
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class UpdatesThenFails(Controller):
            name = "racy"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(clock.now())
                if len(calls) == 1:
                    # concurrent event for the SAME item mid-reconcile
                    # (watch fan-out is synchronous in this store)
                    store.update(obj)
                    raise RuntimeError("failed after mutating")

        mgr.register(UpdatesThenFails())
        store.create(SC(metadata=OM(name="r")))
        assert mgr.run_until_quiet()
        # the concurrent event was folded into the failure retry: nothing
        # queued now, exactly one retry timer armed
        assert len(calls) == 1
        assert not mgr._queue
        assert len(mgr._timer_pending) == 1
        clock.step(1.0)
        mgr.advance(0)
        assert len(calls) == 2           # exactly one retry ran
        self._flush(mgr, clock)
        assert len(calls) == 2           # and no ghost duplicate later

    def test_event_during_terminal_failure_is_not_lost(self):
        """A concurrent watch event arriving while the reconcile ends in
        TerminalError must still re-reconcile the item — 'no retry' means
        the FAILURE isn't retried, not that fresh input is dropped."""
        from karpenter_tpu.controllers.manager import TerminalError
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class UpdatesThenTerminal(Controller):
            name = "term-racy"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                if len(calls) == 1:
                    store.update(obj)
                    raise TerminalError("rejected")

        mgr.register(UpdatesThenTerminal())
        store.create(SC(metadata=OM(name="t")))
        assert mgr.run_until_quiet()
        assert len(calls) == 2  # the mid-reconcile event was re-dispatched

    def test_stale_requeue_timer_does_not_release_quarantine(self):
        """A periodic recheck armed by an earlier SUCCESS must not lift a
        later quarantine: only a fresh watch event releases it."""
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        from karpenter_tpu.controllers.manager import Result
        calls = []

        class SucceedsThenCrashes(Controller):
            name = "periodic"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                if len(calls) == 1:
                    return Result(requeue_after=6000.0)  # periodic recheck
                raise RuntimeError("broke after the first pass")

        mgr.register(SucceedsThenCrashes())
        sc = SC(metadata=OM(name="p"))
        store.create(sc)
        assert mgr.run_until_quiet()       # success: timer armed at +6000
        store.update(sc)                   # trigger the failure chain
        assert mgr.run_until_quiet()
        self._flush(mgr, clock)            # steps far past +6000
        key = ("periodic", "StorageClass", "default", "p")
        assert key in mgr.deadletter       # the stale timer did NOT release
        assert len(calls) == 1 + mgr.max_retries

    def test_singleton_terminal_error_backs_off_at_the_cap(self):
        from karpenter_tpu.controllers.manager import (RETRY_CAP_SECONDS,
                                                       SingletonController,
                                                       TerminalError)
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class TermEngine(SingletonController):
            name = "term-engine"

            def reconcile(self):
                calls.append(clock.now())
                raise TerminalError("config rejected")

        mgr.register(TermEngine())
        mgr.tick()
        assert len(calls) == 1
        clock.step(RETRY_CAP_SECONDS - 1)
        mgr.tick()
        assert len(calls) == 1             # slower than any transient retry
        clock.step(1.0)
        mgr.tick()
        assert len(calls) == 2

    def test_event_during_successful_reconcile_requeues_once(self):
        clock, store, recorder, mgr, Controller, SC, OM = self._env()
        calls = []

        class UpdatesOnce(Controller):
            name = "self-update"
            kinds = (SC,)

            def reconcile(self, obj):
                calls.append(1)
                if len(calls) == 1:
                    store.update(obj)    # dirty mark, no double-queue

        mgr.register(UpdatesOnce())
        store.create(SC(metadata=OM(name="s")))
        assert mgr.run_until_quiet()
        assert len(calls) == 2           # initial + exactly one requeue


class TestNoGcGuard:
    def test_nested_and_threaded_sections_restore_gc(self):
        """no_gc() must be reentrant and thread-safe: the collector resumes
        only when the LAST overlapping section exits, and the outer state is
        restored exactly."""
        import gc
        import threading
        from karpenter_tpu.utils.gcpause import no_gc
        gc.enable()  # establish the precondition (test-order independence)
        with no_gc():
            assert not gc.isenabled()
            with no_gc():  # reentrant
                assert not gc.isenabled()
            assert not gc.isenabled()  # still inside the outer section
        assert gc.isenabled()

        # staggered exits: thread 0 leaves its section FIRST while the
        # others are still inside — GC must stay off until the last exit
        inside = threading.Barrier(4, timeout=30)
        t0_exited = threading.Event()
        mid_states = []

        def worker(i):
            with no_gc():
                inside.wait()
                if i != 0:
                    assert t0_exited.wait(timeout=30)
                    mid_states.append(gc.isenabled())
            if i == 0:
                t0_exited.set()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        # after thread 0 exited, the remaining sections still held GC off
        assert mid_states == [False] * 3
        assert gc.isenabled()  # restored after the last section exits

    def test_no_gc_noop_when_already_disabled(self):
        """Inside the sidecar server (GC disabled process-wide) the guard
        must not re-enable collection on exit."""
        import gc
        from karpenter_tpu.utils.gcpause import no_gc
        gc.disable()
        try:
            with no_gc():
                assert not gc.isenabled()
            assert not gc.isenabled()  # stays off: we didn't turn it off
        finally:
            gc.enable()
