"""Fault-tolerant runtime under seeded chaos.

Three layers of evidence that the system degrades instead of dying
(Candea & Fox crash-only software; Basiri et al. chaos engineering):

- the solver circuit breaker's open/half-open/close transitions, unit and
  integrated (a crashing device path trips to the host oracle with
  fallback_reason="circuit_open" and recovers via a cooldown probe);
- observability of best-effort surfaces (events_dropped_total) and of the
  dead-letter set (/debug/deadletter);
- the seeded soak: the full operator loop (provision -> disrupt ->
  terminate) under ~5% injected store+cloudprovider faults for thousands
  of fake-clock seconds converges, loses no work item, and quarantines
  exactly the deliberately-poisoned object.

Everything is deterministic: fixed seeds, FakeClock, no sleeps, single
thread — chaos as a reproducible experiment, not flakiness.
"""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import Node, ObjectMeta, Pod
from karpenter_tpu.api.storage import StorageClass
from karpenter_tpu.cloudprovider.chaos import ChaosCloudProvider
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Controller, Manager
from karpenter_tpu.kube.chaos import ChaosStore
from karpenter_tpu.metrics.registry import (EVENTS_DROPPED, RECONCILE_ERRORS,
                                            RECONCILE_QUARANTINED,
                                            SOLVER_CIRCUIT_STATE)
from karpenter_tpu.provisioning.tensor_scheduler import (SOLVER_CIRCUIT,
                                                         SolverCircuitBreaker,
                                                         TensorScheduler)
from karpenter_tpu.utils.chaos import (FaultInjector, InjectedFault,
                                       InjectedTerminalFault, chaos_pause)
from karpenter_tpu.utils.clock import FakeClock

from expectations import Env
from factories import make_nodepool, make_pod, make_pods


@pytest.fixture(autouse=True)
def clean_breaker():
    """The module-level breaker is process-global state; tests here trip
    breakers on purpose, so reset around each."""
    SOLVER_CIRCUIT.reset()
    yield
    SOLVER_CIRCUIT.reset()


class TestCircuitBreakerUnit:
    def test_open_half_open_close_transitions(self):
        t = [0.0]
        b = SolverCircuitBreaker(threshold=3, cooldown=30.0,
                                 now=lambda: t[0], publish=True)
        assert b.state == b.CLOSED and b.allow()
        b.record_failure()
        b.record_failure()
        assert b.state == b.CLOSED and b.allow()  # under threshold
        b.record_failure()
        assert b.state == b.OPEN
        assert SOLVER_CIRCUIT_STATE.value() == 1
        assert not b.allow()                       # cooling down
        t[0] = 29.9
        assert not b.allow()
        t[0] = 30.0
        assert b.allow()                           # half-open probe
        assert b.state == b.HALF_OPEN
        assert SOLVER_CIRCUIT_STATE.value() == 2
        b.record_failure()                         # probe fails: re-open
        assert b.state == b.OPEN and not b.allow()
        t[0] = 60.0
        assert b.allow() and b.state == b.HALF_OPEN
        b.record_success()                         # probe succeeds: close
        assert b.state == b.CLOSED and b.allow()
        assert SOLVER_CIRCUIT_STATE.value() == 0

    def test_success_resets_consecutive_count(self):
        b = SolverCircuitBreaker(threshold=3, now=lambda: 0.0)
        for _ in range(5):
            b.record_failure()
            b.record_success()
        assert b.state == b.CLOSED  # never 3 CONSECUTIVE failures

    def test_ad_hoc_breaker_does_not_stomp_the_gauge(self):
        """Exactly one breaker (SOLVER_CIRCUIT, publish=True) owns the
        solver_circuit_state series; a bench/test breaker tripping must
        not overwrite the production export."""
        before = SOLVER_CIRCUIT_STATE.value()
        b = SolverCircuitBreaker(threshold=1, now=lambda: 0.0)
        b.record_failure()
        assert b.state == b.OPEN
        assert SOLVER_CIRCUIT_STATE.value() == before


class TestCircuitBreakerIntegration:
    """The breaker wired into the solve path: a crashing device path trips
    to the host oracle; the cooldown probe restores tensor service."""

    def _scheduler(self, breaker, crash=False):
        ts = TensorScheduler([make_nodepool()], {"default": _kwok_catalog()},
                             circuit=breaker)
        if crash:
            def boom(problem):
                raise RuntimeError("device wedged")
            ts.precompute = boom
        return ts

    def test_trips_to_host_oracle_and_recovers(self):
        t = [0.0]
        breaker = SolverCircuitBreaker(threshold=3, cooldown=60.0,
                                       now=lambda: t[0])
        pods = make_pods(6, cpu="500m")
        # individual crashes: host fallback with the crash reason
        for i in range(3):
            ts = self._scheduler(breaker, crash=True)
            r = ts.solve(pods)
            assert not r.pod_errors          # the oracle still served
            assert "tensor solve failed" in ts.fallback_reason
        assert breaker.state == breaker.OPEN
        # open: straight to the oracle, the device path is NOT attempted
        ts = self._scheduler(breaker, crash=True)
        ts.precompute = None  # would TypeError if touched
        r = ts.solve(pods)
        assert ts.fallback_reason == "circuit_open"
        assert not r.pod_errors
        # cooldown elapses: half-open probe crashes -> re-open
        t[0] = 60.0
        ts = self._scheduler(breaker, crash=True)
        ts.solve(pods)
        assert "tensor solve failed" in ts.fallback_reason
        assert breaker.state == breaker.OPEN
        # next cooldown: healthy probe closes the breaker for good
        t[0] = 120.0
        ts = self._scheduler(breaker)
        r = ts.solve(pods)
        assert ts.fallback_reason == ""
        assert breaker.state == breaker.CLOSED
        assert not r.pod_errors

    def test_force_tensor_bypasses_gate_and_propagates(self):
        """force_tensor (bench/conformance) must see the real crash, not a
        silent fallback."""
        t = [0.0]
        breaker = SolverCircuitBreaker(threshold=1, cooldown=60.0,
                                       now=lambda: t[0])
        ts = self._scheduler(breaker, crash=True)
        ts.force_tensor = True
        with pytest.raises(RuntimeError, match="device wedged"):
            ts.solve(make_pods(2, cpu="250m"))
        assert breaker.state == breaker.OPEN  # still counted


def _kwok_catalog():
    from karpenter_tpu.cloudprovider import kwok
    return kwok.construct_instance_types()[:24]


class TestEventsDropped:
    def test_sink_error_is_counted(self):
        from karpenter_tpu.events.catalog import nodepool_blocked
        from karpenter_tpu.events.recorder import Recorder
        clock = FakeClock()
        dropped0 = EVENTS_DROPPED.value({"reason": "sink_error"})

        def bad_sink(ev):
            raise OSError("apiserver gone")

        rec = Recorder(clock, sink=bad_sink)
        rec.publish(nodepool_blocked("np-1"))
        assert rec.events, "event must still be recorded locally"
        assert EVENTS_DROPPED.value(
            {"reason": "sink_error"}) == dropped0 + 1

    def test_async_sink_delivery_error_is_counted(self):
        from karpenter_tpu.events.catalog import nodepool_blocked
        from karpenter_tpu.events.recorder import AsyncSink, Recorder
        clock = FakeClock()
        dropped0 = EVENTS_DROPPED.value({"reason": "deliver_error"})

        def bad_deliver(ev):
            raise OSError("connection reset")

        sink = AsyncSink(bad_deliver)
        try:
            rec = Recorder(clock, sink=sink)
            rec.publish(nodepool_blocked("np-2"))
            sink.flush()
            assert EVENTS_DROPPED.value(
                {"reason": "deliver_error"}) == dropped0 + 1
        finally:
            sink.close()


class TestFakeProviderChaos:
    def test_seeded_transient_faults_fire(self):
        inj = FaultInjector(seed=3, rate=1.0, reconcile_only=False)
        fake = FakeCloudProvider()
        fake.chaos = inj
        nc = NodeClaim(metadata=ObjectMeta(name="nc-1"))
        with pytest.raises(InjectedFault):
            fake.create(nc)
        with pytest.raises(InjectedFault):
            fake.get("fake://nope")
        with pytest.raises(InjectedFault):
            fake.get_instance_types(make_nodepool())
        with pytest.raises(InjectedFault):
            fake.delete(nc)
        assert inj.fired() == 4
        assert set(inj.counts) == {"fake.create", "fake.get",
                                   "fake.get_instance_types", "fake.delete"}
        # faults fire BEFORE the call is recorded: the request never
        # reached the provider
        assert fake.create_calls == [] and fake.delete_calls == []

    def test_terminal_faults_are_terminal_errors(self):
        from karpenter_tpu.controllers.manager import TerminalError
        inj = FaultInjector(seed=3, rate=1.0, terminal_rate=1.0,
                            reconcile_only=False)
        fake = FakeCloudProvider()
        fake.chaos = inj
        with pytest.raises(TerminalError):
            fake.create(NodeClaim(metadata=ObjectMeta(name="nc-t")))

    def test_reconcile_only_gating(self):
        from karpenter_tpu.utils.injection import with_controller
        inj = FaultInjector(seed=1, rate=1.0)  # reconcile_only default
        fake = FakeCloudProvider()
        fake.chaos = inj
        fake.get_instance_types(make_nodepool())  # setup path: unperturbed
        assert inj.fired() == 0
        with with_controller("provisioner"):
            with pytest.raises(InjectedFault):
                fake.get_instance_types(make_nodepool())
        assert inj.fired() == 1


class TestDeadletterEndpoint:
    def test_debug_deadletter_serves_quarantine(self):
        from urllib.request import urlopen

        from karpenter_tpu.kube.store import Store
        from karpenter_tpu.operator.server import ServingGroup
        clock = FakeClock()
        store = Store(clock)
        mgr = Manager(store, clock, max_retries=2)

        class Crash(Controller):
            name = "crash"
            kinds = (StorageClass,)

            def reconcile(self, obj):
                raise RuntimeError("hopeless")

        mgr.register(Crash())
        store.create(StorageClass(metadata=ObjectMeta(name="bad-sc")))
        mgr.run_until_quiet()
        for _ in range(6):
            clock.step(301.0)
            mgr.advance(0)
        assert mgr.deadletter
        grp = ServingGroup(0, 0, manager=mgr).start()
        try:
            body = urlopen(
                f"http://127.0.0.1:{grp.metrics_port}/debug/deadletter",
                timeout=5).read().decode()
        finally:
            grp.stop()
        assert body.startswith("quarantined 1")
        assert "crash StorageClass/default/bad-sc" in body
        assert "RuntimeError: hopeless" in body


class PoisonController(Controller):
    """Deliberately unreconcilable: always raises for its object — the
    item the soak asserts is the ONLY dead-letter occupant."""

    name = "chaos.poison"
    kinds = (StorageClass,)

    def reconcile(self, obj):
        raise RuntimeError("poison pill: unreconcilable by construction")


@pytest.mark.chaos
class TestChaosSoak:
    """The headline experiment: the full operator loop under ~5% injected
    store + cloudprovider faults for thousands of fake-clock seconds."""

    SEED = 0xC0FFEE
    RATE = 0.05

    def _chaos_env(self):
        inj = FaultInjector(seed=self.SEED, rate=self.RATE)
        clock = FakeClock()
        store = ChaosStore(clock, injector=inj)
        env = Env(
            clock=clock, store=store,
            provider=lambda s: ChaosCloudProvider(
                KwokCloudProvider(store=s), inj))
        # full loop: the disruption engine + orchestration queue run under
        # manager crash isolation like the operator wires them, plus the
        # poison controller whose quarantine the experiment asserts
        env.mgr.register(env.queue, env.disruption, PoisonController())
        return env, inj

    def _churn_round(self, env, rounds=6, step=7.0):
        """One chaos window: drive the loop across batch windows and
        backoff delays WITHOUT asserting quiescence (mid-storm the only
        invariant is 'still running')."""
        for _ in range(rounds):
            env.mgr.run_until_quiet()
            env.clock.step(step)

    def _flush(self, env, max_rounds=60):
        """Faults off: advance past every backoff/requeue delay until every
        failure is resolved. Conservation check: no item may remain queued,
        in failure backoff, or singleton-gated — every transient failure
        retried to success (or quarantined, dropping its backoff state).
        Periodic requeue timers (consolidation rechecks) are steady-state,
        not residual work, so they're exempt."""
        for _ in range(max_rounds):
            assert env.mgr.run_until_quiet(), "livelock after faults off"
            if not env.mgr._queue and not env.mgr.backoff._failures \
                    and not env.mgr._singleton_next:
                return
            env.clock.step(301.0)
        raise AssertionError(
            f"work never drained: queue={len(env.mgr._queue)} "
            f"backoff={dict(env.mgr.backoff._failures)} "
            f"singletons={dict(env.mgr._singleton_next)}")

    def test_soak_converges_with_zero_lost_items(self):
        env, inj = self._chaos_env()
        errs0 = sum(RECONCILE_ERRORS._values.values())
        env.store.create(make_nodepool(name="default"))
        # the poison pill rides along from the start
        env.store.create(StorageClass(metadata=ObjectMeta(name="poison")))

        # phase 1: provision a workload under faults
        for p in make_pods(12, cpu="500m", memory="256Mi"):
            env.store.create(p)
        self._churn_round(env, rounds=24)

        # phase 2: scale down (consolidation fodder) and keep churning
        pods = env.store.list(Pod)
        for p in pods[:5]:
            with chaos_pause(inj):
                env.store.delete(p)
        self._churn_round(env, rounds=24)

        # phase 3: scale back up + delete a node out from under its pods
        for p in make_pods(8, cpu="250m", memory="128Mi",
                           labels={"app": "wave2"}):
            env.store.create(p)
        self._churn_round(env, rounds=12)
        nodes = [n for n in env.store.list(Node)
                 if n.metadata.deletion_timestamp is None]
        if nodes:
            with chaos_pause(inj):
                env.store.delete(nodes[0])
        self._churn_round(env, rounds=36, step=11.0)

        # the experiment only means something if faults actually fired
        assert inj.fired() > 30, inj.counts
        assert sum(RECONCILE_ERRORS._values.values()) > errs0

        # convergence: faults off, flush every retry, then assert
        inj.enabled = False
        self._flush(env)

        live_nodes = {n.name for n in env.store.list(Node)
                      if n.metadata.deletion_timestamp is None}
        for p in env.store.list(Pod):
            assert p.spec.node_name in live_nodes, \
                f"pod {p.name} lost (bound to {p.spec.node_name!r})"
        claims = env.store.list(NodeClaim)
        assert all(c.launched() and c.registered() and c.initialized()
                   for c in claims if c.metadata.deletion_timestamp is None)
        assert env.cluster.synced()

        # quarantine contains EXACTLY the poison pill
        assert list(env.mgr.deadletter) == [
            ("chaos.poison", "StorageClass", "default", "poison")]
        assert RECONCILE_QUARANTINED.value(
            {"controller": "chaos.poison"}) == 1
        assert env.recorder.reasons_for("poison") == ["ReconcileQuarantined"]
        # nothing else ever gave up: every non-poison failure retried to
        # success (no residual backoff state)
        assert all(k[0] == "chaos.poison"
                   for k in env.mgr.backoff._failures), \
            env.mgr.backoff._failures

    def test_soak_is_deterministic(self):
        """Same seed -> byte-identical fault schedule and end state."""
        def run():
            env, inj = self._chaos_env()
            env.store.create(make_nodepool(name="default"))
            for p in make_pods(10, cpu="500m"):
                env.store.create(p)
            self._churn_round(env, rounds=20)
            inj.enabled = False
            self._flush(env)
            # name-independent shape: pod names come from a process-global
            # factory counter, so compare the fault schedule and the
            # placement structure, not identifiers
            return (dict(inj.counts),
                    sorted(n.name for n in env.store.list(Node)),
                    sorted(p.spec.node_name for p in env.store.list(Pod)))

        assert run() == run()
