"""Scenario port of /root/reference/pkg/controllers/nodeclaim/lifecycle/
{initialization,registration,liveness}_test.go: registration invariants and
node sync, initialization gating (NotReady, unregistered resources, startup
and ephemeral taints), liveness TTL, and the kwok kubelet simulation."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import (COND_INITIALIZED, COND_REGISTERED,
                                         NodeClaim)
from karpenter_tpu.api.objects import Node, Taint
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider, KwokKubelet
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycle
from karpenter_tpu.kube.store import Store
from karpenter_tpu.provisioning.provisioner import Binder, PodTrigger, Provisioner
from karpenter_tpu.scheduling.taints import UNREGISTERED_NO_EXECUTE_TAINT
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    provider = KwokCloudProvider(store=store)
    mgr = Manager(store, clock)
    provisioner = Provisioner(store, cluster, provider, clock)
    lifecycle = NodeClaimLifecycle(store, cluster, provider, clock)
    mgr.register(provisioner, PodTrigger(provisioner),
                 Binder(store, cluster, provisioner), lifecycle)

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.provider, e.mgr = \
        clock, store, cluster, provider, mgr
    e.lifecycle = lifecycle
    return e


def settle(env, rounds=6):
    for _ in range(rounds):
        env.mgr.run_until_quiet()
        env.clock.step(1.1)
    assert env.mgr.run_until_quiet(), "manager did not quiesce"


def manual_claim(env, startup_taints=()):
    """A launched claim + fabricated node, driven by direct reconcile()
    calls (no manager) so registration/initialization can be observed
    mid-flight the way the reference drives its controllers."""
    from karpenter_tpu.api.nodeclaim import COND_LAUNCHED
    from karpenter_tpu.api.objects import ObjectMeta
    env.store.create(make_nodepool(name="default"))
    nc = NodeClaim(metadata=ObjectMeta(
        name="manual-nc", namespace="",
        labels={api_labels.NODEPOOL_LABEL_KEY: "default",
                api_labels.LABEL_INSTANCE_TYPE: "c-1x-amd64-linux"}))
    nc.spec.startup_taints = list(startup_taints)
    env.provider.create(nc)  # fabricates the kwok node
    nc.conditions.set_true(COND_LAUNCHED, reason="Launched")
    env.store.create(nc)
    node = next(n for n in env.store.list(Node)
                if n.spec.provider_id == nc.status.provider_id)
    return nc, node


def launch_one(env, pool=None, **pod_kw):
    env.store.create(pool or make_nodepool(name="default"))
    env.store.create(make_pod(**pod_kw))
    settle(env)
    [nc] = env.store.list(NodeClaim)
    return nc, env.store.get(Node, nc.status.node_name)


class TestRegistration:
    """registration_test.go:77-360."""

    def test_owner_reference_added_to_node(self, env):
        nc, node = launch_one(env, cpu="500m")
        [ref] = [r for r in node.metadata.owner_refs if r.kind == "NodeClaim"]
        assert ref.name == nc.name and ref.uid == nc.uid

    def test_registered_label_synced_and_unregistered_taint_removed(self, env):
        nc, node = launch_one(env, cpu="500m")
        assert node.metadata.labels[api_labels.NODE_REGISTERED_LABEL_KEY] == "true"
        assert not any(t.key == api_labels.UNREGISTERED_TAINT_KEY
                       for t in node.spec.taints)
        assert nc.conditions.is_true(COND_REGISTERED)

    def test_labels_and_annotations_synced(self, env):
        pool = make_nodepool(name="default", labels={"team": "ml"})
        pool.spec.template.metadata_annotations["example.com/note"] = "hi"
        nc, node = launch_one(env, pool=pool, cpu="500m")
        assert node.metadata.labels["team"] == "ml"
        assert node.metadata.annotations["example.com/note"] == "hi"

    def test_taints_synced_to_node(self, env):
        pool = make_nodepool(
            name="default",
            taints=[Taint(key="example.com/reserved", value="x",
                          effect="NoSchedule")])
        # the pod must tolerate the pool taint to trigger provisioning
        from karpenter_tpu.api.objects import Toleration
        env.store.create(pool)
        env.store.create(make_pod(cpu="500m", tolerations=[
            Toleration(key="example.com/reserved", operator="Equal",
                       value="x", effect="NoSchedule")]))
        settle(env)
        [nc] = env.store.list(NodeClaim)
        node = env.store.get(Node, nc.status.node_name)
        assert any(t.key == "example.com/reserved" for t in node.spec.taints)

    def test_missing_unregistered_taint_fails_registration(self, env):
        """registration_test.go:115-132: a node that came up without the
        unregistered taint (and isn't labeled registered) violates the
        managed-node invariant."""
        nc, node = manual_claim(env)
        node.spec.taints = [t for t in node.spec.taints
                            if t.key != api_labels.UNREGISTERED_TAINT_KEY]
        node.metadata.labels.pop(api_labels.NODE_REGISTERED_LABEL_KEY, None)
        env.lifecycle.reconcile(nc)
        cond = nc.conditions.get(COND_REGISTERED)
        assert cond is not None and cond.status == "False"
        assert cond.reason == "UnregisteredTaintNotFound"

    def test_startup_taints_not_resynced_after_removal(self, env):
        """registration_test.go:321-360: once the workload removes a startup
        taint, re-reconciling the claim must not restore it."""
        pool = make_nodepool(
            name="default",
            startup_taints=[Taint(key="example.com/agent-not-ready",
                                  effect="NoSchedule")])
        nc, node = launch_one(env, pool=pool, cpu="500m")
        node.spec.taints = [t for t in node.spec.taints
                            if t.key != "example.com/agent-not-ready"]
        env.store.update(node)
        settle(env)
        node = env.store.get(Node, node.name)
        assert not any(t.key == "example.com/agent-not-ready"
                       for t in node.spec.taints)


class TestInitialization:
    """initialization_test.go:115-650."""

    def test_not_initialized_before_registered(self, env):
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="500m"))
        env.mgr.run_until_quiet()  # one pass: launched, not yet settled
        for nc in env.store.list(NodeClaim):
            if not nc.conditions.is_true(COND_REGISTERED):
                assert not nc.conditions.is_true(COND_INITIALIZED)

    def test_initialized_label_added(self, env):
        nc, node = launch_one(env, cpu="500m")
        assert node.metadata.labels[
            api_labels.NODE_INITIALIZED_LABEL_KEY] == "true"
        assert nc.conditions.is_true(COND_INITIALIZED)

    def test_not_ready_node_blocks_initialization(self, env):
        nc, node = manual_claim(env)
        node.status.conditions.append({"type": "Ready", "status": "False"})
        env.lifecycle.reconcile(nc)
        assert nc.conditions.is_true(COND_REGISTERED)
        assert not nc.conditions.is_true(COND_INITIALIZED)
        # kubelet comes up: Ready flips and initialization completes
        node.status.conditions = [{"type": "Ready", "status": "True"}]
        env.lifecycle.reconcile(nc)
        assert nc.conditions.is_true(COND_INITIALIZED)

    def test_unregistered_resources_block_initialization(self, env):
        """initialization_test.go:253-366: a device-plugin resource the
        claim promises must appear on the node before initialization."""
        nc, node = manual_claim(env)
        nc.status.allocatable = dict(nc.status.allocatable)
        nc.status.allocatable["example.com/accelerator"] = 1000
        env.lifecycle.reconcile(nc)
        assert nc.conditions.is_true(COND_REGISTERED)
        assert not nc.conditions.is_true(COND_INITIALIZED)
        node.status.allocatable["example.com/accelerator"] = 1000
        env.lifecycle.reconcile(nc)
        assert nc.conditions.is_true(COND_INITIALIZED)

    def test_startup_taints_block_until_removed(self, env):
        pool = make_nodepool(
            name="default",
            startup_taints=[Taint(key="example.com/agent-not-ready",
                                  effect="NoSchedule")])
        nc, node = launch_one(env, pool=pool, cpu="500m")
        assert not nc.conditions.is_true(COND_INITIALIZED)
        node.spec.taints = [t for t in node.spec.taints
                            if t.key != "example.com/agent-not-ready"]
        env.store.update(node)
        settle(env)
        nc = env.store.get(NodeClaim, nc.name, "")
        assert nc.conditions.is_true(COND_INITIALIZED)

    def test_ephemeral_taints_block_until_removed(self, env):
        nc, node = manual_claim(env)
        node.spec.taints.append(Taint(key="node.kubernetes.io/not-ready",
                                      effect="NoSchedule"))
        env.lifecycle.reconcile(nc)
        assert nc.conditions.is_true(COND_REGISTERED)
        assert not nc.conditions.is_true(COND_INITIALIZED)
        node.spec.taints = [t for t in node.spec.taints
                            if t.key != "node.kubernetes.io/not-ready"]
        env.lifecycle.reconcile(nc)
        assert nc.conditions.is_true(COND_INITIALIZED)


class TestLiveness:
    """liveness_test.go: unregistered claims die at the TTL."""

    def test_unregistered_claim_deleted_after_ttl(self, env):
        env.lifecycle.registration_ttl = 60.0
        env.store.create(make_nodepool(name="default"))
        env.store.create(make_pod(cpu="500m"))
        env.mgr.run_until_quiet()
        # sabotage registration: delete the node out from under the claim
        for node in env.store.list(Node):
            env.store.delete(node)
        env.clock.step(61)
        settle(env, rounds=3)
        # claim deleted; the provisioner may have started a fresh one, but
        # the original is gone
        assert all(nc.status.node_name == "" or
                   env.store.get(Node, nc.status.node_name) is not None
                   for nc in env.store.list(NodeClaim))


class TestKwokKubelet:
    """The sim's out-of-band node agent: startup/ephemeral taints clear and
    Ready stamps after the ready delay."""

    def test_kubelet_sim_clears_startup_taints_and_readies(self, env):
        kubelet = KwokKubelet(env.store, env.clock, ready_delay=2.0)
        env.mgr.register(kubelet)
        pool = make_nodepool(
            name="default",
            startup_taints=[Taint(key="example.com/agent-not-ready",
                                  effect="NoSchedule")])
        env.store.create(pool)
        env.store.create(make_pod(cpu="500m"))
        settle(env)
        [nc] = env.store.list(NodeClaim)
        node = env.store.get(Node, nc.status.node_name)
        assert not any(t.key == "example.com/agent-not-ready"
                       for t in node.spec.taints)
        from karpenter_tpu.utils.node import get_condition
        assert get_condition(node, "Ready")[0] == "True"
        assert nc.conditions.is_true(COND_INITIALIZED)
