"""Pod/node metrics exporters (reference: pkg/controllers/metrics/pod/
suite_test.go + node exporter shapes): the state gauge follows phase and
binding transitions, bound-duration observes once per pod, and combos that
empty out are deleted rather than frozen at their last value."""

import pytest

from karpenter_tpu.api.objects import Node
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.metrics_exporters import (NODE_ALLOCATABLE,
                                                         POD_BOUND_DURATION,
                                                         POD_STATE,
                                                         NodeMetrics,
                                                         PodMetrics)
from karpenter_tpu.kube.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informers import wire_informers
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock)
    cluster = Cluster(store, clock)
    wire_informers(store, cluster)
    mgr = Manager(store, clock)
    pod_metrics = PodMetrics(store, cluster, clock)
    mgr.register(pod_metrics, NodeMetrics(store, cluster))

    class Env:
        pass

    e = Env()
    e.clock, e.store, e.cluster, e.mgr = clock, store, cluster, mgr
    e.pod_metrics = pod_metrics
    return e


class TestPodStateGauge:
    def test_counts_by_phase_and_binding(self, env):
        p1 = make_pod(name="a")
        p2 = make_pod(name="b")
        p2.status.phase = "Running"
        p2.spec.node_name = "n1"
        env.store.create(p1)
        env.store.create(p2)
        env.mgr.run_until_quiet()
        assert POD_STATE.value({"phase": "Pending",
                                "scheduled": "false"}) == 1
        assert POD_STATE.value({"phase": "Running", "scheduled": "true"}) == 1

    def test_state_combo_deleted_when_emptied(self, env):
        """metrics/pod suite_test.go:368+: the state metric disappears with
        the pod instead of freezing at its last value."""
        pod = make_pod(name="only")
        env.store.create(pod)
        env.mgr.run_until_quiet()
        assert POD_STATE.value({"phase": "Pending",
                                "scheduled": "false"}) == 1
        env.store.delete(pod)
        # another pod event refreshes the gauge
        other = make_pod(name="other")
        other.status.phase = "Running"
        other.spec.node_name = "n1"
        env.store.create(other)
        env.mgr.run_until_quiet()
        assert POD_STATE.value({"phase": "Pending",
                                "scheduled": "false"}) == 0

    def test_phase_transition_moves_the_count(self, env):
        pod = make_pod(name="mover")
        env.store.create(pod)
        env.mgr.run_until_quiet()
        pod.status.phase = "Running"
        pod.spec.node_name = "n1"
        env.store.update(pod)
        env.mgr.run_until_quiet()
        assert POD_STATE.value({"phase": "Pending",
                                "scheduled": "false"}) == 0
        assert POD_STATE.value({"phase": "Running", "scheduled": "true"}) == 1


class TestPodBoundDuration:
    def test_bound_observed_once(self, env):
        pod = make_pod(name="bindme")
        env.store.create(pod)
        env.mgr.run_until_quiet()
        before = POD_BOUND_DURATION.count()
        env.clock.step(5)
        pod.spec.node_name = "n1"
        env.store.update(pod)
        env.mgr.run_until_quiet()
        env.store.update(pod)  # a second MODIFIED must not re-observe
        env.mgr.run_until_quiet()
        assert POD_BOUND_DURATION.count() == before + 1


class TestNodeAllocatableGauge:
    def test_node_allocatable_exported(self, env):
        provider = KwokCloudProvider(store=env.store)
        from karpenter_tpu.api.nodeclaim import NodeClaim
        from karpenter_tpu.api.objects import ObjectMeta
        from karpenter_tpu.api import labels as api_labels
        nc = NodeClaim(metadata=ObjectMeta(
            name="m-1", namespace="",
            labels={api_labels.NODEPOOL_LABEL_KEY: "default",
                    api_labels.LABEL_INSTANCE_TYPE: "c-1x-amd64-linux"}))
        provider.create(nc)
        env.mgr.run_until_quiet()
        [node] = env.store.list(Node)
        labels = {"node_name": node.name, "nodepool": "default",
                  "resource_type": "cpu"}
        got = NODE_ALLOCATABLE.value(labels)
        assert got == node.status.allocatable["cpu"]


class TestTerminationMetrics:
    """node/termination/suite_test.go:840-877: terminated counters, the
    termination-duration summary, and the lifetime histogram fire with the
    nodepool label when a node finalizes."""

    def test_termination_metrics_fire_on_finalize(self):
        from karpenter_tpu.api.objects import Node as NodeKind
        from karpenter_tpu.metrics.registry import (NODE_LIFETIME_DURATION,
                                                    NODE_TERMINATION_DURATION,
                                                    NODECLAIMS_TERMINATED,
                                                    NODES_CREATED,
                                                    NODES_TERMINATED)
        from karpenter_tpu.operator.operator import Operator
        from test_operator import settle
        op = Operator(clock=FakeClock())
        labels = {"nodepool": "default"}
        created0 = NODES_CREATED.value(labels)
        term0 = NODES_TERMINATED.value(labels)
        nct0 = NODECLAIMS_TERMINATED.value(labels)
        dur0 = NODE_TERMINATION_DURATION.count(labels)
        life0 = NODE_LIFETIME_DURATION.count(labels)
        op.store.create(make_nodepool(name="default"))
        pod = make_pod(cpu="500m")
        op.store.create(pod)
        settle(op)
        assert NODES_CREATED.value(labels) == created0 + 1
        op.store.delete(pod)
        [node] = op.store.list(NodeKind)
        op.clock.step(120)
        op.store.delete(node)
        settle(op)
        assert op.store.get(NodeKind, node.name) is None
        assert NODES_TERMINATED.value(labels) == term0 + 1
        assert NODECLAIMS_TERMINATED.value(labels) == nct0 + 1
        assert NODE_TERMINATION_DURATION.count(labels) == dur0 + 1
        assert NODE_LIFETIME_DURATION.count(labels) == life0 + 1
        # the lifetime observation reflects the node's ~120 s of life
        assert NODE_LIFETIME_DURATION.sum(labels) >= 100
