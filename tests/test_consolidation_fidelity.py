"""Consolidation-method fidelity: filterOutSameType, timeouts, the >= 2
candidate floor, per-method consolidation memoization, single-node nodepool
fairness, and multi-PDB eviction blocking.

Reference shapes: disruption/multinodeconsolidation.go:110-217,
singlenodeconsolidation.go:44-101, consolidation.go:60-84, utils/pdb.go:56-86.
"""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import COND_CONSOLIDATABLE, NodeClaim
from karpenter_tpu.api.objects import LabelSelector, Node, ObjectMeta, Pod
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.disruption.methods import (MultiNodeConsolidation,
                                              SingleNodeConsolidation,
                                              filter_out_same_type)
from karpenter_tpu.metrics.registry import CONSOLIDATION_TIMEOUTS
from karpenter_tpu.scheduling.requirement import IN, Requirement
from karpenter_tpu.scheduling.requirements import Requirements
from karpenter_tpu.cloudprovider.types import (InstanceType, Offering,
                                               Offerings)
from karpenter_tpu.utils.pdb import Limits

from factories import make_pod

ZONE = "test-zone-1"


def make_it(name, price, cpu=4):
    from karpenter_tpu.utils import resources as res
    return InstanceType(
        name=name,
        requirements=Requirements([
            Requirement(api_labels.LABEL_INSTANCE_TYPE, IN, [name]),
            Requirement(api_labels.LABEL_TOPOLOGY_ZONE, IN, [ZONE]),
            Requirement(api_labels.CAPACITY_TYPE_LABEL_KEY, IN,
                        [api_labels.CAPACITY_TYPE_ON_DEMAND]),
        ]),
        offerings=Offerings([Offering(
            requirements=Requirements([
                Requirement(api_labels.CAPACITY_TYPE_LABEL_KEY, IN,
                            [api_labels.CAPACITY_TYPE_ON_DEMAND]),
                Requirement(api_labels.LABEL_TOPOLOGY_ZONE, IN, [ZONE]),
            ]),
            price=price)]),
        capacity=res.parse_list({"cpu": str(cpu), "memory": "8Gi",
                                 "pods": "110"}))


class FakeStateNode:
    def __init__(self, it_name):
        self._labels = {
            api_labels.LABEL_INSTANCE_TYPE: it_name,
            api_labels.LABEL_TOPOLOGY_ZONE: ZONE,
            api_labels.CAPACITY_TYPE_LABEL_KEY:
                api_labels.CAPACITY_TYPE_ON_DEMAND,
        }

    def labels(self):
        return dict(self._labels)


class FakeCandidate:
    """Just enough Candidate surface for filter_out_same_type/_fair_order."""

    def __init__(self, it, cost=1.0, pool="default", pods=("p",)):
        self.instance_type = it
        self.state_node = FakeStateNode(it.name if it else "")
        self.disruption_cost = cost
        self.nodepool_name = pool
        self.reschedulable_pods = list(pods)


class FakeReplacement:
    def __init__(self, its):
        self.instance_type_options = list(its)
        self.requirements = Requirements([
            Requirement(api_labels.CAPACITY_TYPE_LABEL_KEY, IN,
                        [api_labels.CAPACITY_TYPE_ON_DEMAND]),
            Requirement(api_labels.LABEL_TOPOLOGY_ZONE, IN, [ZONE]),
        ])

    def remove_instance_types_by_price_and_min_values(self, reqs, max_price):
        from karpenter_tpu.cloudprovider.types import satisfies_min_values
        self.instance_type_options = [
            it for it in self.instance_type_options
            if it.offerings.available().worst_launch_price(reqs) < max_price]
        _, err = satisfies_min_values(self.instance_type_options, reqs)
        if err is not None:
            return None, err
        return self, None


class TestFilterOutSameType:
    """multinodeconsolidation.go:164-217 comment scenarios, t3a pricing."""

    def setup_method(self):
        self.nano = make_it("t3a.nano", 0.0047)
        self.small = make_it("t3a.small", 0.0188)
        self.xlarge = make_it("t3a.xlarge", 0.1504)
        self.twoxl = make_it("t3a.2xlarge", 0.3008)

    def test_replacement_including_deleted_type_rejected(self):
        # [2xlarge, 2xlarge, small] -> 1 of {small, xlarge, 2xlarge}: this is
        # really "delete the two 2xlarges" — no valid replacement remains
        candidates = [FakeCandidate(self.twoxl), FakeCandidate(self.twoxl),
                      FakeCandidate(self.small)]
        surviving = filter_out_same_type(
            FakeReplacement([self.small, self.xlarge, self.twoxl]), candidates)
        assert surviving == []

    def test_cheaper_option_survives(self):
        # [2xlarge, 2xlarge, small] -> 1 of {nano, small, xlarge, 2xlarge}:
        # only types strictly cheaper than the deleted small survive
        candidates = [FakeCandidate(self.twoxl), FakeCandidate(self.twoxl),
                      FakeCandidate(self.small)]
        surviving = filter_out_same_type(
            FakeReplacement([self.nano, self.small, self.xlarge, self.twoxl]),
            candidates)
        assert [it.name for it in surviving] == ["t3a.nano"]

    def test_no_overlap_keeps_everything(self):
        candidates = [FakeCandidate(self.twoxl), FakeCandidate(self.xlarge)]
        surviving = filter_out_same_type(
            FakeReplacement([self.nano, self.small]), candidates)
        assert [it.name for it in surviving] == ["t3a.nano", "t3a.small"]

    def test_missing_price_rejects_same_type(self):
        # a candidate whose instance type has NO compatible offering left
        # (e.g. the spot offering was just pulled) prices at 0 in the
        # reference's map lookup -> maxPrice=0 -> replacement rejected
        # (multinodeconsolidation.go filterOutSameType; ADVICE r2 low)
        from karpenter_tpu.utils import resources as res
        pulled = InstanceType(
            name="t3a.xlarge",
            requirements=self.xlarge.requirements,
            offerings=Offerings([]),
            capacity=res.parse_list({"cpu": "16", "memory": "16Gi"}))
        candidates = [FakeCandidate(pulled), FakeCandidate(self.small)]
        surviving = filter_out_same_type(
            FakeReplacement([self.nano, self.xlarge]), candidates)
        assert surviving == []


class TestSingleNodeFairness:
    def test_round_robin_across_nodepools(self):
        its = [make_it(f"it-{i}", 0.1) for i in range(6)]
        cands = [
            FakeCandidate(its[0], cost=1.0, pool="a"),
            FakeCandidate(its[1], cost=2.0, pool="a"),
            FakeCandidate(its[2], cost=3.0, pool="a"),
            FakeCandidate(its[3], cost=1.5, pool="b"),
            FakeCandidate(its[4], cost=2.5, pool="b"),
            FakeCandidate(its[5], cost=4.0, pool="c"),
        ]
        order = SingleNodeConsolidation._fair_order(cands)
        pools = [c.nodepool_name for c in order]
        # first round visits every pool (cheapest-pool-first), then wraps
        assert pools == ["a", "b", "c", "a", "b", "a"]
        costs_a = [c.disruption_cost for c in order if c.nodepool_name == "a"]
        assert costs_a == sorted(costs_a)


class TestMultiPDBBlocking:
    """pdb.go:56-86: ANY matching PDB without headroom blocks eviction, even
    when another matching PDB allows it."""

    def _pdb(self, name, max_unavailable):
        return PodDisruptionBudget(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "x"}),
                         max_unavailable=max_unavailable))

    def test_blocking_pdb_after_permissive_still_blocks(self):
        pod = make_pod(labels={"app": "x"})
        pod.spec.node_name = "n1"
        limits = Limits([self._pdb("permissive", "1"),
                         self._pdb("blocking", "0")], [pod])
        ok, pdb = limits.can_evict(pod)
        assert not ok
        assert pdb.name == "blocking"

    def test_all_permissive_allows(self):
        pod = make_pod(labels={"app": "x"})
        pod.spec.node_name = "n1"
        limits = Limits([self._pdb("p1", "1"), self._pdb("p2", "2")], [pod])
        ok, pdb = limits.can_evict(pod)
        assert ok and pdb is None


class _JumpClock:
    """now() leaps far forward on every call — forces any in-loop deadline."""

    def __init__(self, step=120.0):
        self.t = 0.0
        self.step_size = step

    def now(self):
        self.t += self.step_size
        return self.t


class _FakeCluster:
    def __init__(self):
        self.state = 1.0
        self.clock = _JumpClock(0.0)

    def consolidation_state(self):
        return self.state

    def mark_unconsolidated(self):
        self.state += 1.0
        return self.state


class TestPerMethodMemoization:
    """consolidation.go:60-84: one method marking consolidated must not
    suppress the others; a cluster change re-enables everyone."""

    def test_methods_memoize_independently(self):
        cluster = _FakeCluster()
        multi = MultiNodeConsolidation(cluster, provisioner=None)
        single = SingleNodeConsolidation(cluster, provisioner=None)
        assert not multi.is_consolidated()
        assert not single.is_consolidated()
        multi.mark_consolidated()
        assert multi.is_consolidated()
        assert not single.is_consolidated()   # the ADVICE regression
        single.mark_consolidated()
        assert single.is_consolidated()
        cluster.mark_unconsolidated()
        assert not multi.is_consolidated()
        assert not single.is_consolidated()


class TestFloorsAndTimeouts:
    def test_multi_node_needs_two_candidates(self):
        cluster = _FakeCluster()
        multi = MultiNodeConsolidation(cluster, provisioner=None)
        it = make_it("only", 0.1)
        cmd, results = multi._first_n_consolidation_option(
            [FakeCandidate(it)])
        assert cmd.is_empty()

    def test_single_node_timeout_counts_metric(self):
        cluster = _FakeCluster()
        single = SingleNodeConsolidation(cluster, provisioner=None,
                                         clock=_JumpClock(200.0))
        it = make_it("a", 0.1)
        cands = [FakeCandidate(it, cost=float(i)) for i in range(4)]
        before = CONSOLIDATION_TIMEOUTS.value({"consolidation_type": "single"})
        cmd, results = single.compute_command({"default": 10}, cands)
        assert cmd.is_empty()
        after = CONSOLIDATION_TIMEOUTS.value({"consolidation_type": "single"})
        assert after == before + 1


class TestSingleNodeTimeoutPreservesConstrained:
    """ISSUE 3 satellite regression: a timed-out (or budget-constrained)
    single-node pass proved nothing about its unevaluated candidates, so it
    must never mark_consolidated() — else a later pass against unchanged
    cluster state is silently skipped (is_consolidated() short-circuits in
    the controller) and the pools it never looked at stay unconsolidated
    forever. Only a COMPLETED, unconstrained, decision-free scan memoizes."""

    def _cands(self, n=4, pods=("p",)):
        it = make_it("a", 0.1)
        return [FakeCandidate(it, cost=float(i), pods=pods) for i in range(n)]

    def test_timed_out_pass_never_memoizes(self):
        cluster = _FakeCluster()
        single = SingleNodeConsolidation(cluster, provisioner=None,
                                         clock=_JumpClock(200.0))
        cmd, _ = single.compute_command({"default": 10}, self._cands())
        assert cmd.is_empty()
        assert not single.is_consolidated()

    def test_timed_out_pass_still_reports_budget_constraint(self):
        # budgets admit ONE candidate; the deadline fires before evaluating
        # it — the constrained signal computed up front must survive the
        # early return (no memoization either way)
        cluster = _FakeCluster()
        single = SingleNodeConsolidation(cluster, provisioner=None,
                                         clock=_JumpClock(200.0))
        cmd, _ = single.compute_command({"default": 1}, self._cands())
        assert cmd.is_empty()
        assert not single.is_consolidated()

    def test_budget_constrained_pass_never_memoizes(self):
        cluster = _FakeCluster()
        single = SingleNodeConsolidation(cluster, provisioner=None,
                                         clock=_JumpClock(0.0))
        cmd, _ = single.compute_command({"default": 0}, self._cands())
        assert cmd.is_empty()
        assert not single.is_consolidated()

    def test_completed_unconstrained_empty_pass_memoizes(self):
        # all candidates empty (Emptiness' job): the scan completes with
        # nothing to do and no constraint — the one legal memoization
        cluster = _FakeCluster()
        single = SingleNodeConsolidation(cluster, provisioner=None,
                                         clock=_JumpClock(0.0))
        cmd, _ = single.compute_command({"default": 10},
                                        self._cands(pods=()))
        assert cmd.is_empty()
        assert single.is_consolidated()


class TestEmptyProbeGroup:
    def test_cluster_zone_counts_skips_empty_groups(self):
        """Prefix probes empty a group when all its pods belong to
        non-prefix candidates; counting must skip it, not crash."""
        from karpenter_tpu.provisioning.grouping import (SPREAD_ZONE,
                                                         PodGroup, TopoSpec)
        from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

        ts = TensorScheduler([], {})
        g = PodGroup(pods=[], requirements=Requirements(), requests={},
                     tolerations=(), labels={"app": "x"},
                     topo=[TopoSpec(SPREAD_ZONE)])
        izc = ts.cluster_zone_counts([g], ["z1", "z2"], set())
        assert izc.shape == (1, 2) and not izc.any()


class TestSpotToSpotTruncation:
    """consolidation.go:229-302 + consolidation_test.go:932-1486: the
    spot-to-spot gate, the >= 15-cheaper-types floor, and the launch-list
    cap — max(15, minValues prefix) with minValues, flat 15 without."""

    def _method(self, enabled=True):
        from karpenter_tpu.disruption.methods import SingleNodeConsolidation
        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.events.recorder import Recorder
        m = SingleNodeConsolidation.__new__(SingleNodeConsolidation)
        m.spot_to_spot_enabled = enabled
        m.clock = FakeClock()
        m.recorder = Recorder(m.clock)
        return m

    def _results(self, n_types, min_values=None):
        from karpenter_tpu.api import labels as api_labels
        from karpenter_tpu.cloudprovider.kwok import construct_catalog
        from karpenter_tpu.cloudprovider.types import (order_by_price,
                                                       satisfies_min_values)
        from karpenter_tpu.scheduling.requirement import IN, Requirement
        from karpenter_tpu.scheduling.requirements import Requirements

        catalog = construct_catalog(max(n_types, 40))
        reqs = Requirements()
        if min_values is not None:
            reqs.add(Requirement(api_labels.LABEL_INSTANCE_TYPE, IN,
                                 [it.name for it in catalog],
                                 min_values=min_values))
        # deliberately REVERSED price order: the production path hands the
        # decision unordered host-claim options; decide()'s order_by_price
        # (consolidation.go:183) must do the sorting, and these assertions
        # must fail if it ever stops (the kwok catalog happens to be
        # price-ascending, so plain catalog order would be vacuous)
        its = list(reversed(catalog))[:n_types]

        class StubClaim:
            def __init__(self):
                self.requirements = reqs
                self.instance_type_options = list(its)

            def remove_instance_types_by_price_and_min_values(
                    self, requirements, max_price):
                self.instance_type_options = [
                    it for it in self.instance_type_options
                    if it.offerings.available().worst_launch_price(
                        requirements) < max_price]
                _, err = satisfies_min_values(self.instance_type_options,
                                              requirements)
                return (None, err) if err else (self, None)

        class StubResults:
            new_nodeclaims = [StubClaim()]

        return StubResults()

    def _decide(self, method, results, n_candidates=1):
        """Enter through decide() — the real path, where the price sort
        lives (consolidation.go:183)."""
        from karpenter_tpu.api import labels as api_labels

        class StubCandidate:
            capacity_type = api_labels.CAPACITY_TYPE_SPOT
            name = "stub-node"

            class _SN:
                nodeclaim = None
            state_node = _SN()

            def price(self):
                return 1e9

        return method.decide([StubCandidate()] * n_candidates, results, None)

    @staticmethod
    def _prices(claim):
        return [it.offerings.available().cheapest().price
                for it in claim.instance_type_options]

    def test_disabled_gate_blocks(self):
        cmd, _ = self._decide(self._method(enabled=False), self._results(30))
        assert cmd.is_empty()

    def test_fewer_than_15_cheaper_blocks(self):
        cmd, _ = self._decide(self._method(), self._results(10))
        assert cmd.is_empty()

    def test_default_caps_at_15_cheapest(self):
        r = self._results(30)
        cmd, _ = self._decide(self._method(), r)
        assert not cmd.is_empty()
        prices = self._prices(cmd.replacements[0])
        assert len(prices) == 15
        assert prices == sorted(prices)  # the CHEAPEST 15, price-ordered

    def test_min_values_above_15_raises_cap(self):
        r = self._results(30, min_values=20)
        cmd, _ = self._decide(self._method(), r)
        assert not cmd.is_empty()
        prices = self._prices(cmd.replacements[0])
        assert len(prices) == 20
        assert prices == sorted(prices)

    def test_min_values_below_15_keeps_default(self):
        r = self._results(30, min_values=5)
        cmd, _ = self._decide(self._method(), r)
        assert not cmd.is_empty()
        assert len(cmd.replacements[0].instance_type_options) == 15
