"""Seeded parity fuzzer for the batched leave-one-out single-node engine.

ISSUE 3's contract: `SingleNodeConsolidation.compute_command` through the
batched `LeaveOneOutEngine` (shared DisruptionSnapshot encode + closed-form
per-candidate classification) must return the SAME decision — same
candidate, same replacement instance-type options, same pod errors — as the
reference's serial shape (one full `simulate_scheduling` per candidate, the
per-candidate host oracle). Every case is seed-pinned: a divergence
reproduces by running its seed.

The generator deliberately covers the cases the classifier special-cases:
spot candidates under the spot-to-spot gate and its >= 15-cheaper-types cap
(both enabled and disabled), minValues pools (which push the whole batch
onto the needs_sim fallback rows), uninitialized managed nodes (whose
placements must reject a candidate), multi-pod and multi-group candidates,
and nodes too full to absorb anything.
"""

import random

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.disruption import methods as methods_mod
from karpenter_tpu.disruption.helpers import (build_disruption_budget_mapping,
                                              get_candidates)
from karpenter_tpu.disruption.methods import SingleNodeConsolidation

from expectations import (OD, SPOT, MinValuesReq, bind_pod, catalog,
                          consolidation_nodepool, make_env,
                          make_nodeclaim_and_node)

CPUS = ("100m", "250m", "500m", "1", "2")


def build_cluster(seed: int):
    rng = random.Random(seed)
    spot_to_spot = rng.random() < 0.5
    pool = consolidation_nodepool()
    if rng.random() < 0.2:
        # minValues gates the whole batch onto the needs_sim fallback rows:
        # decisions must still match the oracle exactly
        pool.spec.template.spec.requirements = [MinValuesReq(
            api_labels.LABEL_INSTANCE_TYPE, "Exists", (),
            rng.choice((5, 20)))]
    env = make_env(pool, spot_to_spot=spot_to_spot)
    its = sorted(catalog(), key=lambda it: it.name)
    n_nodes = rng.randint(18, 26)  # above the engine's 16-candidate floor
    for i in range(n_nodes):
        ct = SPOT if rng.random() < 0.4 else OD
        it = rng.choice(its)
        # a slice of nodes stays uninitialized AND unconsolidatable: they
        # are packing targets whose placements must reject a candidate
        initialized = rng.random() > 0.15
        cores = max(1, it.capacity.get("cpu", 4000) // 1000)
        alloc = {"cpu": str(cores), "memory": "16Gi", "pods": "110"}
        nc, node = make_nodeclaim_and_node(
            env, capacity_type=ct, instance_type=it, allocatable=alloc,
            initialized=initialized, consolidatable=initialized)
        shape = rng.random()
        if shape < 0.45:
            # mostly-full node: one pod at ~80% of allocatable — delete is
            # infeasible unless a larger node has matching headroom, so the
            # replace/price classification actually decides these rows
            bind_pod(env, node, cpu=f"{cores * 800}m", memory="128Mi")
        elif shape < 0.6 and cores >= 2:
            # two same-shape pods (one group, k=2)
            for _ in range(2):
                bind_pod(env, node, cpu=f"{cores * 250}m", memory="128Mi")
        else:
            # lightly loaded: delete-shaped rows (multi-group when 2 pods)
            for _ in range(rng.randint(0, 2)):
                bind_pod(env, node, cpu=rng.choice(CPUS), memory="128Mi")
    env.clock.step(600)
    env.settle(rounds=1)
    return env, spot_to_spot


def run_single_node(env, spot_to_spot: bool, batched: bool):
    """One compute_command pass; batched=False forces the reference's
    serial shape (per-candidate simulate_scheduling, the parity oracle)."""
    saved = methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES
    methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = 1 if batched else 10**9
    try:
        m = SingleNodeConsolidation(env.cluster, env.provisioner,
                                    spot_to_spot_enabled=spot_to_spot,
                                    clock=env.clock)
        cands = get_candidates(env.cluster, env.provisioner, m.should_disrupt)
        budgets = build_disruption_budget_mapping(env.cluster, m.reason)
        cmd, results = m.compute_command(budgets, cands)
        stats = m.last_engine_stats
    finally:
        methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = saved
    return cands, cmd, results, stats


def summarize(cmd, results):
    return {
        "decision": cmd.decision,
        "candidates": [c.name for c in cmd.candidates],
        "replacements": [[it.name for it in r.instance_type_options]
                         for r in cmd.replacements],
        "pod_errors": (sorted(results.pod_errors)
                       if results is not None
                       and getattr(results, "pod_errors", None) else []),
    }


# seed-pinned corpus: any failure names its seed for replay
@pytest.mark.parametrize("seed", list(range(7000, 7024)))
def test_leave_one_out_matches_per_candidate_oracle(seed):
    env, spot_to_spot = build_cluster(seed)
    cands_b, cmd_b, res_b, stats = run_single_node(env, spot_to_spot, True)
    cands_o, cmd_o, res_o, _ = run_single_node(env, spot_to_spot, False)
    assert [c.name for c in cands_b] == [c.name for c in cands_o]
    got, want = summarize(cmd_b, res_b), summarize(cmd_o, res_o)
    assert got == want, (seed, stats, got, want)
    if cands_b:
        assert stats is not None, (seed, "engine never engaged")


def test_replace_win_classified_without_extra_probes():
    """Directed scenario killing price-path misclassification: 17 identical
    stuck nodes (most expensive type, immovable pod) where a cheaper
    replacement exists. The engine must classify every row (no fallback
    sims), probe ONLY the winner, and agree with the oracle's replace."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(OD)
    for _ in range(17):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=OD, instance_type=it,
            allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
        bind_pod(env, node, cpu="600m", memory="128Mi")  # > 400m headroom
    env.clock.step(600)
    env.settle(rounds=1)
    cands, cmd, res, stats = run_single_node(env, False, True)
    assert len(cands) == 17
    assert cmd.decision == "replace", summarize(cmd, res)
    assert stats["needs_sim"] == 0 and stats["probes"] == 1, stats
    assert stats["classified"] == 17, stats
    _, cmd_o, res_o, _ = run_single_node(env, False, False)
    assert summarize(cmd, res) == summarize(cmd_o, res_o)


def test_all_stuck_spot_rejects_without_any_probe():
    """Directed scenario killing reject-path laxity: 17 stuck SPOT nodes
    with spot-to-spot disabled must classify to rejection with ZERO probes
    (an always-probe regression shows up as probes > 0), and the pass must
    memoize (nothing to do, no budget constraint)."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(SPOT)
    for _ in range(17):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
        bind_pod(env, node, cpu="600m", memory="128Mi")
    env.clock.step(600)
    env.settle(rounds=1)
    saved = methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES
    methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = 1
    try:
        m = SingleNodeConsolidation(env.cluster, env.provisioner,
                                    spot_to_spot_enabled=False,
                                    clock=env.clock, recorder=env.recorder)
        cands = get_candidates(env.cluster, env.provisioner, m.should_disrupt)
        budgets = build_disruption_budget_mapping(env.cluster, m.reason)
        cmd, _ = m.compute_command(budgets, cands)
    finally:
        methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = saved
    assert len(cands) == 17
    assert cmd.is_empty()
    assert m.last_engine_stats["probes"] == 0, m.last_engine_stats
    assert m.last_engine_stats["classified"] == 17
    assert m.is_consolidated()
    msgs = [e.message for e in env.events("Unconsolidatable")]
    assert any("SpotToSpotConsolidation is disabled" in msg for msg in msgs)


def test_uninitialized_target_rejects_without_any_probe():
    """Directed scenario for the uninitialized-node rejection
    (helpers.go:93-111): every candidate's pod fits ONLY onto a managed
    uninitialized node, which poisons the simulated placement — the
    classifier must reject all rows with ZERO probes (a dropped rejection
    self-heals through wasted probes, which this pins), and the oracle
    agrees the pass is a no-op."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(OD)
    for _ in range(17):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=OD, instance_type=it,
            allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
        bind_pod(env, node, cpu="600m", memory="128Mi")
    # the only node with headroom is managed but NOT initialized
    make_nodeclaim_and_node(
        env, capacity_type=OD, instance_type=it,
        allocatable={"cpu": "32", "memory": "64Gi", "pods": "110"},
        initialized=False, consolidatable=False)
    env.clock.step(600)
    env.settle(rounds=1)
    saved = methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES
    methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = 1
    try:
        m = SingleNodeConsolidation(env.cluster, env.provisioner,
                                    clock=env.clock, recorder=env.recorder)
        cands = get_candidates(env.cluster, env.provisioner, m.should_disrupt)
        budgets = build_disruption_budget_mapping(env.cluster, m.reason)
        cmd, _ = m.compute_command(budgets, cands)
        stats = m.last_engine_stats
    finally:
        methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = saved
    assert len(cands) == 17
    assert cmd.is_empty()
    assert stats["needs_sim"] == 0 and stats["probes"] == 0, stats
    # the rejection must be FOR the uninitialized placement, not an
    # accidental arithmetic dead end
    msgs = [e.message for e in env.events("Unconsolidatable")]
    assert any("uninitialized" in msg for msg in msgs), msgs[:3]
    _, cmd_o, res_o, _ = run_single_node(env, False, False)
    assert cmd_o.is_empty()


def test_budget_gates_pools_but_never_decrements():
    """singlenodeconsolidation.go:55-68 regression pin: a single-node
    command disrupts exactly ONE node, so the budget check only skips
    zero-budget pools — it must NOT decrement per scanned candidate, or a
    budget of 1 caps the scan at the single cheapest candidate and a win
    sitting past the cap is starved forever. 17 stuck spot nodes (every
    one rejected) followed by the one consolidatable node, all in one pool
    with budget 1: the decision must still be found."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(SPOT)
    for _ in range(17):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
        bind_pod(env, node, cpu="600m", memory="128Mi")
    # two pods => rescheduling cost 2 => LAST in the fair order; each fits
    # the stuck nodes' 400m headroom, so deletion wins — while the winner's
    # own 400m headroom stays too small to absorb any 600m stuck pod
    _, winner = make_nodeclaim_and_node(
        env, capacity_type=OD, instance_type=most_expensive_instance(OD),
        allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
    for _ in range(2):
        bind_pod(env, winner, cpu="300m", memory="128Mi")
    env.clock.step(600)
    env.settle(rounds=1)
    saved = methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES
    methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = 1
    try:
        m = SingleNodeConsolidation(env.cluster, env.provisioner,
                                    clock=env.clock)
        cands = get_candidates(env.cluster, env.provisioner, m.should_disrupt)
        assert len(cands) == 18
        cmd, _ = m.compute_command({"default": 1}, cands)
    finally:
        methods_mod.SINGLE_NODE_BATCH_MIN_CANDIDATES = saved
    assert cmd.decision == "delete", cmd.decision
    assert [c.name for c in cmd.candidates] == [winner.name]


# -- ranked multi-node subset search (ISSUE 14) ------------------------------


def run_multi_node(env, spot_to_spot: bool, batched: bool):
    """One MultiNodeConsolidation compute_command pass; batched=False
    forces the engine-off binary search (every midpoint replays — the
    parity oracle for the closed-form subset verdicts)."""
    from karpenter_tpu.disruption.methods import MultiNodeConsolidation
    saved = methods_mod.MULTI_NODE_BATCH_MIN_CANDIDATES
    methods_mod.MULTI_NODE_BATCH_MIN_CANDIDATES = 2 if batched else 10**9
    try:
        m = MultiNodeConsolidation(env.cluster, env.provisioner,
                                   spot_to_spot_enabled=spot_to_spot,
                                   clock=env.clock)
        cands = get_candidates(env.cluster, env.provisioner, m.should_disrupt)
        budgets = build_disruption_budget_mapping(env.cluster, m.reason)
        cmd, results = m.compute_command(budgets, cands)
        stats = m.last_multi_engine_stats
    finally:
        methods_mod.MULTI_NODE_BATCH_MIN_CANDIDATES = saved
    return cands, cmd, results, stats


@pytest.mark.parametrize("seed", list(range(7100, 7124)))
def test_multi_node_subset_engine_matches_binary_search_oracle(seed):
    """The exactness contract end to end: skipping provably-rejected
    midpoints must never change the binary search's decision."""
    env, spot_to_spot = build_cluster(seed)
    cands_b, cmd_b, res_b, _ = run_multi_node(env, spot_to_spot, True)
    cands_o, cmd_o, res_o, _ = run_multi_node(env, spot_to_spot, False)
    assert [c.name for c in cands_b] == [c.name for c in cands_o]
    got, want = summarize(cmd_b, res_b), summarize(cmd_o, res_o)
    assert got == want, (seed, got, want)


def _count_replays(monkeypatch):
    from karpenter_tpu.disruption import prefix as prefix_mod
    calls = {"n": 0}
    orig = prefix_mod.SnapshotEncoding.simulate_subset

    def counted(self, idxs):
        calls["n"] += 1
        return orig(self, idxs)

    monkeypatch.setattr(prefix_mod.SnapshotEncoding, "simulate_subset",
                        counted)
    return calls


def test_multi_node_all_stuck_spot_rejects_without_any_replay(monkeypatch):
    """Directed: 17 stuck SPOT nodes with spot-to-spot disabled — every
    prefix is provably rejected in closed form (single group, overflow,
    spot gate), so the whole binary search runs with ZERO host replays
    and agrees with the oracle's empty command."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(SPOT)
    for _ in range(17):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=SPOT, instance_type=it,
            allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
        bind_pod(env, node, cpu="600m", memory="128Mi")
    env.clock.step(600)
    env.settle(rounds=1)
    calls = _count_replays(monkeypatch)
    cands, cmd, _, stats = run_multi_node(env, False, True)
    assert len(cands) == 17
    assert cmd.is_empty()
    assert calls["n"] == 0, calls
    assert stats is not None and stats["probes_saved"] > 0, stats
    _, cmd_o, _, _ = run_multi_node(env, False, False)
    assert cmd_o.is_empty()


def test_multi_node_uninitialized_target_rejects_without_any_replay(
        monkeypatch):
    """Directed: the only headroom is an uninitialized managed node —
    every prefix's fill provably reaches it, so every midpoint rejects
    closed-form with zero replays (the multi-excluded-column threshold
    math), and the oracle agrees."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(OD)
    for _ in range(17):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=OD, instance_type=it,
            allocatable={"cpu": "1", "memory": "8Gi", "pods": "110"})
        bind_pod(env, node, cpu="600m", memory="128Mi")
    make_nodeclaim_and_node(
        env, capacity_type=OD, instance_type=it,
        allocatable={"cpu": "32", "memory": "64Gi", "pods": "110"},
        initialized=False, consolidatable=False)
    env.clock.step(600)
    env.settle(rounds=1)
    calls = _count_replays(monkeypatch)
    cands, cmd, _, stats = run_multi_node(env, False, True)
    assert len(cands) == 17
    assert cmd.is_empty()
    assert calls["n"] == 0, calls
    assert stats["probes_saved"] > 0, stats
    _, cmd_o, _, _ = run_multi_node(env, False, False)
    assert cmd_o.is_empty()


def test_multi_node_win_found_with_engine_on():
    """Directed: lightly-loaded identical nodes whose pods all fit
    elsewhere — the search must land a non-empty command (here the full
    prefix replaced by one cheaper node beats a shorter delete), and the
    engine-on search must find exactly what the oracle finds."""
    from expectations import most_expensive_instance
    env = make_env()
    it = most_expensive_instance(OD)
    for _ in range(8):
        _, node = make_nodeclaim_and_node(
            env, capacity_type=OD, instance_type=it,
            allocatable={"cpu": "4", "memory": "16Gi", "pods": "110"})
        bind_pod(env, node, cpu="100m", memory="64Mi")
    env.clock.step(600)
    env.settle(rounds=1)
    _, cmd_b, res_b, _ = run_multi_node(env, False, True)
    _, cmd_o, res_o, _ = run_multi_node(env, False, False)
    assert not cmd_b.is_empty()
    assert summarize(cmd_b, res_b) == summarize(cmd_o, res_o)


def test_fuzz_covers_the_feature_space():
    """Meta-check: across the pinned seeds the generator exercised spot
    candidates, both spot-to-spot settings, minValues pools, uninitialized
    nodes, and multi-pod nodes — and at least a few non-trivial decisions
    and a few classified (non-fallback) batches actually happened."""
    saw = {"spot": False, "spot_to_spot_on": False, "spot_to_spot_off": False,
           "min_values": False, "uninitialized": False, "multi_pod": False,
           "decision": False, "classified_rows": False}
    for seed in range(7000, 7024):
        rng = random.Random(seed)
        saw["spot_to_spot_on"] |= rng.random() < 0.5
        env, spot_to_spot = build_cluster(seed)
        saw["spot_to_spot_off"] |= not spot_to_spot
        pool = env.store.list(type(consolidation_nodepool()))[0]
        saw["min_values"] |= bool(pool.spec.template.spec.requirements)
        by_ct = [sn.labels().get(api_labels.CAPACITY_TYPE_LABEL_KEY)
                 for sn in env.cluster.state_nodes(deep_copy=False)]
        saw["spot"] |= SPOT in by_ct
        saw["uninitialized"] |= any(
            sn.managed() and not sn.initialized()
            for sn in env.cluster.state_nodes(deep_copy=False))
        from karpenter_tpu.disruption.helpers import pods_by_node
        counts = [len(v) for v in pods_by_node(env.cluster).values()]
        saw["multi_pod"] |= any(c > 1 for c in counts)
        _, cmd, _, stats = run_single_node(env, spot_to_spot, True)
        saw["decision"] |= not cmd.is_empty()
        saw["classified_rows"] |= bool(stats and stats["classified"] > 0)
    missing = [k for k, v in saw.items() if not v]
    assert not missing, f"fuzzer never generated: {missing}"


# -- KARPENTER_LOO_MIN_CANDIDATES (ISSUE 14 satellite) -----------------------


class TestLooMinCandidatesKnob:
    def test_default_when_unset(self, monkeypatch):
        monkeypatch.delenv("KARPENTER_LOO_MIN_CANDIDATES", raising=False)
        assert methods_mod._loo_min_candidates_from_env() == 16

    def test_valid_values_apply(self, monkeypatch):
        for raw, want in (("0", 0), ("1", 1), ("42", 42)):
            monkeypatch.setenv("KARPENTER_LOO_MIN_CANDIDATES", raw)
            assert methods_mod._loo_min_candidates_from_env() == want

    @pytest.mark.parametrize("raw", ["sixteen", "1.5", "", " ", "-3"])
    def test_invalid_values_reject_loudly(self, monkeypatch, raw):
        monkeypatch.setenv("KARPENTER_LOO_MIN_CANDIDATES", raw)
        with pytest.raises(SystemExit) as exc:
            methods_mod._loo_min_candidates_from_env()
        assert "KARPENTER_LOO_MIN_CANDIDATES" in str(exc.value)
        assert repr(raw) in str(exc.value)

    def test_module_floor_reads_env_at_import(self):
        """The module-level floor is initialized from the env parser (a
        subprocess pins the end-to-end wiring without reloading the module
        under other tests' feet)."""
        import subprocess
        import sys
        code = ("import karpenter_tpu.disruption.methods as m; "
                "print(m.SINGLE_NODE_BATCH_MIN_CANDIDATES)")
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                 "KARPENTER_LOO_MIN_CANDIDATES": "7",
                 "PYTHONPATH": "."},
            capture_output=True, text=True, cwd=".")
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "7"
        bad = subprocess.run(
            [sys.executable, "-c", code],
            env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                 "KARPENTER_LOO_MIN_CANDIDATES": "nope",
                 "PYTHONPATH": "."},
            capture_output=True, text=True, cwd=".")
        assert bad.returncode != 0
        assert "KARPENTER_LOO_MIN_CANDIDATES" in bad.stderr
