"""Tensor-kernel vs host-algebra parity: the encoded mask/bound arithmetic must
reproduce Requirements.Intersects/Compatible and Requirement.Intersection
exactly, including complement/NotIn/Gt/Lt corner cases."""

import random

import numpy as np
import pytest

from karpenter_tpu.ops import encode as enc
from karpenter_tpu.ops import feasibility as feas
from karpenter_tpu.scheduling.requirement import Requirement
from karpenter_tpu.scheduling.requirements import (ALLOW_UNDEFINED_WELL_KNOWN,
                                                   Requirements)

KEYS = ["topology.kubernetes.io/zone", "kubernetes.io/arch", "example.com/team",
        "example.com/tier", "example.com/gen"]
VALUES = {
    "topology.kubernetes.io/zone": ["z1", "z2", "z3", "z4"],
    "kubernetes.io/arch": ["amd64", "arm64"],
    "example.com/team": ["a", "b", "c"],
    "example.com/tier": ["1", "2", "7", "12"],
    "example.com/gen": ["1", "3", "5", "9", "x"],
}
INT_KEYS = ["example.com/tier", "example.com/gen"]


def random_requirements(rng: random.Random) -> Requirements:
    reqs = Requirements()
    for key in KEYS:
        roll = rng.random()
        if roll < 0.35:
            continue  # undefined
        vals = VALUES[key]
        if roll < 0.55:
            reqs.add(Requirement(key, "In", rng.sample(vals, rng.randint(1, len(vals)))))
        elif roll < 0.7:
            reqs.add(Requirement(key, "NotIn", rng.sample(vals, rng.randint(1, len(vals)))))
        elif roll < 0.78:
            reqs.add(Requirement(key, "Exists"))
        elif roll < 0.84:
            reqs.add(Requirement(key, "DoesNotExist"))
        elif key in INT_KEYS:
            op = "Gt" if rng.random() < 0.5 else "Lt"
            reqs.add(Requirement(key, op, [str(rng.randint(0, 13))]))
        else:
            reqs.add(Requirement(key, "In", rng.sample(vals, 1)))
    return reqs


def build_vocab(all_reqs):
    v = enc.Vocab()
    for key in KEYS:
        v.add_key(key)
        for val in VALUES[key]:
            v.add_value(key, val)
    for r in all_reqs:
        v.observe_requirements(r)
    v.freeze()
    return v


@pytest.fixture(scope="module")
def random_pairs():
    rng = random.Random(42)
    a_sets = [random_requirements(rng) for _ in range(40)]
    b_sets = [random_requirements(rng) for _ in range(40)]
    return a_sets, b_sets


def test_intersects_parity(random_pairs):
    a_sets, b_sets = random_pairs
    vocab = build_vocab(a_sets + b_sets)
    a = feas.to_device(enc.stack_encoded([enc.encode_requirements(vocab, r) for r in a_sets]))
    b = feas.to_device(enc.stack_encoded([enc.encode_requirements(vocab, r) for r in b_sets]))
    got = np.asarray(feas.intersects_matrix(a, b))
    for i, ra in enumerate(a_sets):
        for j, rb in enumerate(b_sets):
            want = not ra.intersects(rb)
            assert got[i, j] == want, (
                f"intersects mismatch a={ra!r} b={rb!r} got={got[i, j]} want={want}")


def test_compatible_parity(random_pairs):
    a_sets, b_sets = random_pairs
    vocab = build_vocab(a_sets + b_sets)
    a = feas.to_device(enc.stack_encoded([enc.encode_requirements(vocab, r) for r in a_sets]))
    b = feas.to_device(enc.stack_encoded([enc.encode_requirements(vocab, r) for r in b_sets]))
    allow = np.array([k in ALLOW_UNDEFINED_WELL_KNOWN for k in vocab.keys])
    got = np.asarray(feas.compatible_matrix(a, b, allow))
    for i, ra in enumerate(a_sets):
        for j, rb in enumerate(b_sets):
            want = ra.is_compatible(rb, ALLOW_UNDEFINED_WELL_KNOWN)
            assert got[i, j] == want, (
                f"compatible mismatch a={ra!r} b={rb!r} got={got[i, j]} want={want}")


def test_combine_parity(random_pairs):
    """combine(a,b).has(v) must equal host intersection membership for every
    vocab value, and emptiness/exemption flags must line up."""
    a_sets, b_sets = random_pairs
    vocab = build_vocab(a_sets + b_sets)
    a = feas.to_device(enc.stack_encoded([enc.encode_requirements(vocab, r) for r in a_sets]))
    b = feas.to_device(enc.stack_encoded([enc.encode_requirements(vocab, r) for r in b_sets]))
    # align pairwise (i with i)
    merged = feas.combine(a, b)
    mask = np.asarray(merged.mask)
    for i, (ra, rb) in enumerate(zip(a_sets, b_sets)):
        for key in KEYS:
            k = vocab.key_idx[key]
            inter = ra.get(key).intersection(rb.get(key))
            for vi, val in enumerate(vocab.values[k]):
                got_bit = bool((mask[i, k, vi // 32] >> (vi % 32)) & 1)
                assert got_bit == inter.has(val), (
                    f"combine bit mismatch key={key} val={val} a={ra.get(key)!r} "
                    f"b={rb.get(key)!r} got={got_bit}")
            # OTHER bit == complement-ness of the host intersection
            ob = vocab.other_bit(k)
            got_other = bool((mask[i, k, ob // 32] >> (ob % 32)) & 1)
            assert got_other == inter.complement


def test_fits_matrix():
    requests = np.array([[100, 200, 1], [50, 800, 1], [0, 0, 0]], dtype=np.int32)
    avail = np.array([[100, 500, 10], [40, 900, 10]], dtype=np.int32)
    got = np.asarray(feas.fits_matrix(requests, avail))  # [A=2 avail, B=3 requests]
    assert got.tolist() == [[True, False, True], [False, False, True]]


def test_pods_per_node():
    alloc = np.array([[1000, 4096, 16], [4000, 16384, 64]], dtype=np.int32)
    overhead = np.array([[100, 0, 0]], dtype=np.int32)
    req = np.array([[250, 512, 1], [5000, 512, 1]], dtype=np.int32)
    got = np.asarray(feas.pods_per_node(alloc, overhead, req))
    # group 0: t0 -> min(900//250=3, 8, 16)=3 ; t1 -> min(15, 32, 64)=15
    assert got[0, 0].tolist() == [3, 15]
    # group 1 never fits
    assert got[1, 0].tolist() == [0, 0]


def test_pack_bits_bit_column_round_trip():
    """encode.pack_bits/bit_column carry the packer's per-cohort
    zone-feasibility bitfield (binpack.CohortSet.okz): every position must
    survive the pack, and bitwise AND of packed rows must equal the AND of
    the bool planes."""
    rng = np.random.default_rng(7)
    for z in (1, 3, 6, 8, 9, 17):
        a = rng.random((5, 11, z)) < 0.5
        b = rng.random((5, 11, z)) < 0.5
        pa, pb = enc.pack_bits(a), enc.pack_bits(b)
        assert pa.shape == (5, 11, -(-z // 8))
        for i in range(z):
            np.testing.assert_array_equal(enc.bit_column(pa, i), a[..., i])
            np.testing.assert_array_equal(
                enc.bit_column(pa & pb, i), (a & b)[..., i])


def test_vocab_observed_value_indices_are_sorted():
    """Vocab value indices must not depend on set iteration order
    (PYTHONHASHSEED): the packer breaks zone-water-fill ties on value
    INDEX, so hash-ordered indices made the same spread solve pick
    different zones in different processes. observe_requirements inserts
    each key's unseen values in sorted order."""
    reqs = Requirements([
        Requirement("topology.kubernetes.io/zone", "In",
                    ["test-zone-c", "test-zone-a", "test-zone-b"]),
        Requirement("kubernetes.io/arch", "NotIn", ["arm64", "amd64"]),
    ])
    v = enc.Vocab()
    v.observe_requirements(reqs)
    for k in range(v.K):
        assert v.values[k] == sorted(v.values[k]), (v.keys[k], v.values[k])
    # previously-observed values keep their indices; only NEW values append
    v.observe_requirements(Requirements([
        Requirement("topology.kubernetes.io/zone", "In",
                    ["test-zone-d", "test-zone-a"])]))
    kz = v.key_idx["topology.kubernetes.io/zone"]
    assert v.values[kz] == ["test-zone-a", "test-zone-b", "test-zone-c",
                           "test-zone-d"]
