"""Sharded-mesh precompute parity + nodepool-limit regression tests."""

import jax
import numpy as np
import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.ops import binpack
from karpenter_tpu.parallel.mesh import make_solver_mesh, sharded_precompute
from karpenter_tpu.provisioning.grouping import group_pods
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import make_nodepool, make_pods, spread_zone


def _problem(n_groups=5, n_its=30):
    its = construct_instance_types()[:n_its]
    pool = make_nodepool(name="default")
    pods = []
    for d in range(n_groups):
        labels = {"app": f"d{d}"}
        spread = [spread_zone(key="app", value=f"d{d}")] if d % 2 else None
        pods += make_pods(7, cpu=f"{(d + 1) * 100}m", memory=f"{(d + 1) * 64}Mi",
                          labels=labels, spread=spread)
    ts = TensorScheduler([pool], {"default": its})
    groups, reason = group_pods(pods)
    assert groups is not None, reason
    problem, _, _ = ts.build_problem(groups)
    return problem


@pytest.mark.parametrize("n_devices", [1, 2, 8])
def test_sharded_precompute_matches_single_chip(n_devices):
    if len(jax.devices()) < n_devices:
        pytest.skip("not enough devices")
    problem = _problem()
    mesh = make_solver_mesh(n_devices)
    sharded = sharded_precompute(problem, mesh)
    ref = binpack.precompute(problem)
    np.testing.assert_array_equal(sharded.compat_tm, ref.compat_tm)
    np.testing.assert_array_equal(sharded.it_ok, ref.it_ok)
    np.testing.assert_array_equal(sharded.ppn, ref.ppn)
    np.testing.assert_array_equal(sharded.it_ok_z, ref.it_ok_z)
    np.testing.assert_array_equal(sharded.zone_adm, ref.zone_adm)


def test_sharded_precompute_nondivisible_padding():
    """G=5 groups, T=30 ITs on an 8-device mesh: both axes need padding."""
    if len(jax.devices()) < 8:
        pytest.skip("not enough devices")
    problem = _problem(n_groups=5, n_its=30)
    mesh = make_solver_mesh(8)
    assert mesh.shape["groups"] * mesh.shape["catalog"] == 8
    sharded = sharded_precompute(problem, mesh)
    ref = binpack.precompute(problem)
    np.testing.assert_array_equal(sharded.it_ok, ref.it_ok)


def test_many_zones_bitfield_packing():
    """Regression: >32 zones must pack losslessly (multi-word bitfield)."""
    zones = [f"zone-{i:02d}" for i in range(40)]
    its = [construct_instance_types(zones=zones)[i] for i in range(8)]
    pool = make_nodepool(name="default")
    # pin pods to the last zone (index >= 32 in the vocab)
    pods = make_pods(3, cpu="500m", node_selector={
        api_labels.LABEL_TOPOLOGY_ZONE: zones[-1]})
    ts = TensorScheduler([pool], {"default": its})
    results = ts.solve(pods)
    assert ts.fallback_reason == ""
    assert not results.pod_errors, results.pod_errors
    zone_req = results.new_nodeclaims[0].requirements.get(
        api_labels.LABEL_TOPOLOGY_ZONE)
    assert zone_req.has(zones[-1])


def test_price_order_name_tiebreak():
    """Equal-priced instance types order by name (types.go:128-130)."""
    its = construct_instance_types()[:8]
    pool = make_nodepool(name="default")
    pods = make_pods(2, cpu="500m")
    ts = TensorScheduler([pool], {"default": its})
    results = ts.solve(pods)
    assert ts.fallback_reason == ""
    opts = results.new_nodeclaims[0].instance_type_options
    keyed = [(min(o.price for o in it.offerings), it.name) for it in opts]
    assert keyed == sorted(keyed)


def test_disjoint_limit_resources_across_pools():
    """Regression: pool A limits only cpu, pool B limits only memory. A's
    absent memory limit must NOT be treated as 0 (nodepool.go Limits
    semantics: only named resources are limited)."""
    its = construct_instance_types()[:24]
    pool_a = make_nodepool(name="pool-a", limits={"cpu": "100"})
    pool_b = make_nodepool(name="pool-b", limits={"memory": "1000Gi"})
    pods = make_pods(10, cpu="500m", memory="256Mi")
    ts = TensorScheduler([pool_a, pool_b],
                         {"pool-a": its, "pool-b": its})
    results = ts.solve(pods)
    assert ts.fallback_reason == ""
    assert not results.pod_errors, results.pod_errors
    # pool-a is first in weight order and has plenty of cpu limit left
    pools = {nc.template.nodepool_name for nc in results.new_nodeclaims}
    assert "pool-a" in pools


def test_sharded_precompute_local_single_process():
    """Single-process meshes: local fetch degenerates to the full result
    with one span covering every group."""
    from karpenter_tpu.parallel.mesh import sharded_precompute_local
    problem = _problem()
    mesh = make_solver_mesh(8)
    tensors, spans = sharded_precompute_local(problem, mesh)
    ref = binpack.precompute(problem)
    G = ref.it_ok.shape[0]
    assert [(0, G)] == [(s, min(e, G)) for s, e in spans]
    np.testing.assert_array_equal(tensors.it_ok, ref.it_ok)
    np.testing.assert_array_equal(tensors.ppn, ref.ppn)


def test_multiprocess_sharded_solve_parity():
    """The multi-HOST path end-to-end: a 2-process jax.distributed fleet
    over 4 virtual CPU devices runs (1) the replicated-gather
    sharded_precompute, (2) the local-rows fetch, and (3) the full
    mesh-enabled solve, each asserted exactly equal to the single-device
    reference inside every worker (see
    __graft_entry__._dryrun_multiprocess_worker).

    ENV SKIP (tracking: rode along as tier-1's lone known failure since
    PR 4): this image's jaxlib cannot run multi-process collectives on the
    CPU backend — every worker dies with "Multiprocess computations aren't
    implemented on the CPU backend" before any assertion runs. That is an
    environment limitation, not a code regression, so it skips with the
    exact backend error preserved; on a jaxlib with CPU collectives (or
    real multi-host TPU), the test runs in full."""
    import __graft_entry__ as graft
    try:
        graft._dryrun_multiprocess(4, num_processes=2, timeout=600)
    except RuntimeError as e:
        if "Multiprocess computations aren't implemented on the CPU " \
                "backend" in str(e):
            pytest.skip("jaxlib on this image lacks multi-process CPU "
                        "collectives (XlaRuntimeError: 'Multiprocess "
                        "computations aren't implemented on the CPU "
                        "backend'); needs a CPU-collectives jaxlib or "
                        "real multi-host devices")
        raise


class TestMultihostHelpers:
    def test_init_multihost_single_host_noop(self, monkeypatch):
        from karpenter_tpu.parallel.mesh import init_multihost
        # isolate from ambient multi-host bootstrap env (TPU CI images)
        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)
        assert init_multihost() == 1  # no coordinator: plain single host

    def test_local_result_slice_covers_all_groups_single_process(self):
        from karpenter_tpu.parallel.mesh import (local_result_slice,
                                                 make_solver_mesh)
        mesh = make_solver_mesh(8)
        spans = local_result_slice(mesh, 101)
        # one process owns every shard: one span covering the whole range
        assert spans == [(0, 101)]

    def test_local_result_slice_partitions_across_processes(self):
        """A fake 2-process mesh with INTERLEAVED row ownership: each
        process's spans must be disjoint, non-overlapping, and jointly
        cover every group exactly once."""
        from types import SimpleNamespace
        import numpy as np
        from karpenter_tpu.parallel.mesh import (CATALOG_AXIS, GROUPS_AXIS,
                                                 local_result_slice)

        def dev(pidx):
            return SimpleNamespace(process_index=pidx)

        # rows 0,2 -> process 0; rows 1,3 -> process 1 (topology reorder)
        devices = np.array([[dev(0), dev(0)], [dev(1), dev(1)],
                            [dev(0), dev(0)], [dev(1), dev(1)]])
        mesh = SimpleNamespace(shape={GROUPS_AXIS: 4, CATALOG_AXIS: 2},
                               devices=devices)
        s0 = local_result_slice(mesh, 101, process_index=0)
        s1 = local_result_slice(mesh, 101, process_index=1)
        rows0 = {g for a, b in s0 for g in range(a, b)}
        rows1 = {g for a, b in s1 for g in range(a, b)}
        assert rows0 and rows1
        assert not (rows0 & rows1)          # disjoint: no double-packing
        assert rows0 | rows1 == set(range(101))  # complete coverage
        # interleaving produced more than one span for process 0
        assert len(s0) == 2
