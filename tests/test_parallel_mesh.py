"""Sharded-mesh precompute parity + nodepool-limit regression tests."""

import jax
import numpy as np
import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.ops import binpack
from karpenter_tpu.parallel.mesh import make_solver_mesh, sharded_precompute
from karpenter_tpu.provisioning.grouping import group_pods
from karpenter_tpu.provisioning.tensor_scheduler import TensorScheduler

from factories import make_nodepool, make_pods, spread_zone


def _problem(n_groups=5, n_its=30):
    its = construct_instance_types()[:n_its]
    pool = make_nodepool(name="default")
    pods = []
    for d in range(n_groups):
        labels = {"app": f"d{d}"}
        spread = [spread_zone(key="app", value=f"d{d}")] if d % 2 else None
        pods += make_pods(7, cpu=f"{(d + 1) * 100}m", memory=f"{(d + 1) * 64}Mi",
                          labels=labels, spread=spread)
    ts = TensorScheduler([pool], {"default": its})
    groups, reason = group_pods(pods)
    assert groups is not None, reason
    problem, _, _ = ts.build_problem(groups)
    return problem


@pytest.mark.parametrize("n_devices", [1, 2, 8])
def test_sharded_precompute_matches_single_chip(n_devices):
    if len(jax.devices()) < n_devices:
        pytest.skip("not enough devices")
    problem = _problem()
    mesh = make_solver_mesh(n_devices)
    sharded = sharded_precompute(problem, mesh)
    ref = binpack.precompute(problem)
    np.testing.assert_array_equal(sharded.compat_tm, ref.compat_tm)
    np.testing.assert_array_equal(sharded.it_ok, ref.it_ok)
    np.testing.assert_array_equal(sharded.ppn, ref.ppn)
    np.testing.assert_array_equal(sharded.it_ok_z, ref.it_ok_z)
    np.testing.assert_array_equal(sharded.zone_adm, ref.zone_adm)


def test_sharded_precompute_nondivisible_padding():
    """G=5 groups, T=30 ITs on an 8-device mesh: both axes need padding."""
    if len(jax.devices()) < 8:
        pytest.skip("not enough devices")
    problem = _problem(n_groups=5, n_its=30)
    mesh = make_solver_mesh(8)
    assert mesh.shape["pods_groups"] * mesh.shape["catalog"] == 8
    sharded = sharded_precompute(problem, mesh)
    ref = binpack.precompute(problem)
    np.testing.assert_array_equal(sharded.it_ok, ref.it_ok)


def test_many_zones_bitfield_packing():
    """Regression: >32 zones must pack losslessly (multi-word bitfield)."""
    zones = [f"zone-{i:02d}" for i in range(40)]
    its = [construct_instance_types(zones=zones)[i] for i in range(8)]
    pool = make_nodepool(name="default")
    # pin pods to the last zone (index >= 32 in the vocab)
    pods = make_pods(3, cpu="500m", node_selector={
        api_labels.LABEL_TOPOLOGY_ZONE: zones[-1]})
    ts = TensorScheduler([pool], {"default": its})
    results = ts.solve(pods)
    assert ts.fallback_reason == ""
    assert not results.pod_errors, results.pod_errors
    zone_req = results.new_nodeclaims[0].requirements.get(
        api_labels.LABEL_TOPOLOGY_ZONE)
    assert zone_req.has(zones[-1])


def test_price_order_name_tiebreak():
    """Equal-priced instance types order by name (types.go:128-130)."""
    its = construct_instance_types()[:8]
    pool = make_nodepool(name="default")
    pods = make_pods(2, cpu="500m")
    ts = TensorScheduler([pool], {"default": its})
    results = ts.solve(pods)
    assert ts.fallback_reason == ""
    opts = results.new_nodeclaims[0].instance_type_options
    keyed = [(min(o.price for o in it.offerings), it.name) for it in opts]
    assert keyed == sorted(keyed)


def test_disjoint_limit_resources_across_pools():
    """Regression: pool A limits only cpu, pool B limits only memory. A's
    absent memory limit must NOT be treated as 0 (nodepool.go Limits
    semantics: only named resources are limited)."""
    its = construct_instance_types()[:24]
    pool_a = make_nodepool(name="pool-a", limits={"cpu": "100"})
    pool_b = make_nodepool(name="pool-b", limits={"memory": "1000Gi"})
    pods = make_pods(10, cpu="500m", memory="256Mi")
    ts = TensorScheduler([pool_a, pool_b],
                         {"pool-a": its, "pool-b": its})
    results = ts.solve(pods)
    assert ts.fallback_reason == ""
    assert not results.pod_errors, results.pod_errors
    # pool-a is first in weight order and has plenty of cpu limit left
    pools = {nc.template.nodepool_name for nc in results.new_nodeclaims}
    assert "pool-a" in pools


def test_sharded_precompute_local_single_process():
    """Single-process meshes: local fetch degenerates to the full result
    with one span covering every group."""
    from karpenter_tpu.parallel.mesh import sharded_precompute_local
    problem = _problem()
    mesh = make_solver_mesh(8)
    tensors, spans = sharded_precompute_local(problem, mesh)
    ref = binpack.precompute(problem)
    G = ref.it_ok.shape[0]
    assert [(0, G)] == [(s, min(e, G)) for s, e in spans]
    np.testing.assert_array_equal(tensors.it_ok, ref.it_ok)
    np.testing.assert_array_equal(tensors.ppn, ref.ppn)


def test_multiprocess_sharded_solve_parity():
    """The multi-HOST path end-to-end: a 2-process jax.distributed fleet
    over 4 virtual CPU devices runs (1) the replicated-gather
    sharded_precompute, (2) the local-rows fetch, and (3) the full
    mesh-enabled solve, each asserted exactly equal to the single-device
    reference inside every worker (see
    __graft_entry__._dryrun_multiprocess_worker).

    ENV SKIP (tracking: rode along as tier-1's lone known failure since
    PR 4): this image's jaxlib cannot run multi-process collectives on the
    CPU backend — every worker dies with "Multiprocess computations aren't
    implemented on the CPU backend" before any assertion runs. That is an
    environment limitation, not a code regression, so it skips with the
    exact backend error preserved; on a jaxlib with CPU collectives (or
    real multi-host TPU), the test runs in full."""
    import __graft_entry__ as graft
    try:
        graft._dryrun_multiprocess(4, num_processes=2, timeout=600)
    except RuntimeError as e:
        if "Multiprocess computations aren't implemented on the CPU " \
                "backend" in str(e):
            pytest.skip("jaxlib on this image lacks multi-process CPU "
                        "collectives (XlaRuntimeError: 'Multiprocess "
                        "computations aren't implemented on the CPU "
                        "backend'); needs a CPU-collectives jaxlib or "
                        "real multi-host devices")
        raise


# ---------------------------------------------------------------------------
# shard-padding edge cases: full-solve decision parity vs the single-device
# oracle for shapes where the pow2 per-shard padding does real work (ISSUE 10)
# ---------------------------------------------------------------------------

def _mix_pods(n_deploys, pods_per=7):
    pods = []
    for d in range(n_deploys):
        labels = {"app": f"d{d}"}
        spread = [spread_zone(key="app", value=f"d{d}")] if d % 3 == 1 else None
        pods += make_pods(pods_per, cpu=f"{100 + (d % 7) * 150}m",
                          memory=f"{64 * (1 + d % 5)}Mi",
                          labels=labels, spread=spread)
    return pods


def _solve(pods, its, mesh=None, pack_shards=0, state_nodes=()):
    pool = make_nodepool(name="default")
    ts = TensorScheduler([pool], {"default": its},
                         state_nodes=list(state_nodes), mesh=mesh,
                         pack_shards=pack_shards)
    results = ts.solve(pods)
    assert ts.fallback_reason == "", ts.fallback_reason
    return results


def _claims_digest(results):
    return sorted(
        (nc.template.nodepool_name,
         tuple(sorted(nc.requirements.get(
             api_labels.LABEL_TOPOLOGY_ZONE).values)),
         tuple(it.name for it in nc.instance_type_options),
         len(nc.pods))
        for nc in results.new_nodeclaims)


@pytest.mark.parametrize("n_deploys,n_its", [
    (13, 37),   # neither axis divides the (4, 2) mesh grid
    (2, 30),    # fewer groups than pods_groups shards: all-padding shards
    (1, 24),    # single group on an 8-device mesh: 3 of 4 shards padding
])
def test_mesh_solve_exact_parity_padding_edges(n_deploys, n_its):
    """Directed shard-padding vectors: group/catalog counts that are not
    multiples of the mesh dims, shards made entirely of padding rows, and a
    single-group problem on the full 8-device mesh — each must produce
    decisions EXACTLY equal to the single-device oracle (padding rows have
    empty masks / unavailable offerings, so they can never win a cohort)."""
    if len(jax.devices()) < 8:
        pytest.skip("not enough devices")
    its = construct_instance_types()[:n_its]
    pods = _mix_pods(n_deploys)
    mesh = make_solver_mesh(8)
    r_mesh = _solve(pods, its, mesh=mesh)
    r_single = _solve(pods, its)
    assert _claims_digest(r_mesh) == _claims_digest(r_single)
    assert r_mesh.pod_errors == r_single.pod_errors


def test_all_padding_shard_precompute_rows_are_inert():
    """G=2 on the 8-device (4x2) grid pads the group axis to 32 rows: shards
    1-3 are 100% padding. The padded rows must come back structurally inert
    (no admissible zone, no compatible template) after un-padding is applied
    — this pins pad_problem's empty-mask/false-available invariants."""
    if len(jax.devices()) < 8:
        pytest.skip("not enough devices")
    from karpenter_tpu.parallel.mesh import (PODS_GROUPS_AXIS, pad_problem,
                                             padded_sizes)
    problem = _problem(n_groups=2, n_its=30)
    mesh = make_solver_mesh(8)
    g_mult = mesh.shape[PODS_GROUPS_AXIS]
    Gp, _ = padded_sizes(2, 30, g_mult, mesh.shape["catalog"])
    assert Gp >= 4 * g_mult  # at least one full shard of padding exists
    padded, G, _ = pad_problem(problem, g_mult, mesh.shape["catalog"])
    assert G == 2
    ref = binpack.precompute(padded)
    # empty-mask padding rows are compatible-with-everything in compat_tm
    # (no constraints); what keeps them out of the pack is that no zone is
    # ever admissible for them — plus _unpad_tensors slicing them off
    assert not ref.zone_adm[G:].any(), "padding rows admitted a zone"
    # and the real rows still round-trip exactly through the mesh
    sharded = sharded_precompute(problem, mesh)
    single = binpack.precompute(problem)
    np.testing.assert_array_equal(sharded.it_ok, single.it_ok)
    np.testing.assert_array_equal(sharded.zone_adm, single.zone_adm)


def test_recreated_mesh_reuses_compiled_executable():
    """A NEW Mesh object over the same devices + grid must hit the
    persistent executable cache (keyed on device identity + static shapes,
    not the Mesh object) — the PR-3 compile-cache fix applied to the
    sharded path."""
    if len(jax.devices()) < 8:
        pytest.skip("not enough devices")
    problem = _problem()
    sharded_precompute(problem, make_solver_mesh(8))  # warm/compile
    keys_before = set(binpack._EXEC_CACHE.keys())
    fresh_problem = _problem()
    result = sharded_precompute(fresh_problem, make_solver_mesh(8))
    assert set(binpack._EXEC_CACHE.keys()) == keys_before, \
        "recreated mesh recompiled: executable cache grew"
    np.testing.assert_array_equal(result.it_ok,
                                  binpack.precompute(fresh_problem).it_ok)


# ---------------------------------------------------------------------------
# pods/groups-sharded hierarchical pack (DEVIATIONS 22)
# ---------------------------------------------------------------------------

def _pack_span(results_ignored=None):
    from karpenter_tpu.obs.tracer import TRACER
    trace = TRACER.last()
    spans = [s for s in trace.spans if s.name == "pack"]
    assert len(spans) == 1, [s.name for s in trace.spans]
    return spans[0]


def test_sharded_pack_contract_vs_sequential_oracle():
    """The DEVIATIONS 22 envelope at a directed group-heavy shape: pod
    errors EXACT (including a structurally unschedulable group), placed
    pods exact, node count within the reconcile envelope — and the pack
    span proves the hierarchical path actually engaged."""
    its = construct_instance_types()[:48]
    pods = _mix_pods(40, pods_per=25)
    # one group no instance type can hold: its errors must survive sharding
    pods += make_pods(3, cpu="1000", labels={"app": "impossible"})
    r_seq = _solve(pods, its)
    r_sh = _solve(pods, its, pack_shards=4)
    assert _pack_span().attrs.get("sharded") == 4, \
        "pack_shardable gate unexpectedly rejected a shardable problem"
    assert r_sh.pod_errors == r_seq.pod_errors
    assert r_seq.pod_errors, "directed unschedulable group lost its errors"
    placed_seq = sum(len(nc.pods) for nc in r_seq.new_nodeclaims)
    placed_sh = sum(len(nc.pods) for nc in r_sh.new_nodeclaims)
    assert placed_sh == placed_seq
    n_seq = len(r_seq.new_nodeclaims)
    n_sh = len(r_sh.new_nodeclaims)
    assert n_sh <= int(np.ceil(n_seq * 1.05)) + 4, (n_sh, n_seq)


def test_sharded_pack_single_shard_and_single_group_degenerate():
    """pack_shards=1 and a one-group problem both degenerate to the exact
    sequential pack (byte-identical claims, not just envelope-close)."""
    its = construct_instance_types()[:24]
    for pods, shards in ((_mix_pods(6), 1), (_mix_pods(1, pods_per=40), 4)):
        r_seq = _solve(pods, its)
        r_sh = _solve(pods, its, pack_shards=shards)
        assert _claims_digest(r_sh) == _claims_digest(r_seq)
        assert r_sh.pod_errors == r_seq.pod_errors


def test_sharded_pack_gate_existing_nodes_forces_sequential():
    """Existing nodes couple groups across shards (shared capacity
    draw-down), so pack_shardable must gate the hierarchical pack off: the
    solve runs the sequential pack (no 'sharded' span attr) and decisions
    are byte-identical to a pack_shards=0 run."""
    from factories import make_state_node
    its = construct_instance_types()[:24]
    pods = _mix_pods(8, pods_per=10)
    nodes = [make_state_node(f"existing-{i}", cpu="8", memory="32Gi")
             for i in range(3)]
    r_sh = _solve(pods, its, pack_shards=4, state_nodes=nodes)
    assert "sharded" not in _pack_span().attrs, \
        "hierarchical pack engaged despite existing nodes"
    r_seq = _solve(pods, its, state_nodes=nodes)
    assert _claims_digest(r_sh) == _claims_digest(r_seq)
    assert r_sh.pod_errors == r_seq.pod_errors


def test_pack_shardable_gate_direct():
    from karpenter_tpu.parallel.mesh import pack_shardable
    p = _problem(n_groups=3, n_its=12)
    assert pack_shardable(p, [None], None, None)
    assert not pack_shardable(p, [{"cpu": 100}], None, None)  # pool limit
    assert not pack_shardable(p, [None], [set(), {80}, set()], None)  # ports
    assert not pack_shardable(p, [None], None, {0: 2})  # volume budgets


def test_multiprocess_sharded_solve_parity_4proc():
    """Fleet proof past 2 processes (ISSUE 10 satellite): a 4-process
    jax.distributed fleet over 8 virtual CPU devices, 2 local devices per
    process, running the same worker assertions as the 2-process smoke.

    ENV SKIP: same jaxlib limitation as
    test_multiprocess_sharded_solve_parity — this image's jaxlib cannot run
    multi-process collectives on the CPU backend; the skip preserves the
    backend error so a capable jaxlib runs the test in full."""
    import __graft_entry__ as graft
    try:
        graft._dryrun_multiprocess(8, num_processes=4, timeout=600)
    except RuntimeError as e:
        if "Multiprocess computations aren't implemented on the CPU " \
                "backend" in str(e):
            pytest.skip("jaxlib on this image lacks multi-process CPU "
                        "collectives (XlaRuntimeError: 'Multiprocess "
                        "computations aren't implemented on the CPU "
                        "backend'); needs a CPU-collectives jaxlib or "
                        "real multi-host devices")
        raise


class TestMultihostHelpers:
    def test_init_multihost_single_host_noop(self, monkeypatch):
        from karpenter_tpu.parallel.mesh import init_multihost
        # isolate from ambient multi-host bootstrap env (TPU CI images)
        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                    "JAX_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)
        assert init_multihost() == 1  # no coordinator: plain single host

    def test_local_result_slice_covers_all_groups_single_process(self):
        from karpenter_tpu.parallel.mesh import (local_result_slice,
                                                 make_solver_mesh)
        mesh = make_solver_mesh(8)
        spans = local_result_slice(mesh, 101)
        # one process owns every shard: one span covering the whole range
        assert spans == [(0, 101)]

    def test_local_result_slice_partitions_across_processes(self):
        """A fake 2-process mesh with INTERLEAVED row ownership: each
        process's spans must be disjoint, non-overlapping, and jointly
        cover every group exactly once."""
        from types import SimpleNamespace
        import numpy as np
        from karpenter_tpu.parallel.mesh import (CATALOG_AXIS, GROUPS_AXIS,
                                                 local_result_slice)

        def dev(pidx):
            return SimpleNamespace(process_index=pidx)

        # rows 0,2 -> process 0; rows 1,3 -> process 1 (topology reorder)
        devices = np.array([[dev(0), dev(0)], [dev(1), dev(1)],
                            [dev(0), dev(0)], [dev(1), dev(1)]])
        mesh = SimpleNamespace(shape={GROUPS_AXIS: 4, CATALOG_AXIS: 2},
                               devices=devices)
        s0 = local_result_slice(mesh, 101, process_index=0)
        s1 = local_result_slice(mesh, 101, process_index=1)
        rows0 = {g for a, b in s0 for g in range(a, b)}
        rows1 = {g for a, b in s1 for g in range(a, b)}
        assert rows0 and rows1
        assert not (rows0 & rows1)          # disjoint: no double-packing
        assert rows0 | rows1 == set(range(101))  # complete coverage
        # interleaving produced more than one span for process 0
        assert len(s0) == 2


# ---------------------------------------------------------------------------
# group-size-aware donor-row headroom (ISSUE 14 satellite, ROADMAP item 3)
# ---------------------------------------------------------------------------

def test_donor_headroom_policy_properties():
    """The policy function: deterministic, bounded, monotone in fragment
    size, and degenerate cases keep the old fixed bar."""
    from karpenter_tpu.ops.binpack import (DONOR_HEADROOM_DENSE,
                                           DONOR_HEADROOM_MEDIUM,
                                           DONOR_HEADROOM_SMALL,
                                           donor_headroom)
    assert donor_headroom(1000, 1) == DONOR_HEADROOM_DENSE
    assert donor_headroom(0, 4) == DONOR_HEADROOM_DENSE
    assert donor_headroom(8, 4) == DONOR_HEADROOM_SMALL       # frag 2
    assert donor_headroom(64 * 4, 4) == DONOR_HEADROOM_MEDIUM  # frag 64
    assert donor_headroom(1000 * 4, 4) == DONOR_HEADROOM_DENSE
    # monotone: a larger fragment never gets a LOWER bar
    prev = 0.0
    for frag in (1, 4, 16, 17, 64, 128, 129, 10000):
        bar = donor_headroom(frag * 4, 4)
        assert bar >= prev, (frag, bar, prev)
        prev = bar
    assert DONOR_HEADROOM_SMALL < DONOR_HEADROOM_MEDIUM < DONOR_HEADROOM_DENSE


def _reconcile_span():
    from karpenter_tpu.obs.tracer import TRACER
    trace = TRACER.last()
    spans = [s for s in trace.spans if s.name == "pack.reconcile"]
    assert len(spans) == 1, [s.name for s in trace.spans]
    return spans[0]


def test_group_size_aware_donor_bar_directed_vector(monkeypatch):
    """Directed vector pinning the policy swap: small groups whose
    per-shard tail rows sit at ~13% headroom on the only type that fits
    them. Under the retired fixed 0.25 bar those rows never donate (the
    13% headroom clears no 25% need) and fragments stay stranded one node
    per shard; under the group-size-aware bar (fragment <= 16 pods ->
    0.05) they donate and the cross-shard reconcile coalesces them."""
    from karpenter_tpu.ops import binpack

    # ONE instance type, so the tail-row shape is fully deterministic:
    # ppn = 8, each 15-pod group leaves one 7/8-full tail node whose
    # headroom (~18%) clears the small-group 0.05 bar but not the dense
    # 0.25 bar
    all_its = construct_instance_types()
    big = max((it for it in all_its if it.capacity.get("cpu", 0) <= 4000),
              key=lambda it: it.allocatable().get("cpu", 0))
    its = [big]
    alloc = big.allocatable()["cpu"]
    pod_cpu = int(alloc * 0.117)
    assert 7 * pod_cpu * 1.05 <= alloc < 7 * pod_cpu * 1.25
    pods = []
    for d in range(8):
        pods += make_pods(15, cpu=f"{pod_cpu}m", memory="64Mi",
                          labels={"app": f"donor{d}"})

    r_seq = _solve(pods, its)
    r_new = _solve(pods, its, pack_shards=4)
    held_new = _reconcile_span().attrs.get("donor_rows", 0)

    # force the retired fixed bar and re-pack the same problem
    monkeypatch.setattr(
        binpack, "donor_headroom",
        lambda count, shards: binpack.DONOR_HEADROOM_DENSE)
    r_old = _solve(pods, its, pack_shards=4)
    held_old = _reconcile_span().attrs.get("donor_rows", 0)

    assert held_new > held_old, (held_new, held_old)
    # decision contract unchanged under the new policy (DEVIATIONS 22)
    assert r_new.pod_errors == r_seq.pod_errors == {}
    placed = sum(len(nc.pods) for nc in r_seq.new_nodeclaims)
    assert sum(len(nc.pods) for nc in r_new.new_nodeclaims) == placed
    assert sum(len(nc.pods) for nc in r_old.new_nodeclaims) == placed
    # coalescing the donated tails never costs nodes vs the frozen bar
    assert len(r_new.new_nodeclaims) <= len(r_old.new_nodeclaims)


# ---------------------------------------------------------------------------
# sharded ProblemState (ISSUE 18): device-identity exist keying + the
# cross-shard reconcile memo
# ---------------------------------------------------------------------------

def test_exist_upload_reuse_keyed_on_device_identity(monkeypatch):
    """The cached exist-side upload must key on (content token, PLACEMENT
    identity), not the content token alone: a default-device change (or a
    mesh<->single-device flip) between two solves of the same ProblemState
    reuses the same exist_token but must never be served the other
    placement's arrays."""
    import dataclasses

    from factories import make_state_node

    its = construct_instance_types()[:24]
    pool = make_nodepool(name="default")
    nodes = [make_state_node(f"exist-{i}", cpu="16", memory="64Gi")
             for i in range(3)]
    ts = TensorScheduler([pool], {"default": its}, state_nodes=nodes)
    groups, reason = group_pods(_mix_pods(4))
    assert groups is not None, reason
    problem, _, _ = ts.build_problem(groups)
    p = dataclasses.replace(problem, exist_token=("content", 1),
                            device_cache={})

    args1, _ = binpack.device_args(p)
    args2, _ = binpack.device_args(p)
    # same content + same device: the pair is served from the slot
    assert args2[-3] is args1[-3] and args2[-2] is args1[-2]

    # flip the placement identity under an UNCHANGED content token: the
    # slot must re-place, not serve the stale pair
    monkeypatch.setattr(binpack.ArgPlacer, "device_token",
                        lambda self: ("dev", "elsewhere", 999))
    args3, _ = binpack.device_args(p)
    assert args3[-3] is not args1[-3], \
        "exist upload served across a device-identity flip"
    monkeypatch.undo()
    # flipping BACK is a miss again (the slot now holds the other identity)
    args4, _ = binpack.device_args(p)
    assert args4[-3] is not args3[-3]


def test_mesh_single_device_flip_shared_problem_state_parity():
    """One persistent ProblemState driven through a mesh solve, then a
    single-device solve, then the mesh again (same cluster, same batch):
    every hop must produce decisions identical to a state-free cold solve —
    the exist/catalog device caches are namespaced per placement, so a flip
    re-places instead of feeding one path the other's arrays."""
    from factories import make_state_node
    from karpenter_tpu.provisioning.problem_state import ProblemState

    if len(jax.devices()) < 8:
        pytest.skip("not enough devices")
    its = construct_instance_types()[:24]
    pool = make_nodepool(name="default")
    nodes = [make_state_node(f"exist-{i}", cpu="16", memory="64Gi")
             for i in range(3)]
    pods = _mix_pods(6)

    def solve(mesh, state):
        ts = TensorScheduler([pool], {"default": its}, state_nodes=nodes,
                             mesh=mesh, problem_state=state)
        r = ts.solve(pods)
        assert ts.fallback_reason == "", ts.fallback_reason
        return r

    oracle = _claims_digest(solve(None, None))
    ps = ProblemState()
    mesh = make_solver_mesh(8)
    for hop, m in (("mesh", mesh), ("single", None), ("mesh-again", mesh)):
        r = solve(m, ps)
        assert _claims_digest(r) == oracle, \
            f"{hop} hop diverged after a placement flip"


def test_sharded_pack_reconcile_memo_reused_on_unchanged_warm():
    """The cross-shard reconcile fold is memoized against the warm token +
    per-shard group content: a second solve of the identical batch through
    the same ProblemState must serve the merged CohortSet from the memo
    (pack.reconcile span attr merged=memo) with decisions unchanged."""
    from karpenter_tpu.provisioning.problem_state import ProblemState

    its = construct_instance_types()[:24]
    pool = make_nodepool(name="default")
    pods = _mix_pods(12, pods_per=9)
    ps = ProblemState()

    def solve(state):
        ts = TensorScheduler([pool], {"default": its}, mesh=None,
                             problem_state=state, pack_shards=4)
        r = ts.solve(pods)
        assert ts.fallback_reason == "", ts.fallback_reason
        return r

    oracle = solve(None)
    assert _pack_span().attrs.get("sharded") == 4

    r1 = solve(ps)
    assert _reconcile_span().attrs.get("merged") == "fold"
    r2 = solve(ps)
    span2 = _reconcile_span()
    assert span2.attrs.get("merged") == "memo", \
        "unchanged warm solve re-ran the reconcile fold"
    for r in (r1, r2):
        assert _claims_digest(r) == _claims_digest(oracle)
        assert r.pod_errors == oracle.pod_errors
    # the memoized merge holds the same donor rows the fold produced
    assert span2.attrs.get("donor_rows") is not None
