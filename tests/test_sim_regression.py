"""The sim digest CI regression gate (ISSUE 12 satellite).

The fleet simulator's deterministic ledger digests make byte-exact
perf-BEHAVIOR pinning possible where wall-clock asserts flake (the 2-core
driver box runs cross-process captures 30-50% slower than the r05
captures, but it cannot slow a hash down). tools/sim_regression.py replays
the clipped mixed-day library scenario and compares the ledger digest and
the SLO-report key shape against tests/goldens/sim-regression.json; this
tier-1 wrapper keeps the gate green in every run and pins the gate's OWN
failure modes (a digest mismatch must fail loudly and name the
regeneration command, not silently pass).
"""

from __future__ import annotations

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import sim_regression  # noqa: E402

pytestmark = pytest.mark.sim


@pytest.fixture(scope="module")
def pin():
    # multi-scenario pins: the clipped mixed-day replay, the
    # disruption-wave replay (drift/expiration waves + weighted pools,
    # the streaming disruption engine's decision pin — ISSUE 14), and
    # the clipped service-fleet replay (replica kill + rolling restart,
    # replica-count-invariant digest — ISSUE 17)
    return sim_regression.current_pins()


class TestSimRegressionGate:
    def test_golden_exists_and_matches(self, pin):
        """THE gate: the clipped replay's ledger digest and report shape
        match the pinned golden. If this fails after an intentional
        behavior change, refresh the pin:

            python tools/sim_regression.py --update
        """
        assert os.path.exists(sim_regression.GOLDEN_PATH), (
            "no golden pin; generate one with "
            "`python tools/sim_regression.py --update`")
        with open(sim_regression.GOLDEN_PATH) as f:
            golden = json.load(f)
        problems = sim_regression.compare(pin, golden)
        assert not problems, (
            "sim behavior diverged from the pinned golden:\n"
            + "\n".join(problems)
            + "\nintentional? refresh: python tools/sim_regression.py "
              "--update")

    def test_all_library_scenarios_are_pinned(self, pin):
        """The golden covers every library pin: mixed-day, the ISSUE-14
        disruption-wave (drift + expiration waves through the streaming
        engine), the ISSUE-17 service-fleet roll (replicated sidecar
        kill + rolling restart — the digest must not depend on the
        replica count, so the fleet run is part of the byte-exact
        contract), and the ISSUE-20 state-chaos run (corrupt_state +
        kill_device windows — unledgered, so the digest must equal a
        fault-free run's)."""
        names = {p["scenario"] for p in pin["pins"]}
        assert names == {"mixed-day.yaml", "disruption-wave.yaml",
                         "service-fleet.yaml", "state-chaos.yaml"}

    def test_report_shape_covers_new_sections(self, pin):
        """The ISSUE-12 report sections are part of the pinned shape: the
        fallback ledger and the per-subsystem attribution can't silently
        vanish from the report."""
        for entry in pin["pins"]:
            paths = set(entry["report_shape"])
            assert "fallbacks.classes:dict" in paths
            assert "fallbacks.host_seconds:number" in paths
            assert "fallbacks.host_cost_ratio:number" in paths
            assert "attribution:dict" in paths
            assert "ledger_digest:str" in paths

    def test_mismatch_fails_loudly_with_regen_command(self, pin, tmp_path,
                                                      capsys):
        """A digest regression exits 1 and the message names the exact
        regeneration command — the failing-loudly contract."""
        first = dict(pin["pins"][0])
        first["ledger_digest"] = "0" * 64
        first["report_shape"] = [p for p in first["report_shape"]
                                 if not p.startswith("fallbacks.")]
        bad = {"pins": [first] + [dict(p) for p in pin["pins"][1:]]}
        golden = tmp_path / "golden.json"
        golden.write_text(json.dumps(bad))
        rc = sim_regression.main(["--golden", str(golden)], pin=pin)
        err = capsys.readouterr().err
        assert rc == 1
        assert "ledger digest changed" in err
        assert "report keys NEW vs golden" in err
        assert "python tools/sim_regression.py --update" in err

    def test_missing_scenario_pin_fails_loudly(self, pin, tmp_path, capsys):
        """A pinned scenario silently dropped from the golden (or a new
        scenario with no pin) is its own loud failure."""
        bad = {"pins": [dict(pin["pins"][0])]}
        golden = tmp_path / "golden.json"
        golden.write_text(json.dumps(bad))
        rc = sim_regression.main(["--golden", str(golden)], pin=pin)
        err = capsys.readouterr().err
        assert rc == 1
        assert "has no golden pin" in err

    def test_legacy_single_pin_golden_still_compares(self, pin):
        """The pre-v2 single-dict golden format compares without
        crashing (it reads as one scenario's pin)."""
        legacy = dict(pin["pins"][0])
        problems = sim_regression.compare(pin["pins"][0], legacy)
        assert problems == []

    def test_missing_golden_is_a_distinct_failure(self, pin, tmp_path,
                                                  capsys):
        rc = sim_regression.main(["--golden", str(tmp_path / "nope.json")],
                                 pin=pin)
        assert rc == 2
        assert "--update" in capsys.readouterr().err

    def test_shape_fingerprint_is_value_free(self):
        """report_shape is structural only: two reports with different
        values but the same keys fingerprint identically, and opaque
        data-keyed sections compare as one leaf."""
        a = {"x": 1.5, "churn": {"n": 3}, "events_applied": {"deploy": 2},
             "name": "a", "flag": True, "items": [1, 2]}
        b = {"x": 99.0, "churn": {"n": 7}, "events_applied": {"pdb": 9},
             "name": "b", "flag": False, "items": []}
        assert sim_regression.report_shape(a) == \
            sim_regression.report_shape(b)
        assert "events_applied:dict" in sim_regression.report_shape(a)
