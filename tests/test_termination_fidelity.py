"""Termination fidelity: volume-detach wait, PDB-429 eviction backoff, the
unbind-rebind race, orchestration-queue per-item backoff, node-deletion
provisioning trigger, store UID index (VERDICT r2 #6 and #8).

Reference shapes: node/termination/controller.go:141-150,190-240,
terminator/eviction.go:49-50,94-141, orchestration/queue.go:51-52,128-132,
provisioning/controller.go:92-113."""

import pytest

from karpenter_tpu.api import labels as api_labels
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import Node, ObjectMeta, Pod, PVCRef
from karpenter_tpu.api.policy import PDBSpec, PodDisruptionBudget
from karpenter_tpu.api.objects import LabelSelector
from karpenter_tpu.api.storage import (CSIVolumeSource, PersistentVolume,
                                       PersistentVolumeClaim,
                                       PersistentVolumeSpec, PVCSpec,
                                       VolumeAttachment, VolumeAttachmentSpec)
from karpenter_tpu.disruption.controller import (OrchestrationQueue,
                                                 QueuedCommand)
from karpenter_tpu.disruption.types import Command
from karpenter_tpu.kube.store import Store
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.utils.clock import FakeClock

from factories import make_nodepool, make_pod, make_pods
from test_operator import settle


@pytest.fixture
def op():
    return Operator(clock=FakeClock())


def _provision_one(op, **pod_kw):
    op.store.create(make_nodepool(name="default"))
    pod = make_pod(cpu="500m", **pod_kw)
    op.store.create(pod)
    settle(op)
    node = op.store.list(Node)[0]
    assert op.store.get(Pod, pod.name, pod.namespace).spec.node_name == node.name
    return pod, node


def _bind_volume(op, pod, pv_name="pv-1", claim="pvc-1", node=None):
    op.store.create(PersistentVolume(
        metadata=ObjectMeta(name=pv_name, namespace=""),
        spec=PersistentVolumeSpec(csi=CSIVolumeSource(driver="ebs.csi"))))
    op.store.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name=claim, namespace=pod.namespace),
        spec=PVCSpec(volume_name=pv_name)))
    pod.spec.volumes.append(PVCRef(claim_name=claim))
    op.store.update(pod)
    va = VolumeAttachment(
        metadata=ObjectMeta(name=f"va-{pv_name}", namespace=""),
        spec=VolumeAttachmentSpec(node_name=node.name,
                                  persistent_volume_name=pv_name))
    op.store.create(va)
    return va


class TestVolumeDetachWait:
    def test_detach_blocks_finalizer_until_va_deleted(self, op):
        pod, node = _provision_one(op)
        va = _bind_volume(op, pod, node=node)
        op.store.delete(node)
        settle(op)
        # pods drained, but the attachment pins the node
        live = op.store.get(Node, node.name)
        assert live is not None
        assert live.metadata.deletion_timestamp is not None
        # the CSI AD controller detaches (the test plays its role)
        op.store.delete(va)
        settle(op)
        assert op.store.get(Node, node.name) is None

    def test_undrainable_pod_volume_does_not_block(self, op):
        # a do-not-disrupt pod never drains, so its volume never detaches —
        # it must not wedge termination (controller.go filterVolumeAttachments)
        pod, node = _provision_one(op)
        pod.metadata.annotations[api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = \
            "true"
        _bind_volume(op, pod, node=node)
        # stamp a TGP so the do-not-disrupt pod is force-expired eventually
        op.store.delete(node)
        settle(op)
        live = op.store.get(Node, node.name)
        # the only VA belongs to the undrainable pod: it is filtered out, so
        # once the pod itself is gone/expired the node can finalize; while
        # the pod holds on (no TGP), drain keeps the node alive
        assert live is not None  # pod still bound (do-not-disrupt, no TGP)

    def test_tgp_expiry_skips_volume_wait(self, op):
        pool = make_nodepool(name="default")
        pool.spec.template.spec.termination_grace_period = 60.0
        op.store.create(pool)
        pod = make_pod(cpu="500m")
        op.store.create(pod)
        settle(op)
        node = op.store.list(Node)[0]
        _bind_volume(op, pod, node=node)
        op.store.delete(node)
        op.step()
        assert op.store.get(Node, node.name) is not None
        op.clock.step(61)  # past the termination deadline
        settle(op)
        # volume still attached, but the deadline waives the wait
        assert op.store.get(Node, node.name) is None


class TestEvictionBackoff:
    def test_pdb_blocked_pod_backs_off(self, op):
        pod, node = _provision_one(op, labels={"app": "guarded"})
        op.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace=pod.namespace),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "guarded"}),
                         max_unavailable="0")))
        term = next(c for c in op.manager.controllers
                    if c.name == "node.termination")
        op.store.delete(node)
        op.step()
        key = (pod.namespace, pod.name, pod.uid)
        assert term._backoff.failures(key) == 1
        # re-reconciles inside the backoff window do not hammer the PDB
        live_node = op.store.get(Node, node.name)
        term.reconcile(live_node)
        term.reconcile(live_node)
        assert term._backoff.failures(key) == 1
        # pod still bound: eviction is blocked, node still draining
        assert op.store.get(Pod, pod.name, pod.namespace).spec.node_name
        # past the backoff delay the eviction is attempted again
        op.clock.step(0.2)
        term.reconcile(live_node)
        assert term._backoff.failures(key) == 2

    def test_single_pass_honors_pdb_budget(self, op):
        """Evictions granted in one drain pass must count against the PDB
        headroom: 2 same-PDB pods with maxUnavailable=1 lose exactly one pod
        per pass, not both (the API server reflects each eviction in PDB
        status before the next; the snapshot must too)."""
        _, node = _provision_one(op)
        pods = make_pods(2, cpu="100m", labels={"app": "ds"})
        for p in pods:
            # non-reschedulable (daemonset) pods are hard-deleted on evict,
            # the path where the stale snapshot can't see the loss
            p.is_daemonset_pod = True
            op.store.create(p)
        for p in pods:
            live = op.store.get(Pod, p.name, p.namespace)
            live.spec.node_name = node.name
            op.store.update(live)
        op.store.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "ds"}),
                         max_unavailable="1")))
        term = next(c for c in op.manager.controllers
                    if c.name == "node.termination")
        op.store.delete(node)
        term.reconcile(op.store.get(Node, node.name))  # pass 1: regular pod
        term.reconcile(op.store.get(Node, node.name))  # pass 2: daemon group
        remaining = [p for p in op.store.list(Pod)
                     if p.spec.node_name == node.name and p.is_daemonset_pod]
        assert len(remaining) == 1

    def test_pdb_release_lets_drain_finish(self, op):
        pod, node = _provision_one(op, labels={"app": "guarded"})
        pdb = PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace=pod.namespace),
            spec=PDBSpec(selector=LabelSelector(match_labels={"app": "guarded"}),
                         max_unavailable="0"))
        op.store.create(pdb)
        op.store.delete(node)
        settle(op)
        assert op.store.get(Node, node.name) is not None
        op.store.delete(pdb)
        settle(op)
        assert op.store.get(Node, node.name) is None


class TestUnbindRebindRace:
    def test_evicted_pod_lands_on_new_node(self, op):
        """An evicted (unbound) pod must re-provision onto replacement
        capacity, never back onto the still-terminating node."""
        pod, node = _provision_one(op)
        op.store.delete(node)
        settle(op)
        live = op.store.get(Pod, pod.name, pod.namespace)
        assert live.spec.node_name            # rebound...
        assert live.spec.node_name != node.name  # ...on a NEW node
        assert op.store.get(Node, node.name) is None


class TestOrchestrationQueueBackoff:
    def test_waiting_command_delays_double(self):
        clock = FakeClock()
        store = Store(clock)
        cluster = Cluster(store, clock)
        q = OrchestrationQueue(store, cluster, clock)
        # replacement exists but never initializes -> the command waits
        nc = NodeClaim(metadata=ObjectMeta(name="repl-1", namespace=""))
        store.create(nc)
        q.add(QueuedCommand(command=Command(), replacement_names=["repl-1"],
                            enqueued_at=clock.now()))
        delays = []
        for _ in range(5):
            r = q.reconcile()
            delays.append(r.requeue_after)
            clock.step(r.requeue_after + 0.001)
        assert delays == [1.0, 2.0, 4.0, 8.0, 10.0]  # 1s base, 10s cap

    def test_success_forgets_backoff(self):
        clock = FakeClock()
        store = Store(clock)
        cluster = Cluster(store, clock)
        q = OrchestrationQueue(store, cluster, clock)
        nc = NodeClaim(metadata=ObjectMeta(name="repl-2", namespace=""))
        store.create(nc)
        qc = QueuedCommand(command=Command(), replacement_names=["repl-2"],
                           enqueued_at=clock.now())
        q.add(qc)
        q.reconcile()
        clock.step(2)
        from karpenter_tpu.api.nodeclaim import (COND_INITIALIZED,
                                                 COND_LAUNCHED,
                                                 COND_REGISTERED)
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED):
            nc.conditions.set_true(cond)
        assert q.reconcile() is None
        assert not q.items
        assert q._backoff.failures(qc.key) == 0


class TestNodeDeletionTrigger:
    def test_deleting_node_triggers_provisioner(self, op):
        pod, node = _provision_one(op)
        op.provisioner.batcher.reset()
        assert op.provisioner.batcher._first is None
        op.store.delete(node)
        op.manager.drain()
        assert op.provisioner.batcher._first is not None


class TestStoreUidIndex:
    def test_get_by_uid(self):
        store = Store(FakeClock())
        pod = make_pod(cpu="100m")
        store.create(pod)
        assert store.get_by_uid(Pod, pod.uid) is pod
        store.delete(pod)
        assert store.get_by_uid(Pod, pod.uid) is None

    def test_uid_removed_after_finalizer_release(self):
        store = Store(FakeClock())
        node = Node(metadata=ObjectMeta(name="n1", namespace=""))
        node.metadata.finalizers.append("test/finalizer")
        store.create(node)
        uid = node.metadata.uid
        store.delete(node)
        assert store.get_by_uid(Node, uid) is node  # still finalizing
        store.remove_finalizer(node, "test/finalizer")
        assert store.get_by_uid(Node, uid) is None


# ---------------------------------------------------------------------------
# Widened port of node/termination/suite_test.go:106-877
# ---------------------------------------------------------------------------

from karpenter_tpu.api.objects import OwnerReference, Toleration
from karpenter_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT


def _terminate(op, node, rounds=6):
    op.store.delete(node)
    for _ in range(rounds):
        settle(op)
        op.clock.step(2)


class TestDrainSemantics:
    def test_pod_tolerating_disrupted_taint_not_evicted(self, op):
        """suite_test.go:193-254: a pod that tolerates the disruption taint
        opted into dying with the node; it is not evicted and does not hold
        the drain open."""
        pod, node = _provision_one(op)
        rider = make_pod(cpu="100m", name="rider", tolerations=[
            Toleration(key=DISRUPTED_NO_SCHEDULE_TAINT.key,
                       operator="Exists")])
        rider.spec.node_name = node.name
        op.store.create(rider)
        settle(op)
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None  # drain completed
        # the workload pod was evicted (unbound), the rider never was: it
        # went down with the node (its record remains, bound to the gone
        # node, exactly like a real kubelet-killed pod before GC)
        live_rider = op.store.get(Pod, "rider", rider.namespace)
        assert live_rider is None or live_rider.spec.node_name == node.name

    def test_pod_tolerating_only_unschedulable_is_evicted(self, op):
        """suite_test.go:255-282."""
        pod, node = _provision_one(op)
        tol = make_pod(cpu="100m", name="tol-unsched", tolerations=[
            Toleration(key="node.kubernetes.io/unschedulable",
                       operator="Exists")])
        tol.spec.node_name = node.name
        op.store.create(tol)
        settle(op)
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None
        live = op.store.get(Pod, "tol-unsched", tol.namespace)
        assert live is None or live.spec.node_name != node.name  # evicted

    def test_pods_without_owner_ref_do_not_block(self, op):
        """suite_test.go:283-312."""
        pod, node = _provision_one(op)
        bare = make_pod(cpu="100m", name="bare")  # no ownerRef at all
        bare.spec.node_name = node.name
        op.store.create(bare)
        settle(op)
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None

    def test_terminal_pods_do_not_block(self, op):
        """suite_test.go:313-331."""
        pod, node = _provision_one(op)
        done = make_pod(cpu="100m", name="done")
        done.status.phase = "Succeeded"
        done.spec.node_name = node.name
        op.store.create(done)
        settle(op)
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None

    def test_static_pods_not_evicted(self, op):
        """suite_test.go:487-531: node-owned (static) pods are never
        evicted; the node still terminates."""
        pod, node = _provision_one(op)
        static = make_pod(cpu="100m", name="static")
        static.metadata.owner_refs.append(OwnerReference(kind="Node",
                                                         name=node.name))
        static.spec.node_name = node.name
        op.store.create(static)
        settle(op)
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None

    def test_non_critical_pods_evicted_before_critical(self, op):
        """suite_test.go:450-486: the drain processes one priority group
        per pass — regular pods leave before critical ones."""
        pod, node = _provision_one(op)
        crit = make_pod(cpu="100m", name="crit")
        crit.spec.priority_class_name = "system-cluster-critical"
        crit.spec.node_name = node.name
        op.store.create(crit)
        settle(op)
        from karpenter_tpu.controllers.node_termination import NodeTermination
        term = NodeTermination(op.store, op.cluster, op.clock)
        op.store.delete(node)
        term.reconcile(node)  # FIRST drain pass: regular group only
        live_reg = op.store.get(Pod, pod.name, pod.namespace)
        live_crit = op.store.get(Pod, "crit", crit.namespace)
        assert live_reg is None or live_reg.spec.node_name != node.name
        assert live_crit is not None and live_crit.spec.node_name == node.name
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None


class TestInstanceGone:
    def test_node_without_instance_released_undrained(self, op):
        """suite_test.go:567-601: the cloud instance is gone (spot reclaim)
        — waiting on a dead kubelet's evictions is pointless."""
        pod, node = _provision_one(op)
        blocked = make_pod(cpu="100m", name="blocked")
        blocked.metadata.annotations[
            api_labels.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        blocked.spec.node_name = node.name
        op.store.create(blocked)
        settle(op)
        # mark NotReady (dead kubelet) and rip the instance out of kwok
        from karpenter_tpu.utils.node import set_condition
        node.status.conditions = []
        set_condition(node, "Ready", "False", now=op.clock.now())
        op.store.update(node)
        pid = node.spec.provider_id
        # kwok's "cloud" is the store's Node objects: simulate the instance
        # vanishing by making get() raise for this provider id
        from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
        real_get = op.cloud_provider.get

        def gone(provider_id):
            if provider_id == pid:
                raise NodeClaimNotFoundError(provider_id)
            return real_get(provider_id)

        op.cloud_provider.get = gone
        _terminate(op, node, rounds=2)
        assert op.store.get(Node, node.name) is None
        # stranded workloads were released: the do-not-disrupt pod is either
        # unbound (awaiting replacement capacity) or already rescheduled
        live = op.store.get(Pod, "blocked", blocked.namespace)
        assert live is None or live.spec.node_name != node.name

    def test_ready_node_still_drains_even_if_instance_lookup_fails(self, op):
        """suite_test.go:602-634: a Ready node's kubelet is heartbeating —
        the instance exists; never shortcut the drain."""
        pod, node = _provision_one(op)
        from karpenter_tpu.utils.node import set_condition
        node.status.conditions = []
        set_condition(node, "Ready", "True", now=op.clock.now())
        op.store.update(node)
        from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError

        def gone(provider_id):
            raise NodeClaimNotFoundError(provider_id)

        op.cloud_provider.get = gone
        _terminate(op, node)
        # normal drain path ran: node gone AND the workload pod was evicted
        assert op.store.get(Node, node.name) is None
        live = op.store.get(Pod, pod.name, pod.namespace)
        assert live is None or live.spec.node_name != node.name


class TestTolerantPodVolumes:
    def test_tolerating_pod_volume_does_not_block_termination(self, op):
        """A disrupted-taint-tolerating pod is never evicted, so its
        VolumeAttachment will never detach — it must not hold the node
        (controller.go:216 IsDrainable filter)."""
        pod, node = _provision_one(op)
        rider = make_pod(cpu="100m", name="rider-vol", tolerations=[
            Toleration(key=DISRUPTED_NO_SCHEDULE_TAINT.key,
                       operator="Exists")])
        rider.spec.node_name = node.name
        op.store.create(rider)
        _bind_volume(op, rider, pv_name="pv-rider", claim="pvc-rider",
                     node=node)
        settle(op)
        _terminate(op, node)
        assert op.store.get(Node, node.name) is None
